"""Determinism lint — the replay/placement planes must stay replayable.

Pass 12 (fast, AST-only; rides ``make lint`` and the tier-1 clean gate).
The fleet's whole recovery story is deterministic re-execution: PR 10
re-completes a dead replica's in-flight requests by replaying the
journal byte-identically on survivors, and PR 8's placement contract is
same-summaries-⇒-same-decision. Both collapse silently if a module on
those paths consults ambient nondeterminism. Each rule below names a
class this repo has already paid for once:

``unseeded-rng``
    ``random.Random()`` with no seed, the module-level ``random.*``
    global-state functions, the legacy ``np.random.*`` global RNG, and
    ``np.random.default_rng()`` with no seed. The fault injector and the
    health prober derive per-decision ``random.Random(seed)`` instances
    precisely so chaos runs replay; an unseeded RNG on those paths is a
    replay divergence with no log line.
``builtin-hash``
    builtin ``hash()`` — str/bytes hashing is salted per process
    (PYTHONHASHSEED), so any key, ordering, or routing decision derived
    from it differs across restarts and across replicas. The PR 6 fix
    (``zlib.crc32`` for the fault-injector keys) generalized into a
    rule: use ``zlib.crc32``/``hashlib`` for cross-process-stable keys.
``unordered-iteration``
    a ``for`` loop over a ``set``/``frozenset`` (literal, constructor,
    set comprehension, set algebra, or a name/attribute bound to one)
    whose body feeds an ordered decision — appends/extends an
    accumulator, yields, or selects-first via ``break``/``return``. Set
    iteration order is insertion-and-hash dependent; two replicas
    replaying the same events can pick different victims. Iterate
    ``sorted(s)`` (exempt by construction — ``sorted()`` returns a
    list) or keep an explicitly ordered structure.
``wall-clock-decision``
    direct ``time.time()``/``monotonic()``/``perf_counter()`` calls in
    scoped decision modules. PR 7 introduced the injectable ``Clock``
    seam (``obs.SystemClock``/``VirtualClock``) exactly so schedulers
    and routers read time through a replayable source; a raw clock read
    is a decision input that can never be replayed.

Scope: determinism is a *contract of specific planes*, not the whole
tree — ``DETERMINISM_SCOPE`` below lists the modules whose
nondeterminism is an outage (fleet routing/health/replay, the fault
injector, snapshot/prefix/paging state machines, the scheduler scoring
path, scheduler plugins). Other modules (benches, demos) may use
ambient RNGs freely. A file outside the scope opts in by defining
``GRAFTCHECK_DETERMINISM_LINT`` at top level — the seeded fixture
idiom. Suppression: ``# graftcheck: ignore[rule]`` with a rationale,
per the README policy.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, apply_suppressions, parse_suppressions

# Path suffix/prefix fragments (``/``-separated) naming the load-bearing
# modules. A trailing ``/`` means "the whole subtree".
DETERMINISM_SCOPE: Tuple[str, ...] = (
    "fleet/",
    "plugins/",
    "testing/faults.py",
    "models/snapshot.py",
    "models/prefix_cache.py",
    "models/paging.py",
    "models/proposers.py",
    "sched/scheduler.py",
    "sched/framework.py",
)

# Files outside the scope opt in by assigning this at module top level
# (how the seeded bad_determinism.py fixture gets linted).
OPT_IN_MARKER = "GRAFTCHECK_DETERMINISM_LINT"

# random-module functions that consume the hidden module-global RNG.
_GLOBAL_RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "randbytes", "getrandbits",
    "choice", "choices", "sample", "shuffle", "uniform", "triangular",
    "betavariate", "expovariate", "gammavariate", "gauss",
    "lognormvariate", "normalvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "seed",
})
# numpy.random constructors that ARE seedable — unseeded only when
# called with no arguments. Everything else under np.random.* is the
# legacy module-global RNG and is flagged unconditionally.
_SEEDABLE_NP = frozenset({
    "default_rng", "Generator", "SeedSequence", "RandomState",
    "PCG64", "PCG64DXSM", "Philox", "MT19937", "SFC64",
})
_WALL_CLOCK_FNS = frozenset({
    "time", "monotonic", "perf_counter",
    "time_ns", "monotonic_ns", "perf_counter_ns",
})
# Loop-body calls that feed an ordered accumulator.
_ORDERED_SINKS = frozenset({"append", "extend", "insert", "appendleft"})


def in_determinism_scope(path: str, source: str = "") -> bool:
    """True when ``path`` names a module whose determinism is load-bearing
    (DETERMINISM_SCOPE) or the source opts in via the fixture marker."""
    p = path.replace(os.sep, "/")
    for frag in DETERMINISM_SCOPE:
        if frag.endswith("/"):
            if f"/{frag}" in p or p.startswith(frag):
                return True
        elif p == frag or p.endswith(f"/{frag}"):
            return True
    return OPT_IN_MARKER in source


def _call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee (``a.b.c`` or ``name``), else None."""
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


class _ImportMap:
    """Aliases for the modules/functions the rules care about."""

    def __init__(self, tree: ast.AST) -> None:
        self.random_mods: Set[str] = set()      # import random [as r]
        self.np_mods: Set[str] = set()          # import numpy [as np]
        self.nprandom_mods: Set[str] = set()    # import numpy.random as npr
        self.time_mods: Set[str] = set()        # import time [as t]
        self.random_cls: Set[str] = set()       # from random import Random
        self.random_fns: Set[str] = set()       # from random import choice…
        self.np_seedable: Set[str] = set()      # from numpy.random import default_rng
        self.np_global_fns: Set[str] = set()    # from numpy.random import shuffle
        self.time_fns: Set[str] = set()         # from time import time…
        for n in ast.walk(tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    name = a.asname or a.name
                    if a.name == "random":
                        self.random_mods.add(name)
                    elif a.name == "numpy":
                        self.np_mods.add(name)
                    elif a.name == "numpy.random" and a.asname:
                        self.nprandom_mods.add(name)
                    elif a.name == "time":
                        self.time_mods.add(name)
            elif isinstance(n, ast.ImportFrom) and n.module:
                for a in n.names:
                    name = a.asname or a.name
                    if n.module == "random":
                        if a.name == "Random":
                            self.random_cls.add(name)
                        elif a.name in _GLOBAL_RANDOM_FNS:
                            self.random_fns.add(name)
                    elif n.module == "numpy.random":
                        if a.name in _SEEDABLE_NP:
                            self.np_seedable.add(name)
                        else:
                            self.np_global_fns.add(name)
                    elif n.module == "time":
                        if a.name in _WALL_CLOCK_FNS:
                            self.time_fns.add(name)


def _rng_finding(node: ast.Call, path: str,
                 imports: _ImportMap) -> Optional[Finding]:
    dotted = _call_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    seeded = bool(node.args) or bool(node.keywords)
    # random.Random() / Random() — unseeded instance.
    if (dotted in {f"{m}.Random" for m in imports.random_mods}
            or (not rest and head in imports.random_cls)):
        if not seeded:
            return Finding(
                "unseeded-rng", path, node.lineno,
                "random.Random() with no seed draws from OS entropy — "
                "replay on a survivor diverges. Derive the seed from "
                "stable inputs (the testing/faults.py idiom: "
                "crc32(kind:key) ^ run_seed)")
        return None
    # random.<global fn>() / bare imported global fn — shared hidden state.
    if ((head in imports.random_mods and rest in _GLOBAL_RANDOM_FNS)
            or (not rest and head in imports.random_fns)):
        return Finding(
            "unseeded-rng", path, node.lineno,
            f"module-global random.{rest or head}() shares one hidden "
            f"RNG across every caller and thread — even seeded once, "
            f"interleaving reorders draws. Use a per-component "
            f"random.Random(seed)")
    # numpy.random.* — seedable constructors vs the legacy global RNG.
    np_prefixes = ({f"{m}.random" for m in imports.np_mods}
                   | imports.nprandom_mods)
    np_head, _, np_fn = dotted.rpartition(".")
    if np_head in np_prefixes:
        if np_fn in _SEEDABLE_NP:
            if not seeded:
                return Finding(
                    "unseeded-rng", path, node.lineno,
                    f"np.random.{np_fn}() with no seed pulls OS entropy — "
                    f"pass an explicit seed so the stream replays")
            return None
        return Finding(
            "unseeded-rng", path, node.lineno,
            f"legacy np.random.{np_fn}() uses the module-global "
            f"RandomState — use np.random.default_rng(seed) so the "
            f"stream is per-component and replayable")
    if not rest and head in imports.np_seedable and not seeded:
        return Finding(
            "unseeded-rng", path, node.lineno,
            f"{head}() with no seed pulls OS entropy — pass an explicit "
            f"seed so the stream replays")
    if not rest and head in imports.np_global_fns:
        return Finding(
            "unseeded-rng", path, node.lineno,
            f"legacy numpy.random.{head}() uses the module-global "
            f"RandomState — use np.random.default_rng(seed)")
    return None


def _clock_finding(node: ast.Call, path: str,
                   imports: _ImportMap) -> Optional[Finding]:
    dotted = _call_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    if ((head in imports.time_mods and rest in _WALL_CLOCK_FNS)
            or (not rest and head in imports.time_fns)):
        fn = rest or head
        return Finding(
            "wall-clock-decision", path, node.lineno,
            f"time.{fn}() read directly in a decision module — inject "
            f"the obs Clock seam (SystemClock in production, "
            f"VirtualClock in tests) so staleness/deadline/backoff "
            f"decisions replay; a raw clock read can never be replayed")
    return None


# -- unordered-iteration --------------------------------------------------


def _is_set_expr(node: ast.AST, local_sets: Set[str],
                 attr_sets: Set[str]) -> bool:
    """Conservatively: does ``node`` statically evaluate to a set?
    Literals, ``set()``/``frozenset()`` calls, set comprehensions, names
    and ``self.<attr>`` bound to one of those, and set-algebra BinOps
    over them. ``sorted(s)`` returns a list, so ordering a set at the
    loop header exempts it by construction."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in {"set", "frozenset"}:
            return True
        # s.union(t) / s.difference(t) / … on a known set.
        if (isinstance(fn, ast.Attribute)
                and fn.attr in {"union", "difference", "intersection",
                                "symmetric_difference"}
                and _is_set_expr(fn.value, local_sets, attr_sets)):
            return True
        return False
    if isinstance(node, ast.Name):
        return node.id in local_sets
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr in attr_sets
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return (_is_set_expr(node.left, local_sets, attr_sets)
                or _is_set_expr(node.right, local_sets, attr_sets))
    return False


def _collect_set_bindings(tree: ast.AST) -> Tuple[Dict[ast.AST, Set[str]],
                                                  Set[str]]:
    """Per-function local names statically bound to sets, plus the
    ``self.<attr>`` names any method assigns a set to (class-wide — the
    usual ``self._members = set()`` in __init__ pattern). Flow-
    insensitive on purpose: a name EVER bound to a set is suspect."""
    attr_sets: Set[str] = set()
    fn_locals: Dict[ast.AST, Set[str]] = {}
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        fn_locals[fn] = set()
    # Two passes: attribute bindings first (visible to every method),
    # then locals (which may chain off already-known names).
    for fn in funcs:
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and _is_set_expr(
                    n.value, set(), set()):
                for t in n.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attr_sets.add(t.attr)
    for fn in funcs:
        local = fn_locals[fn]
        for _ in range(2):   # one re-pass resolves a = set(); b = a | c
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and _is_set_expr(
                        n.value, local, attr_sets):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            local.add(t.id)
    return fn_locals, attr_sets


def _ordered_sink(body: List[ast.stmt]) -> Optional[Tuple[int, str]]:
    """(lineno, what) of the first ordered-decision sink in a loop body:
    an ordered-accumulator call (append/extend/insert/appendleft), a
    ``yield``, or first-match selection via ``break``/``return value``."""
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and n.func.attr in _ORDERED_SINKS:
                return n.lineno, f".{n.func.attr}() into an accumulator"
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                return n.lineno, "a yield (caller sees set order)"
            if isinstance(n, ast.Break):
                return n.lineno, "first-match selection via break"
            if isinstance(n, ast.Return) and n.value is not None:
                return n.lineno, "first-match selection via return"
    return None


def _iter_findings(tree: ast.AST, path: str) -> List[Finding]:
    out: List[Finding] = []
    fn_locals, attr_sets = _collect_set_bindings(tree)
    for scope, local_sets in fn_locals.items():
        for n in ast.walk(scope):
            if not isinstance(n, (ast.For, ast.AsyncFor)):
                continue
            if not _is_set_expr(n.iter, local_sets, attr_sets):
                continue
            sink = _ordered_sink(n.body)
            if sink is None:
                continue
            _lineno, what = sink
            out.append(Finding(
                "unordered-iteration", path, n.lineno,
                f"for-loop over a set feeds an ordered decision ({what}) "
                f"— set order is hash/insertion dependent, so two "
                f"replicas replaying the same events diverge. Iterate "
                f"sorted(...) or keep an ordered structure"))
    return out


def lint_determinism_source(path: str, source: str,
                            tree: Optional[ast.AST] = None,
                            force: bool = False) -> List[Finding]:
    """Run the determinism rules over one file. Scope-gated: outside
    DETERMINISM_SCOPE (and without the opt-in marker) this returns []
    unless ``force`` — decision-plane determinism is a contract of
    specific modules, not a tree-wide style rule."""
    if not force and not in_determinism_scope(path, source):
        return []
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return []   # the AST lint reports the syntax error
    imports = _ImportMap(tree)
    findings: List[Finding] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = _rng_finding(n, path, imports) or _clock_finding(
            n, path, imports)
        if f is not None:
            findings.append(f)
        elif (isinstance(n.func, ast.Name) and n.func.id == "hash"
              and (n.args or n.keywords)):
            findings.append(Finding(
                "builtin-hash", path, n.lineno,
                "builtin hash() is salted per process (PYTHONHASHSEED) — "
                "any key/ordering/routing derived from it differs across "
                "restarts and replicas. Use zlib.crc32 (the PR 6 fault-"
                "injector fix) or hashlib for stable keys"))
    findings.extend(_iter_findings(tree, path))
    findings = apply_suppressions(findings, parse_suppressions(source))
    return findings


def run_determinism(paths=None) -> List[Finding]:
    """Standalone entry: walk ``paths`` (default: the installed package)
    and lint every in-scope file. run_fast_passes folds this into its
    single shared-parse file walk instead."""
    from .astlint import iter_python_files

    if paths is None:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings.extend(lint_determinism_source(path, source))
    return findings
