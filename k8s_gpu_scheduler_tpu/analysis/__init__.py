"""graftcheck — static analysis for the jax_graft serving/training stack.

Twelve coordinated passes over the repo (``python -m
k8s_gpu_scheduler_tpu.analysis``; importable APIs below):

1. **AST lint** (``astlint``): jit-hostile patterns (tracer casts, host
   time/numpy/syncs inside traced functions, bare except) and the
   scheduler lock-lint (attributes a ``threading.Lock`` guards, accessed
   outside it).
2. **VMEM budgeter** (``vmem``): static working-set estimates for the
   Pallas kernels against the ~16 MiB/core budget, plus block
   divisibility for every LlamaConfig preset.
3. **jaxpr audit** (``jaxpr_audit`` + ``entrypoints``): traces the jitted
   entry points and flags captured weight constants, f32 upcasts in bf16
   paths, dead outputs, and host transfers in hot loops.
4. **Recompile guard** (``recompile``): jit cache-miss accounting + the
   donation contract (buffers actually consumed), with a pytest fixture
   (tests/conftest.py ``recompile_guard``) asserting steady-state decode
   never retraces.
5. **Shared-page audit** (``alias``): the prefix cache's copy-on-write
   rule — dispatches the real prefill/decode programs against pools with
   declared shared pages and byte-verifies those pages came back
   untouched (an aliased-page write is silent KV cross-contamination).
6. **Retry-lint** (``retrylint``, runs inside the AST pass): unbounded
   ``while True`` retry loops (no attempt bound/deadline on the failure
   path) and blocking sleeps/socket calls made while holding a lock —
   the two anti-patterns utils/retry.py's bounded ``RetryPolicy``
   replaces in the control-plane clients.
7. **Trace-lint** (``tracelint``, runs inside the AST pass): the
   ``trace-in-jit`` rule — obs/ span/tracer/flight-recorder calls inside
   a jit-traced body are host syncs (at best trace-time constants that
   replay a lie); tracing belongs on the host side of the dispatch, and
   this pass keeps it there.
8. **GSPMD sharding audit** (``gspmd``): walks the traced jaxpr of the
   sharded entry points (generate-with-mesh, the paged serving
   shard_map islands) and checks every ``sharding_constraint`` /
   island mapping against the rules table in parallel/sharding.py —
   rank-5 cache constraints must match ``serving.CACHE_SPEC``, island
   pools must map the kv-heads dim to ``tp`` (POOL_SPEC), big scan
   carries outside islands must be constrained somewhere, and nothing
   huge may be annotated fully-replicated. Tracing-only (no
   compilation), so ``make lint`` runs it too (``--fast --gspmd``).

9. **Symbolic traffic audit** (``traffic`` + ``entrypoints``): walks
   each registered serving entry point's jaxpr and costs every
   equation's result bytes symbolically in the pool geometry dims
   (n_pages, S, hit = hb·ps, tb, W = 1+γ, M), then checks the measured
   scaling class against the per-entry TRAFFIC CONTRACT the registry
   declares — rules ``traffic-contract`` (measured class exceeds
   declared, contract missing, island pool-dim not 1/tp),
   ``dense-materialization`` (full-pool or slots×prefix-window
   intermediates — the PR 13 dense prefix gather class; the retained
   gather fallback is the one sanctioned carrier) and
   ``peak-residency`` (donation-aware liveness: pool-scale live-bytes
   high-water vs the declared multiple of the pool — broken donation
   reads as an exact 2× copy). Tracing only; runs in the full CLI.
10. **Lock-order & donated-buffer audit** (``lockorder``, fast): the
   lock-lint's lock→attr map extended into a repo-wide
   lock-acquisition-ORDER graph — ``lock-cycle`` (potential deadlocks,
   incl. re-acquiring a non-reentrant lock), ``use-after-donate``
   (host reads of engine attrs aliasing per-dispatch-donated device
   arrays outside the step path — the pool_metrics scrape-race class)
   and ``torn-snapshot`` (multi-gauge drains split across acquisitions
   of one lock — the PR 7 exporter class). Plus the suppression-policy
   lint ``bare-suppression`` (findings.py, rides the AST pass): a
   ``# graftcheck: ignore[rule]`` with no rationale is itself a
   finding, and the README suppression catalogue is regenerated from
   the tree (``--suppressions``).
11. **Wire-format schema audit** (``wirecompat``): builds every wire
   artifact — ``ServingSnapshot`` pytree + host meta doc,
   ``ReplicaSummary`` JSON, the ``RequestJournal`` doc — from a
   registry of audit constructors, extracts the live schema (leaf
   dtypes/ranks, doc keys, per-field decoder-has-a-default probed by
   deletion), and diffs it against the committed goldens under
   ``tests/data/graftcheck/schemas/``. Rules: ``wire-break`` (field
   removed or dtype/rank changed — an old artifact stops loading),
   ``wire-no-default`` (new field whose decoder has no default — a
   NEW decoder rejects OLD artifacts), ``wire-golden-stale`` (any
   other drift; regenerate with ``--update-schemas`` after review).
   Runs in the full CLI; CI asserts the clean tree AND that
   ``--update-schemas`` is a git no-op.
12. **Determinism lint** (``determinism``, fast): over the modules
   whose determinism is load-bearing (fleet routing/health/replay,
   the fault injector, snapshot/prefix/paging, the scheduler scoring
   path) — ``unseeded-rng`` (entropy-seeded or module-global RNGs),
   ``builtin-hash`` (PYTHONHASHSEED-dependent keys; the PR 6 crc32
   fix as a rule), ``unordered-iteration`` (set iteration feeding an
   ordered decision), ``wall-clock-decision`` (raw ``time.*`` reads
   where the injectable Clock seam is the contract). Rides ``make
   lint`` and the tier-1 clean gate.

Suppression: ``# graftcheck: ignore[rule]`` on the offending line, with a
rationale in the surrounding comment (policy in README; enforced by
``bare-suppression``).

The AST + VMEM passes are import-light and fast — ``make lint`` and the
tier-1 gate (tests/test_graftcheck_clean.py) run only those; the traced
passes add a few seconds and run in the full CLI and their own tests.
"""
from .findings import (
    ALL_RULES, Finding, Report, lint_suppressions, parse_suppressions,
    suppression_catalogue,
)
from .alias import audit_shared_pages, check_shared_pages
from .astlint import lint_source, run_astlint
from .lockorder import lint_lockorder_source, run_lockorder
from .traffic import (
    TrafficContract, audit_traffic_callable, audit_traffic_jaxpr,
)
from .determinism import (
    DETERMINISM_SCOPE, in_determinism_scope, lint_determinism_source,
    run_determinism,
)
from .retrylint import lint_retry
from .tracelint import lint_trace_calls
from .wirecompat import (
    WIRE_ARTIFACTS, default_schema_dir, diff_schemas, extract_schemas,
    load_golden, write_goldens,
)
from .vmem import (
    VMEM_BYTES_PER_CORE, audit_vmem, decode_attention_footprint,
    flash_attention_footprint, paged_decode_attention_footprint,
    paged_prefill_attention_footprint, paged_verify_attention_footprint,
)

__all__ = [
    "ALL_RULES",
    "Finding",
    "Report",
    "parse_suppressions",
    "lint_source",
    "lint_retry",
    "lint_trace_calls",
    "run_astlint",
    "VMEM_BYTES_PER_CORE",
    "audit_vmem",
    "decode_attention_footprint",
    "flash_attention_footprint",
    "paged_decode_attention_footprint",
    "paged_prefill_attention_footprint",
    "paged_verify_attention_footprint",
    "audit_shared_pages",
    "check_shared_pages",
    "lint_lockorder_source",
    "run_lockorder",
    "lint_suppressions",
    "suppression_catalogue",
    "TrafficContract",
    "audit_traffic_callable",
    "audit_traffic_jaxpr",
    "DETERMINISM_SCOPE",
    "in_determinism_scope",
    "lint_determinism_source",
    "run_determinism",
    "diff_schemas",
    "extract_schemas",
    "run_fast_passes",
    "run_gspmd_pass",
    "run_traced_passes",
    "run_traffic_pass",
    "run_wirecompat_pass",
]


def run_fast_passes(paths=None) -> Report:
    """AST lint + lock-order + determinism lint + VMEM budgeter — no
    tracing, suitable for collection-time gating. ``paths`` defaults to
    the installed package directory. Files defining
    ``GRAFTCHECK_VMEM_AUDIT`` (a list of ``(name, footprint)`` pairs)
    get their declared kernel footprints budget-checked too."""
    import os
    import time

    report = Report()
    if paths is None:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    # One file walk, one read, ONE ast.parse per file shared between the
    # AST lint and the lock-order pass (parsing dominates both; the
    # standalone run_astlint/run_lockorder APIs still parse themselves).
    import ast as _ast

    from .astlint import iter_python_files

    t0 = time.perf_counter()
    lock_s = 0.0
    det_s = 0.0
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = _ast.parse(source, filename=path)
        except SyntaxError:
            tree = None     # lint_source re-parses and reports the error
        report.extend(lint_source(path, source, tree=tree))
        if tree is not None:
            t1 = time.perf_counter()
            report.extend(lint_lockorder_source(path, source, tree=tree))
            lock_s += time.perf_counter() - t1
            t1 = time.perf_counter()
            report.extend(lint_determinism_source(path, source, tree=tree))
            det_s += time.perf_counter() - t1
    report.pass_seconds["astlint"] = (time.perf_counter() - t0
                                      - lock_s - det_s)
    report.pass_seconds["lockorder"] = lock_s
    report.pass_seconds["determinism"] = det_s
    t0 = time.perf_counter()
    report.extend(audit_vmem())
    for src, _attr, entries in _discover_hooks(
            paths, ("GRAFTCHECK_VMEM_AUDIT",)):
        for entry in _safe_entries(report, src, "GRAFTCHECK_VMEM_AUDIT",
                                   entries, arity=2):
            name, fp = entry
            report.extend(fp.check(anchor=src))
    report.pass_seconds["vmem"] = time.perf_counter() - t0
    return report


def _safe_entries(report: Report, src: str, attr: str, entries,
                  arity: int):
    """Yield well-formed hook entries; malformed ones (wrong arity, not a
    tuple) and import failures become findings instead of crashing the
    run — a broken hook must surface, not take the lint down with it."""
    if isinstance(entries, Exception):
        report.extend([Finding("hook-error", src, 0,
                               f"{attr}: {type(entries).__name__}: "
                               f"{entries}")])
        return
    for i, entry in enumerate(entries):
        if not isinstance(entry, (tuple, list)) or len(entry) != arity:
            report.extend([Finding(
                "hook-error", src, 0,
                f"{attr}[{i}]: expected a {arity}-tuple, got "
                f"{type(entry).__name__}")])
            continue
        yield entry


def run_traced_passes(paths=None) -> Report:
    """jaxpr audit + recompile/donation guard + shared-page (alias)
    audit over the entry-point registry, plus any
    ``GRAFTCHECK_JAXPR_AUDIT`` / ``GRAFTCHECK_RECOMPILE_AUDIT`` /
    ``GRAFTCHECK_ALIAS_AUDIT`` hooks found in ``paths`` (how a seeded
    bad-fixture file, if it lands in the tree, gets caught)."""
    import time

    from . import entrypoints as eps
    from .alias import audit_shared_pages
    from .jaxpr_audit import audit_callable
    from .recompile import audit_steady_state

    report = Report()
    hooks = list(_discover_hooks(
        paths, ("GRAFTCHECK_JAXPR_AUDIT", "GRAFTCHECK_RECOMPILE_AUDIT",
                "GRAFTCHECK_ALIAS_AUDIT")))

    t0 = time.perf_counter()
    for name, fn, args in eps.jaxpr_entrypoints():
        report.extend(audit_callable(fn, args, name))
    for src, attr, entries in hooks:
        if attr != "GRAFTCHECK_JAXPR_AUDIT":
            continue
        for entry in _safe_entries(report, src, attr, entries, arity=3):
            name, fn, args = entry
            report.extend(audit_callable(fn, args, name))
    report.pass_seconds["jaxpr"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    for name, build in eps.recompile_scenarios():
        report.extend(audit_steady_state(build, name))
    for src, attr, entries in hooks:
        if attr != "GRAFTCHECK_RECOMPILE_AUDIT":
            continue
        for entry in _safe_entries(report, src, attr, entries, arity=2):
            name, build = entry
            report.extend(audit_steady_state(build, name))
    report.extend(eps.donation_audit())
    report.pass_seconds["recompile"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    for name, build in eps.alias_scenarios():
        report.extend(audit_shared_pages(build, name))
    for src, attr, entries in hooks:
        if attr != "GRAFTCHECK_ALIAS_AUDIT":
            continue
        for entry in _safe_entries(report, src, attr, entries, arity=2):
            name, build = entry
            report.extend(audit_shared_pages(build, name))
    report.pass_seconds["alias"] = time.perf_counter() - t0

    gspmd = run_gspmd_pass(paths)
    report.findings.extend(gspmd.findings)
    report.pass_seconds.update(gspmd.pass_seconds)

    traffic = run_traffic_pass(paths)
    report.findings.extend(traffic.findings)
    report.pass_seconds.update(traffic.pass_seconds)

    wire = run_wirecompat_pass(paths)
    report.findings.extend(wire.findings)
    report.pass_seconds.update(wire.pass_seconds)
    return report


def run_wirecompat_pass(paths=None, schema_dir=None,
                        update: bool = False) -> Report:
    """Wire-format schema-compatibility audit (analysis/wirecompat.py)
    over the wire-artifact registry plus any
    ``GRAFTCHECK_WIRECOMPAT_AUDIT`` hooks found in ``paths`` (entries
    are ``(name, live_schema, golden_schema)`` triples; ``live_schema``
    may be a callable). Host-only (numpy, no tracing) but folded into
    the full CLI run like gspmd/traffic; ``update=True`` rewrites the
    goldens instead of diffing (the CLI's ``--update-schemas``)."""
    import time

    from .wirecompat import (
        default_schema_dir, diff_schemas, extract_schemas, load_golden,
        write_goldens,
    )

    report = Report()
    t0 = time.perf_counter()
    if schema_dir is None:
        schema_dir = default_schema_dir()
    live = extract_schemas(report)
    if update:
        write_goldens(live, schema_dir)
    else:
        for name, schema in live.items():
            golden = load_golden(name, schema_dir)
            report.extend(diff_schemas(name, schema, golden,
                                       anchor=f"<wire:{name}>"))
    for src, attr, entries in _discover_hooks(
            paths, ("GRAFTCHECK_WIRECOMPAT_AUDIT",)):
        for entry in _safe_entries(report, src, attr, entries, arity=3):
            name, live_schema, golden_schema = entry
            try:
                if callable(live_schema):
                    live_schema = live_schema()
                report.extend(diff_schemas(name, dict(live_schema),
                                           dict(golden_schema), anchor=src))
            except Exception as e:  # noqa: BLE001 — a broken hook is a finding
                report.extend([Finding(
                    "hook-error", src, 0,
                    f"{attr}: bad schema entry for {name}: "
                    f"{type(e).__name__}: {e}")])
    report.pass_seconds["wirecompat"] = time.perf_counter() - t0
    return report


def run_traffic_pass(paths=None) -> Report:
    """Symbolic HBM-traffic/residency audit (analysis/traffic.py) over
    the serving entry registry plus any ``GRAFTCHECK_TRAFFIC_AUDIT``
    hooks found in ``paths``. Tracing-only — folded into the full traced
    run. Every registered entry must declare a contract in
    ``entrypoints.TRAFFIC_CONTRACTS``; a missing one is itself a
    finding (an unstated complexity class cannot regress)."""
    import time

    from . import entrypoints as eps
    from .traffic import TrafficContract, audit_traffic_callable

    report = Report()
    t0 = time.perf_counter()
    contracts = eps.traffic_contracts()
    for name, build in eps.traffic_entrypoints():
        contract = contracts.get(name)
        if contract is None:
            report.extend([Finding(
                "traffic-contract", f"<traffic:{name}>", 0,
                f"{name}: registered serving entry point declares NO "
                f"traffic contract — add one to "
                f"entrypoints.TRAFFIC_CONTRACTS (decode O(pos), verify "
                f"O(pos+γ), prefill O(hit+tail), …)")])
            continue
        try:
            fn, args = build()
        except Exception as e:  # noqa: BLE001 — a broken builder is a finding
            report.extend([Finding(
                "traffic-trace-error", f"<traffic:{name}>", 0,
                f"could not build {name}: {type(e).__name__}: "
                f"{str(e)[:300]}")])
            continue
        report.extend(audit_traffic_callable(
            fn, args, name, eps.TRAFFIC_GEOMETRY, contract))
    for src, attr, entries in _discover_hooks(
            paths, ("GRAFTCHECK_TRAFFIC_AUDIT",)):
        for entry in _safe_entries(report, src, attr, entries, arity=5):
            name, fn, args, geometry, contract = entry
            if contract is None:
                report.extend([Finding(
                    "traffic-contract", src, 0,
                    f"{name}: hook entry declares no traffic contract")])
                continue
            try:
                contract = (contract if isinstance(contract, TrafficContract)
                            else TrafficContract(**dict(contract)))
            except Exception as e:  # noqa: BLE001 — malformed hook contract
                report.extend([Finding("hook-error", src, 0,
                                       f"{attr}: bad contract for {name}: "
                                       f"{e}")])
                continue
            report.extend(audit_traffic_callable(
                fn, args, name, dict(geometry), contract))
    report.pass_seconds["traffic"] = time.perf_counter() - t0
    return report


def run_gspmd_pass(paths=None) -> Report:
    """GSPMD sharding-annotation audit (analysis/gspmd.py) over the
    sharded entry points plus any ``GRAFTCHECK_GSPMD_AUDIT`` hooks found
    in ``paths``. Tracing-only — cheap enough that ``make lint`` runs it
    next to the fast passes (``--fast --gspmd``); also folded into the
    full traced run."""
    import time

    from . import entrypoints as eps
    from .gspmd import audit_sharded_callable

    report = Report()
    t0 = time.perf_counter()
    for name, fn, args, expect in eps.gspmd_entrypoints():
        report.extend(audit_sharded_callable(fn, args, name, **expect))
    for src, attr, entries in _discover_hooks(
            paths, ("GRAFTCHECK_GSPMD_AUDIT",)):
        for entry in _safe_entries(report, src, attr, entries, arity=4):
            name, fn, args, expect = entry
            report.extend(audit_sharded_callable(
                fn, args, name, **dict(expect)))
    report.pass_seconds["gspmd"] = time.perf_counter() - t0
    return report


def _discover_hooks(paths, attrs: tuple):
    """Find modules under ``paths`` whose top level assigns any of the
    hook ``attrs``, import each such module ONCE, and yield
    ``(path, attr, entries)`` per attr it defines — ``entries`` is the
    registered list, or the Exception if the import failed (a broken hook
    must surface as a finding, not vanish). One tree walk and one
    exec_module per file regardless of how many hook attrs it defines."""
    import ast
    import importlib.util
    import os

    from .astlint import iter_python_files

    if paths is None:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            present = [a for a in attrs if a in src]
            if not present:
                continue
            tree = ast.parse(src)

            def targets_of(n):
                if isinstance(n, ast.Assign):
                    return n.targets
                if isinstance(n, ast.AnnAssign):   # GRAFTCHECK_X: list = …
                    return [n.target]
                return []

            assigned = [a for a in present if any(
                getattr(t, "id", None) == a
                for n in tree.body for t in targets_of(n))]
            if not assigned:
                continue
            spec = importlib.util.spec_from_file_location(
                f"_graftcheck_hook_{abs(hash(path))}", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:  # noqa: BLE001 — a broken hook is a finding
            yield path, attrs[0], e
            continue
        for attr in assigned:
            yield path, attr, list(getattr(mod, attr, []))
