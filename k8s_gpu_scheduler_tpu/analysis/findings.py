"""Finding model + suppression parsing for graftcheck (analysis/).

A Finding is one (rule, file:line, message) triple; every pass returns a
list of them and the CLI renders/exits on the union. Suppression is
line-anchored source comments:

    # graftcheck: ignore[rule-a,rule-b]   — suppress those rules on this line
    # graftcheck: ignore                  — suppress every rule on this line

Suppressions are deliberate, reviewable artifacts: the policy (README
"graftcheck" section) is that each one carries a rationale in the
surrounding comment, so a sanctioned host sync or a GIL-atomic lock-free
read is documented where it happens instead of silently exempted.

This module must stay import-light (no jax): the AST lint and the CLI's
fast path load it before anything heavy.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

_SUPPRESS_RE = re.compile(
    r"#.*?graftcheck:\s*ignore(?P<bracket>\[(?P<rules>[^\]]*)\])?")
_RULE_NAME_RE = re.compile(r"^[a-z0-9_-]+$")

# Sentinel entry meaning "every rule suppressed on this line".
ALL_RULES = "*"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # file path, or a logical anchor like "<jaxpr:generate>"
    line: int          # 1-based; 0 when the finding has no line anchor
    message: str
    severity: str = "error"   # "error" fails the run; "warning" reports only

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


def _iter_comments(source: str):
    """(lineno, col, text) for every REAL comment token — tokenizing (not
    regexing raw lines) so a marker inside a string literal or docstring
    can never register as a suppression. Falls back to nothing on a
    tokenize error (the lint reports the syntax error separately)."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of suppressed rule names (ALL_RULES
    for a bare ``ignore``). A trailing comment covers its own line; a
    comment-ONLY line (nothing but whitespace before the ``#``) covers the
    next line too, for statements too long to carry the comment inline."""
    lines = source.splitlines()
    out: Dict[int, Set[str]] = {}
    for lineno, col, text in _iter_comments(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group("bracket") is None:
            ruleset = {ALL_RULES}            # bare `ignore` — explicit
        else:
            # Bracketed form: only well-formed kebab-case rule names
            # count. A typo (`[HOST-SYNC]`, `[host sync]`) must suppress
            # NOTHING — degrading to suppress-all would make the typo
            # invisible forever.
            ruleset = {r.strip() for r in m.group("rules").split(",")
                       if _RULE_NAME_RE.match(r.strip())}
            if not ruleset:
                continue
        out.setdefault(lineno, set()).update(ruleset)
        before = lines[lineno - 1][:col] if lineno <= len(lines) else ""
        if not before.strip():
            out.setdefault(lineno + 1, set()).update(ruleset)
    return out


def apply_suppressions(findings: Iterable[Finding],
                       suppressions: Dict[int, Set[str]]) -> List[Finding]:
    kept = []
    for f in findings:
        sup = suppressions.get(f.line, ())
        if ALL_RULES in sup or f.rule in sup:
            continue
        kept.append(f)
    return kept


# -- suppression policy lint + catalogue --------------------------------------

# The README policy: every suppression carries a rationale in the same
# comment (or a comment-only line directly above it). "Rationale" = at
# least this many word characters beyond the marker itself — enough to
# rule out a marker with no prose (or one decorated only with
# punctuation) without judging prose quality; a terse-but-real
# "GIL-atomic" passes.
_RATIONALE_MIN_WORD_CHARS = 8
# The marker core alone, for splitting a comment into marker vs prose
# (the outer _SUPPRESS_RE's leading `#.*?` would swallow prose BEFORE
# the marker into the match).
_SUPPRESS_CORE_RE = re.compile(
    r"graftcheck:\s*ignore(\[(?P<rules>[^\]]*)\])?")


def iter_suppression_comments(source: str):
    """(lineno, rules, rationale) for every suppression comment —
    ``rules`` is the suppressed set (ALL_RULES for the bare form),
    ``rationale`` is the comment's remaining prose: the marker comment's
    own text before/after the marker, falling back to a comment-ONLY
    line directly above (the idiom for statements whose trailing comment
    has no room for prose)."""
    comments: Dict[int, tuple] = {}
    for lineno, col, text in _iter_comments(source):
        comments[lineno] = (col, text)
    lines = source.splitlines()

    def prose_of(text: str) -> str:
        m = _SUPPRESS_CORE_RE.search(text)
        rest = (text[:m.start()] + " " + text[m.end():]) if m else text
        rest = rest.replace("#", " ").strip(" -—:\t")
        return " ".join(rest.split())

    for lineno in sorted(comments):
        _col, text = comments[lineno]
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group("bracket") is None:
            rules = {ALL_RULES}
        else:
            rules = {r.strip() for r in m.group("rules").split(",")
                     if _RULE_NAME_RE.match(r.strip())}
            if not rules:
                continue
        rationale = prose_of(text)
        if sum(1 for c in rationale if c.isalnum()) \
                < _RATIONALE_MIN_WORD_CHARS and lineno - 1 in comments:
            above_col, above = comments[lineno - 1]
            above_only = lineno - 2 < len(lines) and not \
                lines[lineno - 2][:above_col].strip()
            if above_only and not _SUPPRESS_RE.search(above):
                rationale = prose_of(above)
        yield lineno, rules, rationale


def lint_suppressions(path: str, source: str) -> List[Finding]:
    """``bare-suppression``: a suppression marker whose comment carries no
    rationale. The policy (README "graftcheck") is that every suppression
    documents WHY where it happens; a bare marker is an exemption nobody
    can review. NOT itself suppressible — a bare marker cannot vouch for
    itself."""
    out: List[Finding] = []
    for lineno, rules, rationale in iter_suppression_comments(source):
        word_chars = sum(1 for c in rationale if c.isalnum())
        if word_chars < _RATIONALE_MIN_WORD_CHARS:
            what = ("all rules" if ALL_RULES in rules
                    else ",".join(sorted(rules)))
            out.append(Finding(
                "bare-suppression", path, lineno,
                f"suppression of [{what}] carries no rationale — say WHY "
                f"in the same comment (policy: README \"graftcheck\"); "
                f"an exemption nobody can review is how sanctioned "
                f"suppressions rot into blanket ones"))
    return out


def suppression_catalogue(paths) -> List[str]:
    """Markdown table rows — one per distinct suppression in ``paths`` —
    for the README catalogue: ``| file | rules | rationale |`` (no line
    numbers, so unrelated edits to a file do not churn the docs; adding,
    removing, or rewording a suppression does). Regenerated from the
    tree (``python -m k8s_gpu_scheduler_tpu.analysis --suppressions``)
    and drift-tested, so the catalogue cannot lag the code."""
    import os

    from .astlint import iter_python_files

    rows: List[str] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        rel = os.path.relpath(path, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))).replace(
                os.sep, "/")
        for _lineno, rules, rationale in iter_suppression_comments(source):
            what = ("*" if ALL_RULES in rules else ", ".join(
                f"`{r}`" for r in sorted(rules)))
            row = f"| `{rel}` | {what} | {rationale or '(none)'} |"
            if row not in rows:
                rows.append(row)
    return sorted(rows)


@dataclass
class Report:
    """Accumulated findings across passes, with per-pass wall time so the
    bench leg can track lint latency."""
    findings: List[Finding] = field(default_factory=list)
    pass_seconds: Dict[str, float] = field(default_factory=dict)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def render(self, header: Optional[str] = None) -> str:
        lines = []
        if header:
            lines.append(header)
        for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.rule)):
            lines.append(f.render())
        n_err = len(self.errors)
        n_warn = len(self.findings) - n_err
        lines.append(f"graftcheck: {n_err} error(s), {n_warn} warning(s)")
        return "\n".join(lines)
