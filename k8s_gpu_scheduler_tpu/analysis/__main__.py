"""graftcheck CLI — ``python -m k8s_gpu_scheduler_tpu.analysis [paths...]``.

Default: all twelve passes (AST lint incl. retry/trace/suppression
lints, lock-order audit, determinism lint, VMEM budgeter, jaxpr audit,
recompile guard, alias audit, GSPMD audit, symbolic traffic audit,
wire-format schema audit) over the package tree plus any extra
``paths``. Exit code 0 iff no error-severity findings; findings print
as ``file:line: [rule] message``.

``--fast`` runs only the AST + lock-order + determinism + VMEM passes
(no jax tracing) — what ``make lint`` and the tier-1 gate use.
``--json`` emits a machine-readable summary line whose ``findings`` key
is the full list (stable schema: rule, path, line, severity, message)
so CI can annotate instead of grepping text. ``--suppressions`` prints
the suppression catalogue (the README block is regenerated from it,
drift-tested). ``--update-schemas`` rewrites the committed wire-format
goldens (tests/data/graftcheck/schemas/) from the live codecs and
exits — the ONLY sanctioned way to move them; CI asserts it is a git
no-op, so schema drift must arrive with its golden in the same commit.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_gpu_scheduler_tpu.analysis",
        description="graftcheck static analysis")
    parser.add_argument("paths", nargs="*",
                        help="extra files/dirs to analyze (the package "
                             "tree is always included)")
    parser.add_argument("--fast", action="store_true",
                        help="AST lint + lock-order + VMEM budgeter only "
                             "(no tracing)")
    parser.add_argument("--gspmd", action="store_true",
                        help="with --fast: add the GSPMD sharding audit "
                             "(tracing-only, no compilation — what "
                             "`make lint` runs); implied by the full run")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON summary line (findings list + "
                             "per-pass timings)")
    parser.add_argument("--suppressions", action="store_true",
                        help="print the suppression catalogue (markdown "
                             "rows — the README block regenerates from "
                             "this) and exit")
    parser.add_argument("--update-schemas", action="store_true",
                        help="regenerate the committed wire-format golden "
                             "schemas (tests/data/graftcheck/schemas/) "
                             "from the live codecs and exit — review the "
                             "diff; CI pins this to a git no-op")
    parser.add_argument("--warnings-as-errors", action="store_true")
    args = parser.parse_args(argv)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg_root] + list(args.paths)

    if args.suppressions:
        from .findings import suppression_catalogue

        for row in suppression_catalogue(paths):
            print(row)
        return 0

    if args.update_schemas:
        from . import run_wirecompat_pass

        report = run_wirecompat_pass(paths, update=True)
        if report.errors:
            print(report.render(header="graftcheck --update-schemas:"),
                  file=sys.stderr)
            return 1
        from .wirecompat import default_schema_dir

        print(f"graftcheck: wire-format goldens rewritten under "
              f"{default_schema_dir()}", file=sys.stderr)
        return 0

    if not args.fast or args.gspmd:
        # The traced passes initialize jax: keep tier-1's hermetic-CPU
        # convention and give the pipeline entry point a multi-device mesh
        # BEFORE the first jax import.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    from . import run_fast_passes, run_gspmd_pass, run_traced_passes

    report = run_fast_passes(paths)
    if not args.fast:
        # The full traced run already folds the gspmd + traffic passes in.
        traced = run_traced_passes(paths)
        report.findings.extend(traced.findings)
        report.pass_seconds.update(traced.pass_seconds)
    elif args.gspmd:
        gspmd = run_gspmd_pass(paths)
        report.findings.extend(gspmd.findings)
        report.pass_seconds.update(gspmd.pass_seconds)

    failing = report.findings if args.warnings_as_errors else report.errors
    if args.json:
        print(json.dumps({
            # Machine-readable findings — the stable schema CI annotates
            # from (one object per finding, most-severe info inline).
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "severity": f.severity, "message": f.message}
                for f in sorted(report.findings,
                                key=lambda f: (f.path, f.line, f.rule))],
            "n_findings": len(report.findings),
            "errors": len(report.errors),
            "pass_seconds": {k: round(v, 3)
                             for k, v in report.pass_seconds.items()},
            "rules": sorted({f.rule for f in report.findings}),
        }))
    if report.findings:
        print(report.render(header="graftcheck findings:"), file=sys.stderr)
    else:
        timing = ", ".join(f"{k} {v * 1000:.0f} ms"
                           for k, v in report.pass_seconds.items())
        print(f"graftcheck: clean ({timing})", file=sys.stderr)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
