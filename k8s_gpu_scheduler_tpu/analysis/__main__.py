"""graftcheck CLI — ``python -m k8s_gpu_scheduler_tpu.analysis [paths...]``.

Default: all four passes (AST lint, VMEM budgeter, jaxpr audit, recompile
guard) over the package tree plus any extra ``paths``. Exit code 0 iff no
error-severity findings; findings print as ``file:line: [rule] message``.

``--fast`` runs only the AST + VMEM passes (no jax tracing) — what
``make lint`` and the tier-1 gate use. ``--json`` emits a machine-
readable summary (the bench leg consumes it).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_gpu_scheduler_tpu.analysis",
        description="graftcheck static analysis")
    parser.add_argument("paths", nargs="*",
                        help="extra files/dirs to analyze (the package "
                             "tree is always included)")
    parser.add_argument("--fast", action="store_true",
                        help="AST lint + VMEM budgeter only (no tracing)")
    parser.add_argument("--gspmd", action="store_true",
                        help="with --fast: add the GSPMD sharding audit "
                             "(tracing-only, no compilation — what "
                             "`make lint` runs); implied by the full run")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON summary line")
    parser.add_argument("--warnings-as-errors", action="store_true")
    args = parser.parse_args(argv)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg_root] + list(args.paths)

    if not args.fast or args.gspmd:
        # The traced passes initialize jax: keep tier-1's hermetic-CPU
        # convention and give the pipeline entry point a multi-device mesh
        # BEFORE the first jax import.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    from . import run_fast_passes, run_gspmd_pass, run_traced_passes

    report = run_fast_passes(paths)
    if not args.fast:
        # The full traced run already folds the gspmd pass in.
        traced = run_traced_passes(paths)
        report.findings.extend(traced.findings)
        report.pass_seconds.update(traced.pass_seconds)
    elif args.gspmd:
        gspmd = run_gspmd_pass(paths)
        report.findings.extend(gspmd.findings)
        report.pass_seconds.update(gspmd.pass_seconds)

    failing = report.findings if args.warnings_as_errors else report.errors
    if args.json:
        print(json.dumps({
            "findings": len(report.findings),
            "errors": len(report.errors),
            "pass_seconds": {k: round(v, 3)
                             for k, v in report.pass_seconds.items()},
            "rules": sorted({f.rule for f in report.findings}),
        }))
    if report.findings:
        print(report.render(header="graftcheck findings:"), file=sys.stderr)
    else:
        timing = ", ".join(f"{k} {v * 1000:.0f} ms"
                           for k, v in report.pass_seconds.items())
        print(f"graftcheck: clean ({timing})", file=sys.stderr)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
