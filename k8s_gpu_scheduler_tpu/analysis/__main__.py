"""graftcheck CLI — ``python -m k8s_gpu_scheduler_tpu.analysis [paths...]``.

Default: all ten passes (AST lint incl. retry/trace/suppression lints,
lock-order audit, VMEM budgeter, jaxpr audit, recompile guard, alias
audit, GSPMD audit, symbolic traffic audit) over the package tree plus
any extra ``paths``. Exit code 0 iff no error-severity findings;
findings print as ``file:line: [rule] message``.

``--fast`` runs only the AST + lock-order + VMEM passes (no jax
tracing) — what ``make lint`` and the tier-1 gate use. ``--json`` emits
a machine-readable summary line whose ``findings`` key is the full list
(stable schema: rule, path, line, severity, message) so CI can annotate
instead of grepping text. ``--suppressions`` prints the suppression
catalogue (the README block is regenerated from it, drift-tested).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_gpu_scheduler_tpu.analysis",
        description="graftcheck static analysis")
    parser.add_argument("paths", nargs="*",
                        help="extra files/dirs to analyze (the package "
                             "tree is always included)")
    parser.add_argument("--fast", action="store_true",
                        help="AST lint + lock-order + VMEM budgeter only "
                             "(no tracing)")
    parser.add_argument("--gspmd", action="store_true",
                        help="with --fast: add the GSPMD sharding audit "
                             "(tracing-only, no compilation — what "
                             "`make lint` runs); implied by the full run")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON summary line (findings list + "
                             "per-pass timings)")
    parser.add_argument("--suppressions", action="store_true",
                        help="print the suppression catalogue (markdown "
                             "rows — the README block regenerates from "
                             "this) and exit")
    parser.add_argument("--warnings-as-errors", action="store_true")
    args = parser.parse_args(argv)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg_root] + list(args.paths)

    if args.suppressions:
        from .findings import suppression_catalogue

        for row in suppression_catalogue(paths):
            print(row)
        return 0

    if not args.fast or args.gspmd:
        # The traced passes initialize jax: keep tier-1's hermetic-CPU
        # convention and give the pipeline entry point a multi-device mesh
        # BEFORE the first jax import.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    from . import run_fast_passes, run_gspmd_pass, run_traced_passes

    report = run_fast_passes(paths)
    if not args.fast:
        # The full traced run already folds the gspmd + traffic passes in.
        traced = run_traced_passes(paths)
        report.findings.extend(traced.findings)
        report.pass_seconds.update(traced.pass_seconds)
    elif args.gspmd:
        gspmd = run_gspmd_pass(paths)
        report.findings.extend(gspmd.findings)
        report.pass_seconds.update(gspmd.pass_seconds)

    failing = report.findings if args.warnings_as_errors else report.errors
    if args.json:
        print(json.dumps({
            # Machine-readable findings — the stable schema CI annotates
            # from (one object per finding, most-severe info inline).
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "severity": f.severity, "message": f.message}
                for f in sorted(report.findings,
                                key=lambda f: (f.path, f.line, f.rule))],
            "n_findings": len(report.findings),
            "errors": len(report.errors),
            "pass_seconds": {k: round(v, 3)
                             for k, v in report.pass_seconds.items()},
            "rules": sorted({f.rule for f in report.findings}),
        }))
    if report.findings:
        print(report.render(header="graftcheck findings:"), file=sys.stderr)
    else:
        timing = ", ".join(f"{k} {v * 1000:.0f} ms"
                           for k, v in report.pass_seconds.items())
        print(f"graftcheck: clean ({timing})", file=sys.stderr)
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
