"""jaxpr audit — trace jitted entry points and inspect the ClosedJaxpr.

Works on whatever ``jax.make_jaxpr`` returns for an entry point (tracing
only — nothing is compiled or executed), so it is cheap enough to run on
every lint invocation. Rules:

- ``captured-const``: a closed-over constant bigger than the threshold.
  Weights captured by value (a lambda closing over params, a
  ``partial``-bound table) are baked into EVERY compiled program: memory
  bloat now, a full retrace+recompile whenever the host rebinds them.
  Entry points must take big arrays as ARGUMENTS.
- ``f32-upcast``: a convert to float32 whose result exceeds the element
  threshold, or a matmul mixing bf16/f16 against f32 (XLA silently
  upcasts the whole contraction to f32 — 2x the FLOPs and bytes of the
  bf16 path). Small f32 islands (norm/softmax stats) are the documented
  numerics convention and stay under the threshold by construction.
- ``dead-output``: an equation with no effects whose outputs nothing
  consumes — compute that survives because make_jaxpr does not DCE, i.e.
  a forgotten intermediate that XLA may or may not remove.
- ``host-transfer``: ``device_put`` / host callbacks inside the traced
  program; ERROR severity inside a ``scan``/``while`` body (a per-
  iteration host round trip in the hot loop), warning at top level.

Thresholds are deliberate: entry points are audited at TOY shapes
(tiny config), so anything that scales with the model is small and only
genuinely suspicious tensors cross the line.
"""
from __future__ import annotations

from typing import Any, Iterable, List, Optional

from .findings import Finding

CONST_BYTES_LIMIT = 1 << 20            # 1 MiB closed-over constant
F32_ELEMS_LIMIT = 1 << 16              # 64k-element f32 intermediate
DEAD_ELEMS_LIMIT = 1 << 12             # dead outputs below this are noise
#   (autodiff litters traces with dead sign/convert scalars; only a dead
#    tensor of real size indicates forgotten compute)

_CALLBACK_PRIMS = {"debug_callback", "pure_callback", "io_callback",
                   "callback"}
_LOOP_PRIMS = {"scan", "while"}
# Primitives that legitimately produce values nothing consumes (effects,
# bookkeeping) or that DCE reasoning should not second-guess.
_DEAD_OK_PRIMS = _CALLBACK_PRIMS | {"custom_jvp_call", "custom_vjp_call"}


def _iter_subjaxprs(params: dict) -> Iterable[tuple]:
    """(jaxpr, is_loop_body) for every sub-jaxpr in an eqn's params."""
    import jax.core as jc

    def jaxpr_of(v: Any):
        if isinstance(v, jc.ClosedJaxpr):
            return v.jaxpr
        if isinstance(v, jc.Jaxpr):
            return v
        return None

    for key, val in params.items():
        vals = val if isinstance(val, (tuple, list)) else [val]
        for v in vals:
            j = jaxpr_of(v)
            if j is not None:
                yield key, j


def audit_jaxpr(closed, name: str,
                const_bytes_limit: int = CONST_BYTES_LIMIT,
                f32_elems_limit: int = F32_ELEMS_LIMIT) -> List[Finding]:
    """Audit one ClosedJaxpr (from ``jax.make_jaxpr(fn)(*args)``)."""
    anchor = f"<jaxpr:{name}>"
    findings: List[Finding] = []

    for i, const in enumerate(getattr(closed, "consts", ()) or ()):
        nbytes = getattr(const, "nbytes", 0)
        if nbytes and nbytes > const_bytes_limit:
            shape = getattr(const, "shape", ())
            dtype = getattr(const, "dtype", "?")
            findings.append(Finding(
                "captured-const", anchor, 0,
                f"{name} closes over a {nbytes / 2**20:.1f} MiB constant "
                f"(shape {tuple(shape)}, {dtype}): weights captured by "
                f"value recompile on rebind and bloat every executable — "
                f"pass it as an argument (const #{i})"))

    def visit(jaxpr, in_loop: bool) -> None:
        used = set()
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                used.add(id(v))
        for v in jaxpr.outvars:
            used.add(id(v))

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "convert_element_type":
                new_dtype = str(eqn.params.get("new_dtype", ""))
                out = eqn.outvars[0].aval
                if new_dtype == "float32" and out.size > f32_elems_limit \
                        and str(eqn.invars[0].aval.dtype) in ("bfloat16",
                                                              "float16"):
                    findings.append(Finding(
                        "f32-upcast", anchor, 0,
                        f"{name}: {out.size}-element "
                        f"{tuple(out.shape)} upcast to f32 from "
                        f"{eqn.invars[0].aval.dtype} — a full-size f32 "
                        f"intermediate in a bf16 path (2x HBM)",
                        severity="error"))
            elif prim == "dot_general":
                dts = {str(v.aval.dtype) for v in eqn.invars
                       if hasattr(v.aval, "dtype")}
                if "float32" in dts and dts & {"bfloat16", "float16"}:
                    findings.append(Finding(
                        "f32-upcast", anchor, 0,
                        f"{name}: dot_general mixes {sorted(dts)} — XLA "
                        f"upcasts the whole contraction to f32; cast the "
                        f"f32 operand down (or keep stats out of matmuls)"))
            elif prim == "device_put" or prim in _CALLBACK_PRIMS:
                what = ("host callback" if prim in _CALLBACK_PRIMS
                        else "device_put")
                findings.append(Finding(
                    "host-transfer", anchor, 0,
                    f"{name}: {what} ({prim}) "
                    + ("inside a scan/while body — a host round trip per "
                       "iteration of the hot loop" if in_loop
                       else "in the traced program"),
                    severity="error" if in_loop else "warning"))

            if not eqn.effects and eqn.outvars \
                    and prim not in _DEAD_OK_PRIMS \
                    and prim not in _LOOP_PRIMS:
                import jax.core as jc

                live = [v for v in eqn.outvars
                        if not isinstance(v, jc.DropVar) and id(v) in used]
                big = max((getattr(v.aval, "size", 0)
                           for v in eqn.outvars), default=0)
                if not live and big > DEAD_ELEMS_LIMIT:
                    # Every output is dropped or unused — the tail of a
                    # dead compute chain (tracing keeps it; XLA usually
                    # DCEs, but the source is still paying trace cost and
                    # hiding intent). Tiny dead scalars (autodiff
                    # byproducts) stay under DEAD_ELEMS_LIMIT.
                    findings.append(Finding(
                        "dead-output", anchor, 0,
                        f"{name}: {prim} output "
                        f"{[str(v.aval) for v in eqn.outvars]} is never "
                        f"consumed — dead compute that make_jaxpr keeps "
                        f"and XLA may not remove",
                        severity="warning"))

            for _key, sub in _iter_subjaxprs(eqn.params):
                visit(sub, in_loop or prim in _LOOP_PRIMS)

    visit(closed.jaxpr, in_loop=False)
    return findings


def audit_callable(fn, args, name: str, **limits) -> List[Finding]:
    """Trace ``fn(*args)`` with make_jaxpr and audit the result. Tracing
    failures become findings instead of crashes, so one broken entry point
    cannot hide the others."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — report, keep auditing
        return [Finding("trace-error", f"<jaxpr:{name}>", 0,
                        f"could not trace {name}: {type(e).__name__}: "
                        f"{str(e)[:300]}")]
    return audit_jaxpr(closed, name, **limits)
