"""The repo's jitted entry points, as auditable scenarios.

One registry, two consumers:

- ``jaxpr_entrypoints()`` → (name, fn, args) triples for the jaxpr audit
  (tracing only — toy shapes, no compilation);
- ``recompile_scenarios()`` → (name, build) pairs for the steady-state
  retrace + donation audit (compiles at toy shapes, dispatches a few
  times).

Everything is built at ``LlamaConfig.tiny`` scale: the properties being
audited (captured constants, upcasts, host transfers, retraces, donation)
are shape-independent, and toy shapes keep the whole dynamic pass under a
few seconds on CPU.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Tuple

import numpy as np


def _tiny():
    import jax

    from ..models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def jaxpr_entrypoints() -> List[Tuple[str, Callable, tuple]]:
    """(name, fn, example_args) for every traced entry point."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import serving
    from ..models.llama import make_train_step
    from ..ops.decode_attention import (
        dense_decode_reference, flash_decode_attention,
    )

    cfg, params = _tiny()
    prompt = jnp.zeros((2, 8), jnp.int32)
    entries: List[Tuple[str, Callable, tuple]] = [
        ("llama_generate",
         partial(serving.generate, cfg=cfg, max_new=4, max_len=32),
         (params, prompt)),
    ]

    opt = optax.adamw(1e-3)
    state = jax.eval_shape(opt.init, params)          # structure only
    state = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), state)
    batch = {"tokens": prompt, "targets": prompt}
    entries.append(("llama_train_step", make_train_step(cfg, None, opt),
                    (params, state, batch)))

    # ContinuousBatcher dispatches (int8-KV mode exercises every operand).
    eng = serving.ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                    chunk=2, prefill_bucket=4,
                                    kv_dtype="int8")
    slots = np.zeros((2,), np.int32)
    curs = np.full((2,), 4, np.int32)
    tokens = np.zeros((2, 4), np.int32)
    lens = np.full((2,), 4, np.int32)
    entries.append((
        "batcher_prefill", eng._prefill,
        (params, eng._k, eng._v, eng._ks, eng._vs, eng._bitmap,
         eng._rope_pos, eng._last, slots, curs, tokens, lens, np.int32(1))))
    entries.append((
        "batcher_decode", eng._decode,
        (params, eng._k, eng._v, eng._ks, eng._vs, eng._bitmap,
         np.int32(4), eng._rope_pos, eng._last,
         np.asarray([True, False]), np.int32(2))))

    # Paged ContinuousBatcher dispatches (the block-table/page-pool
    # layout; fused decode so the table-indirected kernel traces too).
    import dataclasses

    peng = serving.ContinuousBatcher(
        params, dataclasses.replace(cfg, decode_attn="fused"), n_slots=2,
        max_len=32, chunk=2, prefill_bucket=4, kv_dtype="int8",
        kv_layout="paged", page_size=8)
    pids = np.ones((2, 1), np.int32)                 # one 8-row page each
    tokens8 = np.zeros((2, 8), np.int32)             # tb page-rounded to 8
    no_ptbl = np.zeros((2, 0), np.int32)             # hb=0: plain prefill
    no_hits = np.zeros((2,), np.int32)
    entries.append((
        "batcher_prefill_paged", peng._prefill,
        (params, peng._k, peng._v, peng._ks, peng._vs, peng._lens,
         peng._last, slots, pids, no_ptbl, no_hits, tokens8, lens,
         np.int32(1))))
    # Tail prefill with a prefix-cache hit (hb=1): the first 8 logical
    # rows ride a shared page, only the tail prefills — the program the
    # prefix cache's admission dispatches.
    entries.append((
        "batcher_prefill_paged_prefix", peng._prefill,
        (params, peng._k, peng._v, peng._ks, peng._vs, peng._lens,
         peng._last, slots, pids, np.full((2, 1), 2, np.int32),
         np.full((2,), 8, np.int32), tokens8, lens, np.int32(1))))
    entries.append((
        "batcher_decode_paged", peng._decode,
        (params, peng._k, peng._v, peng._ks, peng._vs,
         peng._table_np.copy(), peng._lens, peng._last,
         np.asarray([True, False]), np.int32(2))))

    # Speculative verify dispatch (the batched 1+gamma window program the
    # spec batcher runs instead of the decode chunk — fused multi-query
    # kernel + int8 pool, every operand class exercised).
    seng = serving.ContinuousBatcher(
        params, dataclasses.replace(cfg, decode_attn="fused"), n_slots=2,
        max_len=32, chunk=2, prefill_bucket=4, kv_dtype="int8",
        kv_layout="paged", page_size=8, speculative=True, gamma=2)
    entries.append((
        "batcher_verify_paged_spec", seng._decode,
        (params, seng._k, seng._v, seng._ks, seng._vs,
         seng._table_np.copy(), seng._lens, seng._last,
         np.zeros((2, 2), np.int32), np.asarray([True, False]),
         np.int32(1), np.full((2,), 2, np.int32))))

    # Pipeline train step (pp >= 2 needs >= 2 local devices; conftest/CLI
    # request an 8-device CPU mesh before jax initializes).
    if len(jax.devices()) >= 2:
        from jax.sharding import Mesh

        from ..models.pipeline import pp_loss_fn

        mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
        entries.append((
            "pipeline_loss_grad",
            jax.value_and_grad(partial(pp_loss_fn, cfg=cfg, mesh=mesh,
                                       microbatches=2)),
            (params, {"tokens": jnp.zeros((4, 8), jnp.int32),
                      "targets": jnp.zeros((4, 8), jnp.int32)})))

    # Decode attention, fused and dense (interpret mode traces the kernel).
    q = jnp.zeros((2, 8, 8), jnp.bfloat16)
    kc = jnp.zeros((2, 64, 8, 8), jnp.bfloat16)
    lengths = jnp.full((2,), 17, jnp.int32)
    entries.append(("flash_decode_attention",
                    partial(flash_decode_attention, interpret=True),
                    (q, kc, kc, lengths)))
    entries.append(("dense_decode_reference",
                    lambda q, k, v, n: dense_decode_reference(
                        q, k, v, lengths=n),
                    (q, kc, kc, lengths)))

    # Paged decode attention: same contract through a page pool + block
    # table (the table is a scalar-prefetch operand of the kernel).
    from ..ops.decode_attention import paged_decode_attention

    pool = jnp.zeros((17, 8, 8, 8), jnp.bfloat16)    # 16 pages + null
    table = jnp.tile(jnp.arange(1, 9, dtype=jnp.int32)[None], (2, 1))
    entries.append(("paged_decode_attention",
                    partial(paged_decode_attention, interpret=True),
                    (q, pool, pool, table, lengths)))

    # Multi-query verify window over the same pool (t = 3 rows per slot,
    # per-row causal bound — the speculative verify kernel).
    from ..ops.decode_attention import paged_verify_attention

    qv = jnp.zeros((2, 3, 8, 8), jnp.bfloat16)
    entries.append(("paged_verify_attention",
                    partial(paged_verify_attention, interpret=True),
                    (qv, pool, pool, table, jnp.full((2,), 9, jnp.int32))))

    # Prefix-attention prefill over the same pool (tb = 16 tail rows per
    # slot, two-regime mask: cached prefix pages through the table, the
    # tail's own dense K/V causal — the hb>0 tail-prefill kernel).
    from ..ops.decode_attention import paged_prefill_attention

    qp = jnp.zeros((2, 16, 8, 8), jnp.bfloat16)
    tailkv = jnp.zeros((2, 16, 8, 8), jnp.bfloat16)
    entries.append(("paged_prefill_attention",
                    partial(paged_prefill_attention, interpret=True),
                    (qp, pool, pool, table[:, :2],
                     jnp.full((2,), 16, jnp.int32), tailkv, tailkv)))
    return entries


# -- symbolic traffic-contract entry points (pass 9) --------------------------

# The audit engines' geometry, one shared table: every SCALE-bearing dim
# (what the traffic contracts police) has a value distinct from every
# other dim in play — INCLUDING the tp-sliced widths the weight-sharded
# islands introduce (d/tp = 48, d_ff/tp = 80, which is why the audit
# config is d_model=96/d_ff=160 rather than tiny's 64/128: tiny's
# sliced q width 64/2 = 32 collides with the `hit` symbol and every
# local projection would read as hit-scaled) — so a concrete shape
# resolves to one monomial. Structural dims (heads, head_dim, page
# size…) may collide — they are vocabulary, never policed. Order is
# resolution priority. `d` and `d_ff` double as the FULL-weight dims
# the replicated-weight island check (analysis/traffic.py
# weight_sharded contracts) matches [L, K, N] island invars against.
TRAFFIC_GEOMETRY: Dict[str, int] = {
    "n_pages": 23,     # pool pages (explicit, not the 1+M·n_blocks default)
    "S": 56,           # max_len (the contiguous window / O(pos) bound)
    "hit": 32,         # hb·ps prefix-hit window (hb=4 rung)
    "tb": 16,          # tail bucket
    "W": 5,            # 1+gamma verify window (gamma=4)
    "M": 3,            # slots
    "L": 2, "vocab": 256, "d_ff": 160, "d": 96,
    "Hkv": 8, "hd": 12, "ps": 8,
}


def _traffic_cfg():
    """The traffic-audit model config — tiny-scale but with d_model/d_ff
    chosen so every dim in play (full AND tp-sliced) resolves to one
    geometry symbol (see TRAFFIC_GEOMETRY's comment)."""
    from ..models.llama import LlamaConfig

    return LlamaConfig(vocab=256, d_model=96, n_layers=2, n_heads=8,
                       n_kv_heads=8, d_ff=160, max_seq=128, remat=False)


def traffic_contracts() -> Dict[str, "object"]:
    from .traffic import TrafficContract

    return {
        # Decode chunk: O(pos) — pos ≤ S; pool + scales + table donated.
        "traffic_decode_chunk": TrafficContract(
            kv_scale={"S": 1}, donated=(1, 2, 3, 4, 5)),
        # Speculative verify window: O(pos + γ) — the 1+γ window may
        # attend itself (W²) on the dense reference path.
        "traffic_verify_window": TrafficContract(
            kv_scale={"S": 1, "W": 2}, donated=(1, 2, 3, 4, 5)),
        # Sampling verify branch (temperature > 0): rejection sampling
        # replaces the exact-match cumprod but stays in the SAME traffic
        # class — per-position softmax/uniform/categorical are all
        # O(W·vocab) with no new pool-scale intermediates, and the
        # pool/scales/table donation chain is unchanged.
        "traffic_verify_window_sampled": TrafficContract(
            kv_scale={"S": 1, "W": 2}, donated=(1, 2, 3, 4, 5)),
        # Plain prefill rung (hb=0): the tail attends itself causally —
        # tb² scores — and nothing else.
        "traffic_prefill_tb16_hb0": TrafficContract(
            kv_scale={"tb": 2}, donated=(1, 2, 3, 4)),
        # Prefix-tail rung, Pallas kernel: O(hit+tail) traffic with ZERO
        # dense prefix intermediates — hit appears in no monomial (the
        # kernel streams pages through the table indirection).
        "traffic_prefill_tb16_hb4_kernel": TrafficContract(
            kv_scale={"tb": 2}, donated=(1, 2, 3, 4)),
        # Prefix-tail rung, retained gather fallback: the SANCTIONED
        # dense materialization (parity reference + plan-rejected-rung
        # fallback, counted at runtime via
        # tpu_serve_decode_fallback_total{reason="no_prefill_plan"}).
        "traffic_prefill_tb16_hb4_gather": TrafficContract(
            kv_scale={"tb": 2, "hit": 1}, dense_ok=True,
            rationale="retained dense-gather fallback: the numerical "
                      "parity reference, and the runtime fallback for "
                      "plan-rejected rungs — counted, never silent",
            donated=(1, 2, 3, 4)),
        # tp-island variants: same classes, plus the 1/tp pool-dim check
        # (rank-5 pool values inside the island carry Hkv/tp) and — for
        # weight_sharded entries — the replicated-weight check: every
        # [L, K, N] weight INVAR of the island must carry a sliced dim;
        # a full (d, d)/(d, ffn)/(ffn, d) weight operand is the
        # replicated layout this PR retires, flagged as a
        # traffic-contract finding. One row per sharded-weight dispatch
        # class: decode (both combines), verify, and every prefill rung
        # family member (hb0 / hb4-kernel / hb4-gather).
        "traffic_decode_chunk_tp2": TrafficContract(
            kv_scale={"S": 1}, donated=(1, 2, 3, 4, 5), tp=2,
            weight_sharded=True),
        "traffic_decode_chunk_tp2_psum": TrafficContract(
            kv_scale={"S": 1}, donated=(1, 2, 3, 4, 5), tp=2,
            weight_sharded=True),
        "traffic_verify_window_tp2": TrafficContract(
            kv_scale={"S": 1, "W": 2}, donated=(1, 2, 3, 4, 5), tp=2,
            weight_sharded=True),
        "traffic_prefill_tb16_hb0_tp2": TrafficContract(
            kv_scale={"tb": 2}, donated=(1, 2, 3, 4), tp=2,
            weight_sharded=True),
        "traffic_prefill_tb16_hb4_kernel_tp2": TrafficContract(
            kv_scale={"tb": 2}, donated=(1, 2, 3, 4), tp=2,
            weight_sharded=True),
        "traffic_prefill_tb16_hb4_gather_tp2": TrafficContract(
            kv_scale={"tb": 2, "hit": 1}, dense_ok=True,
            rationale="retained dense-gather fallback (see the non-tp "
                      "row) — the island edition carries the same "
                      "sanction",
            donated=(1, 2, 3, 4), tp=2, weight_sharded=True),
        # KV-tier promotion upload (serving.scatter_pool_pages — the ONE
        # page-relocation primitive, shared with snapshot restore): the
        # payload is O(promoted pages) (a constant in this geometry —
        # the page count is deliberately NOT a tracked symbol value),
        # and the only pool-scale values are the .at[idx].set update
        # chain itself — no full-pool dequant/transpose intermediates.
        # Pool planes (args 0-3) are donated: each old plane dies at its
        # own scatter, so peak residency stays at one pool working set.
        "traffic_promote_upload": TrafficContract(
            kv_scale={}, donated=(0, 1, 2, 3)),
        # The LEGACY replicated-weight island (weight_sharding=False)
        # keeps a contract row of its own: same traffic classes, NO
        # weight_sharded check — and the tests pin that auditing it
        # UNDER a weight_sharded contract trips the replicated-weight
        # finding (the silent-downgrade class, made loud).
        "traffic_decode_chunk_tp2_replicated": TrafficContract(
            kv_scale={"S": 1}, donated=(1, 2, 3, 4, 5), tp=2),
    }


def _traffic_engine(speculative: bool = False,
                    prefill_attn=None, tp: bool = False,
                    weight_sharding: bool = True,
                    tp_combine: str = "all_gather",
                    temperature: float = 0.0):
    """A paged audit engine at the TRAFFIC_GEOMETRY shapes (fused decode,
    int8 KV — every operand class in play). tp entries default to the
    runtime default — Megatron-sliced weights, all_gather combine —
    with knobs so the psum-combine and legacy replicated-weight islands
    get their own contract rows."""
    import dataclasses

    import jax

    from ..models import serving
    from ..models.llama import init_params

    cfg = dataclasses.replace(_traffic_cfg(), decode_attn="fused")
    params = init_params(cfg, jax.random.PRNGKey(0))
    kw: dict = {}
    if speculative:
        kw.update(speculative=True, gamma=4)
    if temperature:
        kw.update(temperature=temperature, top_k=8)
    if tp:
        kw.update(mesh=_audit_mesh(), weight_sharding=weight_sharding,
                  tp_combine=tp_combine)
    # The legacy replicated-weight engine is built DELIBERATELY here
    # (its contract row is the audit's subject): neither warn nor
    # count — the suppression restores the warn-once/counter state so
    # the first REAL engine still warns and the production metric
    # stays clean of audit throwaways.
    with serving.fallback_notes_suppressed("weights_replicated"):
        return serving.ContinuousBatcher(
            params, cfg, n_slots=3,
            max_len=56, chunk=2, prefill_bucket=16, kv_dtype="int8",
            kv_layout="paged", page_size=8, n_pages=23,
            prefill_attn=prefill_attn, **kw)


# THE single source of the traffic registry: (name, build spec). Both
# traffic_entrypoints() and traffic_entry_names() derive from it, so an
# entry cannot drop out of the audit while its contract (and the
# name-list the tier-1 contract test iterates) silently lives on.
_TRAFFIC_ENTRIES: Tuple[Tuple[str, dict], ...] = (
    ("traffic_decode_chunk", {"kind": "decode"}),
    ("traffic_verify_window", {"kind": "verify"}),
    ("traffic_verify_window_sampled",
     {"kind": "verify", "temperature": 0.6}),
    ("traffic_prefill_tb16_hb0", {"kind": "prefill", "hb": 0}),
    ("traffic_prefill_tb16_hb4_kernel",
     {"kind": "prefill", "hb": 4, "attn": "kernel"}),
    ("traffic_prefill_tb16_hb4_gather",
     {"kind": "prefill", "hb": 4, "attn": "gather"}),
    ("traffic_promote_upload", {"kind": "promote"}),
    ("traffic_decode_chunk_tp2", {"kind": "decode", "tp": True}),
    ("traffic_decode_chunk_tp2_psum",
     {"kind": "decode", "tp": True, "combine": "psum"}),
    ("traffic_decode_chunk_tp2_replicated",
     {"kind": "decode", "tp": True, "ws": False}),
    ("traffic_verify_window_tp2", {"kind": "verify", "tp": True}),
    ("traffic_prefill_tb16_hb0_tp2",
     {"kind": "prefill", "hb": 0, "tp": True}),
    ("traffic_prefill_tb16_hb4_kernel_tp2",
     {"kind": "prefill", "hb": 4, "attn": "kernel", "tp": True}),
    ("traffic_prefill_tb16_hb4_gather_tp2",
     {"kind": "prefill", "hb": 4, "attn": "gather", "tp": True}),
)


def _make_traffic_build(kind: str, hb: int = 0, attn=None,
                        tp: bool = False, ws: bool = True,
                        combine: str = "all_gather",
                        temperature: float = 0.0) -> Callable[[], tuple]:
    def build():
        if kind == "promote":
            # The tier promotion upload: the REAL relocation primitive
            # (serving.scatter_pool_pages), payloads shaped like a
            # 7-page promotion — 7 collides with no geometry symbol
            # value, so the moved-pages dim is a CONSTANT and anything
            # scale-bearing beyond the pool update chain is a finding.
            from ..models import serving

            eng = _traffic_engine()
            P = 7
            idx = np.arange(1, 1 + P, dtype=np.int32)

            def pay(pool):
                shape = tuple(pool.shape)
                return np.zeros((shape[0], P) + shape[2:], np.float32)

            return serving.scatter_pool_pages, (
                eng._k, eng._v, eng._ks, eng._vs, idx,
                pay(eng._k), pay(eng._v), pay(eng._ks), pay(eng._vs))
        if kind == "decode":
            eng = _traffic_engine(tp=tp, weight_sharding=ws,
                                  tp_combine=combine)
            return eng._decode, (
                eng.params, eng._k, eng._v, eng._ks, eng._vs,
                eng._table_np.copy(), eng._lens, eng._last,
                np.asarray([True, True, False]), np.int32(2))
        if kind == "verify":
            eng = _traffic_engine(speculative=True, tp=tp,
                                  weight_sharding=ws, tp_combine=combine,
                                  temperature=temperature)
            return eng._decode, (
                eng.params, eng._k, eng._v, eng._ks, eng._vs,
                eng._table_np.copy(), eng._lens, eng._last,
                np.zeros((3, 4), np.int32),
                np.asarray([True, True, False]),
                np.int32(2), np.full((3,), 4, np.int32))
        eng = _traffic_engine(prefill_attn=attn, tp=tp,
                              weight_sharding=ws, tp_combine=combine)
        slots = np.arange(3, dtype=np.int32)
        pids = np.tile(np.asarray([[5, 6]], np.int32), (3, 1))
        if hb:
            ptbl = np.tile(np.arange(1, 1 + hb, dtype=np.int32)[None],
                           (3, 1))
            hits = np.full((3,), hb * 8, np.int32)
        else:
            ptbl = np.zeros((3, 0), np.int32)
            hits = np.zeros((3,), np.int32)
        return eng._prefill, (
            eng.params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
            eng._last, slots, pids, ptbl, hits,
            np.zeros((3, 16), np.int32), np.full((3,), 16, np.int32),
            np.int32(1))

    return build


def traffic_entrypoints() -> List[Tuple[str, Callable[[], tuple]]]:
    """(name, build) for the symbolic traffic audit (analysis/traffic.py);
    ``build()`` → (fn, args). Contracts live in ``TRAFFIC_CONTRACTS`` —
    a missing contract is itself a finding, and tests/test_analysis.py
    pins that every name in ``_TRAFFIC_ENTRIES`` declares one WITHOUT
    paying engine construction. tp entries drop out only when the host
    cannot trace them (< 2 devices)."""
    import jax

    have_tp = len(jax.devices()) >= 2
    return [(name, _make_traffic_build(**spec))
            for name, spec in _TRAFFIC_ENTRIES
            if have_tp or not spec.get("tp")]


def traffic_entry_names() -> List[str]:
    """The full registry name list WITHOUT building any engine — what the
    tier-1 every-entry-declares-a-contract test iterates (the tp
    variants are listed unconditionally: a contract must exist even
    where the audit host cannot trace them)."""
    return [name for name, _spec in _TRAFFIC_ENTRIES]


# -- GSPMD sharding-audit entry points ----------------------------------------

def _audit_mesh():
    """The forced-host mesh the sharded entries trace under: all five
    axis names present (CACHE_SPEC references dp/fsdp/tp), tp=2 when the
    process has at least two devices (the CLI/conftest force 8), tp=1
    otherwise — the annotations (what this audit reads) are identical
    either way."""
    import jax

    from ..parallel.mesh import MeshSpec, make_mesh

    tp = 2 if len(jax.devices()) >= 2 else 1
    return make_mesh(MeshSpec.for_devices(tp, tp=tp))


def _sharded_tiny_engine(speculative: bool = False,
                         weight_sharding: bool = True,
                         tp_combine: str = "all_gather"):
    """A multi-chip paged engine (shard_map islands over tp) at toy
    scale — the jitted dispatches the gspmd audit traces and the
    recompile/donation scenarios drive. Defaults to the runtime
    default — Megatron-sliced weights, all_gather combine; the legacy
    replicated-weight island (weight_sharding=False) and the psum
    combine get their own scenarios."""
    import dataclasses

    from ..models import serving

    cfg, params = _tiny()
    # Deliberate legacy-layout builds (the audit's subject) neither
    # warn nor count (see _traffic_engine).
    with serving.fallback_notes_suppressed("weights_replicated"):
        return serving.ContinuousBatcher(
            params, dataclasses.replace(cfg, decode_attn="fused"),
            n_slots=2,
            max_len=32, chunk=2, prefill_bucket=8, kv_dtype="int8",
            kv_layout="paged", page_size=8, mesh=_audit_mesh(),
            weight_sharding=weight_sharding, tp_combine=tp_combine,
            speculative=speculative, gamma=2 if speculative else 4)


def gspmd_entrypoints() -> List[Tuple[str, Callable, tuple, dict]]:
    """(name, fn, args, expectations) for the GSPMD sharding audit
    (analysis/gspmd.py): the mesh-constrained static generate path
    (``cache_spec=True`` — its rank-5 cache constraints must match
    CACHE_SPEC), the paged serving islands (``pool_spec=True`` — their
    rank-5 pool operands must map the kv-heads dim to tp;
    ``weight_specs=True`` — their [L, K, N] weight operands must slice
    per the WEIGHT_SPECS table, column on the output axis, row on the
    input axis), and the legacy replicated-weight island
    (weight_sharding=False — pool expectations only, by design). The
    weight expectation needs a REAL tp >= 2 mesh (at tp = 1 the engine
    keeps replicated weights — there is nothing to slice), so it drops
    to pool-only on a single-device host."""
    import jax
    import jax.numpy as jnp

    from ..models import serving

    cfg, params = _tiny()
    mesh = _audit_mesh()
    wspec = {"pool_spec": True,
             **({"weight_specs": True} if len(jax.devices()) >= 2
                else {})}
    prompt = jnp.zeros((2, 8), jnp.int32)
    entries: List[Tuple[str, Callable, tuple, dict]] = [
        ("generate_sharded",
         partial(serving.generate, cfg=cfg, max_new=4, mesh=mesh,
                 max_len=32),
         (params, prompt), {"cache_spec": True}),
    ]

    eng = _sharded_tiny_engine()
    slots = np.zeros((2,), np.int32)
    lens = np.full((2,), 4, np.int32)
    pids = np.ones((2, 1), np.int32)
    tokens8 = np.zeros((2, 8), np.int32)
    entries.append((
        "batcher_decode_paged_tp", eng._decode,
        (eng.params, eng._k, eng._v, eng._ks, eng._vs,
         eng._table_np.copy(), eng._lens, eng._last,
         np.asarray([True, False]), np.int32(2)), dict(wspec)))
    entries.append((
        "batcher_prefill_paged_tp", eng._prefill,
        (eng.params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
         eng._last, slots, pids, np.zeros((2, 0), np.int32),
         np.zeros((2,), np.int32), tokens8, lens, np.int32(1)),
        dict(wspec)))
    # Prefix tail-prefill rung (hb=1) inside the island: the Pallas
    # prefix-attention kernel runs per shard on its local head family
    # with the pool operands mapped per POOL_SPEC — the same
    # expectations as the plain prefill entry.
    entries.append((
        "batcher_prefill_paged_prefix_tp", eng._prefill,
        (eng.params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
         eng._last, slots, pids, np.full((2, 1), 2, np.int32),
         np.full((2,), 8, np.int32), tokens8, lens, np.int32(1)),
        dict(wspec)))
    seng = _sharded_tiny_engine(speculative=True)
    entries.append((
        "batcher_verify_paged_tp", seng._decode,
        (seng.params, seng._k, seng._v, seng._ks, seng._vs,
         seng._table_np.copy(), seng._lens, seng._last,
         np.zeros((2, 2), np.int32), np.asarray([True, False]),
         np.int32(1), np.full((2,), 2, np.int32)),
        dict(wspec)))
    # psum combine: same sliced-weight expectations — the combine only
    # changes the body's collectives, never the operand layout.
    peng = _sharded_tiny_engine(tp_combine="psum")
    entries.append((
        "batcher_decode_paged_tp_psum", peng._decode,
        (peng.params, peng._k, peng._v, peng._ks, peng._vs,
         peng._table_np.copy(), peng._lens, peng._last,
         np.asarray([True, False]), np.int32(2)), dict(wspec)))
    # Legacy replicated-weight island (weight_sharding=False): pool
    # expectations hold, weight expectations deliberately NOT declared
    # — and the tests pin that auditing it WITH weight_specs=True is
    # flagged (the loud version of the old silent layout).
    leng = _sharded_tiny_engine(weight_sharding=False)
    entries.append((
        "batcher_decode_paged_tp_replicated", leng._decode,
        (leng.params, leng._k, leng._v, leng._ks, leng._vs,
         leng._table_np.copy(), leng._lens, leng._last,
         np.asarray([True, False]), np.int32(2)), {"pool_spec": True}))
    return entries


# -- steady-state decode / donation scenarios ---------------------------------

def _batcher_scenario() -> tuple:
    """warmup: one request end-to-end (compiles prefill rung + decode).
    steady: three more waves with DIFFERENT prompt lengths on the same
    bucket rung and different fill bitmaps — by design one compiled
    program serves them all, so the tracked jit caches must not grow."""
    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8")
    rng = np.random.default_rng(0)

    def warmup():
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, plen - 1), max_new=2)
            eng.run()
        return go

    steady = [wave(4), wave(6), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_batcher_scenario() -> tuple:
    """Paged analog of _batcher_scenario: steady-state decode across waves
    whose BLOCK TABLES differ (fresh admissions land on recycled pages in
    a different physical order every wave) must still be one compiled
    program — the table varies in content, never in shape, and the pool +
    table ride the donation chain."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8)
    rng = np.random.default_rng(0)

    def warmup():
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, plen - 1), max_new=2)
            eng.run()
        return go

    steady = [wave(4), wave(6), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_traced_batcher_scenario() -> tuple:
    """Tracing-on edition of the paged scenario: the obs tracer records a
    span around every host-side dispatch, which must be INVISIBLE to the
    compiled programs — same jit keys (spans never enter traced code:
    the trace-in-jit lint enforces the boundary statically, this
    scenario enforces it dynamically), zero retraces across waves, pool
    + table still riding the donation chain."""
    import dataclasses

    from ..models.serving import ContinuousBatcher
    from ..obs import Tracer

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            tracer=Tracer())
    rng = np.random.default_rng(0)

    def warmup():
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, plen - 1), max_new=2)
            eng.run()
        return go

    steady = [wave(4), wave(6), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_prefix_batcher_scenario() -> tuple:
    """Prefix-cache edition of the paged scenario: every steady wave's
    admissions HIT the radix cache (a shared 8-token system prefix the
    warmup donated), so the dispatches are the tail-prefill program with
    a mounted shared page plus decode chunks whose tables mix shared and
    owned pages. By design still one compiled program per rung — hit
    lengths, tables and tail tokens vary in CONTENT only — and the pool
    keeps riding the donation chain."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            prefix_cache=True)
    rng = np.random.default_rng(0)
    sys_prefix = list(rng.integers(0, cfg.vocab, 8))

    def warmup():
        # Miss rung (full prefill), then — after its reap donates the
        # prefix page — the hit rung (tail prefill, hb=1).
        eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab, 5)),
                   max_new=3)
        eng.run()
        eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab, 5)),
                   max_new=3)
        eng.run()

    def wave(suffix: int):
        def go():
            eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab,
                                                      suffix)), max_new=3)
            eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab,
                                                      suffix - 1)),
                       max_new=2)
            eng.run()
        return go

    steady = [wave(4), wave(6), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _prefix_kernel_multiturn_scenario() -> tuple:
    """Multi-turn edition of the prefix scenario, Pallas-kernel prefill:
    every steady wave is a TWO-TURN conversation — turn 1 reaps and
    donates its prompt AND decoded pages into the radix tree
    (donate_decoded), turn 2 re-submits the full transcript plus new
    user text and mounts it as a cached prefix, dispatching the hb>0
    tail-prefill rung whose body is now ops.paged_prefill_attention
    (decode_attn='fused'). By design still one compiled program per
    (tb, hb) rung across waves — hit lengths, prefix tables and tail
    tokens vary in CONTENT only, the donated decoded pages just deepen
    the tree — and the pool keeps riding the donation chain. The
    step()-driven loop flushes per step, so the decoded-suffix donation
    path (host mirror at reap) is actually exercised."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=64, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            prefix_cache=True)
    rng = np.random.default_rng(0)

    def conversation(seed_row: int):
        # Fixed lengths every wave → fixed (tb, hb) rungs: turn-1 prompt
        # 16 tokens (tb=16, hb=0), 12 decoded; turn-2 = transcript + 4
        # new tokens = 32, of which 3 pages mount (tb=8, hb=4 rung).
        p1 = list(rng.integers(0, cfg.vocab, 16))
        eng.submit(p1, max_new=12)
        done = {}
        while eng.pending:
            done.update(eng.step())
        (rid, toks), = done.items()
        eng.submit(p1 + toks + list(rng.integers(0, cfg.vocab, 4)),
                   max_new=4)
        while eng.pending:
            eng.step()

    def warmup():
        conversation(0)

    steady = [lambda i=i: conversation(i) for i in (1, 2, 3)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_tiered_batcher_scenario() -> tuple:
    """KV-tiering edition of the prefix scenario: the pool (10 pages) is
    deliberately too small for the working set, so every steady wave
    runs a full demote→promote cycle — a fresh 28-token miss whose
    admission LRU-evicts cached leaves INTO the host-DRAM tier (the
    step-boundary readback drain), then a re-submission of an earlier
    prompt whose match extends through the demoted nodes and re-uploads
    them ahead of the tail prefill. By design still one compiled program
    per rung: demotion is a host-side device_get (no dispatch at all),
    the promotion upload is the eager scatter_pool_pages relocation
    (audited separately by the traffic registry), and the prefill/decode
    rungs see the same (tb, hb) buckets every wave — page ids, tier keys
    and payload bytes vary in CONTENT only. Pool + table keep riding the
    donation chain throughout."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=64, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8, n_pages=10,
                            prefix_cache=True, kv_tiering=True,
                            dram_pages=32)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab, 28)) for _ in range(7)]

    def turn(p):
        eng.submit(p, max_new=8)
        eng.run()

    def warmup():
        # Three distinct misses overflow the pool (demotions begin at
        # the third admission), then the first prompt returns through
        # the tier: the promote + tail-prefill (hb) rung compiles here.
        for p in prompts[:3]:
            turn(p)
        turn(prompts[0])

    def wave(i: int):
        def go():
            before = eng.pool_metrics()["page_promotions_total"]
            turn(prompts[3 + i])     # fresh miss → demotion pressure
            turn(prompts[1 + i])     # demoted path → promote + hit rung
            # A wave that stopped cycling the tier would make this
            # zero-retrace audit vacuous — fail loudly instead.
            assert eng.pool_metrics()["page_promotions_total"] > before, \
                "tiered wave served no promoted hit"
        return go

    steady = [wave(0), wave(1), wave(2)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_chunked_batcher_scenario() -> tuple:
    """Chunked-prefill edition of the paged scenario: a long prompt's
    budgeted prefill CHUNKS interleave with live decode traffic across
    every steady wave (the Sarathi-Serve schedule). Each chunk is a
    (tb, hb) rung of the same prefill program family — hb grows as the
    slot's own earlier chunks become the resident "hit" — so the whole
    walk compiles once during warmup and steady-state mixed
    prefill+decode must be ZERO retrace with the pool/table riding the
    donation chain. Waves vary prompt lengths (same rungs), budget
    contention (a short prompt waiting behind the long one's chunks)
    and pure-prefill steps (no fully-prefilled slot -> no decode
    dispatch)."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            prefill_chunk_tokens=8)
    rng = np.random.default_rng(0)

    def warmup():
        # The 20-token prompt walks every chunk rung — (8,0), (8,1),
        # (8,2) — while the short prompt exercises budget contention
        # and the single-chunk path; run() covers both block-table jit
        # keys of the decode program.
        eng.submit(rng.integers(0, cfg.vocab, 20), max_new=3)
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, 5), max_new=2)
            eng.run()
        return go

    steady = [wave(20), wave(19), wave(18)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_spec_batcher_scenario() -> tuple:
    """Speculative edition of the paged scenario: steady-state VERIFY
    dispatches across waves whose ACCEPT LENGTHS vary (self-repetitive
    prompts cycle and accept multi-token prefixes; random prompts reject
    everything — 0-accept full rewinds) must still be one compiled
    program: the verify window pads to the fixed 1+gamma, the commit
    length is a traced scalar, and the pool + table keep riding the
    donation chain."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=48, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            speculative=True, gamma=2)
    rng = np.random.default_rng(0)
    phrase = list(rng.integers(0, cfg.vocab, 3))

    def warmup():
        # Covers the prefill rung, the verify program under BOTH block-
        # table jit keys (numpy upload on admission steps, committed
        # device table on pure-verify steps), and a multi-step drain.
        eng.submit(phrase * 2, max_new=4)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(phrase * 2 + phrase[:plen - 6], max_new=3)
            eng.submit(list(rng.integers(0, cfg.vocab, plen - 1)),
                       max_new=2)
            eng.run()
        return go

    steady = [wave(6), wave(7), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _paged_spec_sampled_batcher_scenario() -> tuple:
    """Sampled + adaptive edition of the speculative scenario: steady
    state now varies BOTH the accept lengths (repetitive prompts accept,
    random prompts reject — rejection sampling, not exact match) AND the
    per-slot effective gamma (spec_adaptive — the accept-rate EMA
    shrinks/reopens windows between dispatches). Both ride TRACED
    operands (seed counter, eff vector) against the fixed 1+gamma_max
    padded window, so one compiled verify program must serve every wave
    — an eff- or seed-keyed retrace here would recompile per dispatch in
    steady state. Donation of the pool + table through the sampled
    branch is pinned separately in donation_audit()."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=48, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            speculative=True, gamma=2,
                            spec_adaptive=True,
                            temperature=0.8, top_k=8)
    rng = np.random.default_rng(0)
    phrase = list(rng.integers(0, cfg.vocab, 3))

    def warmup():
        # Covers the prefill rung, the sampled verify program under BOTH
        # block-table jit keys, and a multi-step drain — long enough for
        # the adaptive EMA to move off its fleet seed.
        eng.submit(phrase * 2, max_new=4)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(phrase * 2 + phrase[:plen - 6], max_new=3)
            eng.submit(list(rng.integers(0, cfg.vocab, plen - 1)),
                       max_new=2)
            eng.run()
        return go

    steady = [wave(6), wave(7), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _sharded_paged_batcher_scenario(weight_sharding: bool = False) -> tuple:
    """Multi-chip edition of the paged scenario: steady-state decode on a
    FORCED multi-device host mesh (shard_map islands over tp, pool
    sharded on kv heads) across waves whose block tables differ — the
    zero-retrace + donation contract must survive the island boundary:
    jit keys now include shardings, so this scenario is the guard the
    ROADMAP asked to run \"under a real multi-process mesh\" in its
    CI-reachable form (XLA host-platform device virtualization exercises
    the same GSPMD/shard_map partitioning the TPU path uses).
    ``weight_sharding=True`` is the Megatron-sliced edition
    (batcher_steady_decode_paged_tp_wsharded): the params pytree rides
    the islands SLICED and committed once at engine birth, so steady
    state must additionally prove the sliced-weight placement never
    re-keys the jit cache; False keeps the PR 12 legacy replicated
    island covered."""
    eng = _sharded_tiny_engine(weight_sharding=weight_sharding)
    rng = np.random.default_rng(0)
    cfg = eng.cfg

    def warmup():
        # Two waves: covers the prefill rung, the decode program under
        # BOTH block-table jit keys (numpy upload on admission steps,
        # donated-through device table on pure-decode steps), and the
        # host-built → island-output lens/last committal.
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()
        eng.submit(rng.integers(0, cfg.vocab, 6), max_new=3)
        eng.run()

    def wave(plen: int):
        def go():
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, plen - 1), max_new=2)
            eng.run()
        return go

    steady = [wave(4), wave(6), wave(8)]
    return warmup, steady, {"decode": eng._decode, "prefill": eng._prefill}


def _generate_scenario() -> tuple:
    import jax
    import jax.numpy as jnp

    from ..models.serving import make_server_step

    cfg, params = _tiny()
    handler = make_server_step(cfg, None, max_new=3, max_len=32)
    prompt = jnp.zeros((2, 8), jnp.int32)

    def warmup():
        jax.block_until_ready(handler(params, prompt))  # graftcheck: ignore[host-sync] — warmup barrier in the audit harness itself

    def steady():
        handler(params, prompt)

    return warmup, [steady, steady], {"generate": handler}


def recompile_scenarios() -> List[Tuple[str, Callable[[], tuple]]]:
    return [
        ("batcher_steady_decode", _batcher_scenario),
        ("batcher_steady_decode_paged", _paged_batcher_scenario),
        ("batcher_steady_decode_paged_traced",
         _paged_traced_batcher_scenario),
        ("batcher_steady_decode_paged_prefix", _paged_prefix_batcher_scenario),
        ("batcher_steady_decode_paged_tiered", _paged_tiered_batcher_scenario),
        ("batcher_steady_decode_paged_spec", _paged_spec_batcher_scenario),
        ("batcher_steady_decode_paged_spec_sampled",
         _paged_spec_sampled_batcher_scenario),
        ("batcher_steady_mixed_chunked", _paged_chunked_batcher_scenario),
        ("batcher_steady_decode_paged_tp", _sharded_paged_batcher_scenario),
        ("batcher_steady_decode_paged_tp_wsharded",
         partial(_sharded_paged_batcher_scenario, weight_sharding=True)),
        ("batcher_steady_prefix_kernel", _prefix_kernel_multiturn_scenario),
        ("generate_steady_state", _generate_scenario),
    ]


def donation_audit() -> List:
    """Verify the serving donation contracts actually hold: the batcher's
    decode dispatch (caches + scale planes + bitmap, serving.py
    donate_argnums=(1..5)) and the train step (params + opt state). The
    engines/args are throwaways — donation consumes them."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..models.llama import make_train_step
    from ..models.serving import ContinuousBatcher
    from .recompile import check_donation, check_donation_leaves

    findings = []
    cfg, params = _tiny()
    eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=4, kv_dtype="int8")
    args = (params, eng._k, eng._v, eng._ks, eng._vs, eng._bitmap,
            np.int32(0), eng._rope_pos, eng._last,
            np.asarray([True, True]), np.int32(1))
    findings += check_donation(eng._decode, *args, donated=(1, 2, 3, 4, 5),
                               name="batcher_decode")

    # Paged decode: the page pool, its scale planes AND the block table
    # must all be consumed — the table is donated-through unchanged in
    # steady state, which still has to alias (no silent copy per chunk).
    import jax.numpy as jnp

    peng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                             prefill_bucket=4, kv_dtype="int8",
                             kv_layout="paged", page_size=8)
    pargs = (params, peng._k, peng._v, peng._ks, peng._vs,
             jnp.asarray(peng._table_np), jnp.zeros((2,), jnp.int32),
             jnp.zeros((2,), jnp.int32), np.asarray([True, True]),
             np.int32(1))
    findings += check_donation(peng._decode, *pargs,
                               donated=(1, 2, 3, 4, 5),
                               name="batcher_decode_paged")

    # Speculative verify: the same pool/scales/table donation contract as
    # the decode chunk — the verify dispatch replaces it one-for-one in
    # spec mode, so a copy here would double the pool per verify.
    seng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                             prefill_bucket=4, kv_dtype="int8",
                             kv_layout="paged", page_size=8,
                             speculative=True, gamma=2)
    sargs = (params, seng._k, seng._v, seng._ks, seng._vs,
             jnp.asarray(seng._table_np), jnp.zeros((2,), jnp.int32),
             jnp.zeros((2,), jnp.int32), np.zeros((2, 2), np.int32),
             np.asarray([True, True]), np.int32(1),
             np.full((2,), 2, np.int32))
    findings += check_donation(seng._decode, *sargs,
                               donated=(1, 2, 3, 4, 5),
                               name="batcher_verify_paged_spec")

    # Sampled verify (temperature > 0): the rejection-sampling branch
    # adds seed/eff operands — REPLICATED and never donated — while the
    # pool/scales/table contract must stay exactly (1..5): a donation
    # slip here would double the pool on every sampled verify.
    szeng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                              prefill_bucket=4, kv_dtype="int8",
                              kv_layout="paged", page_size=8,
                              speculative=True, gamma=2,
                              temperature=0.7, top_k=4)
    szargs = (params, szeng._k, szeng._v, szeng._ks, szeng._vs,
              jnp.asarray(szeng._table_np), jnp.zeros((2,), jnp.int32),
              jnp.zeros((2,), jnp.int32), np.zeros((2, 2), np.int32),
              np.asarray([True, True]), np.int32(1),
              np.full((2,), 2, np.int32))
    findings += check_donation(szeng._decode, *szargs,
                               donated=(1, 2, 3, 4, 5),
                               name="batcher_verify_paged_spec_sampled")

    # Tail prefill (prefix-cache hit shape): the pool + scale planes must
    # donate through the hb>0 program too — a copy here would double the
    # pool's HBM on every admission with a hit.
    peng2 = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                              prefill_bucket=4, kv_dtype="int8",
                              kv_layout="paged", page_size=8,
                              prefix_cache=True)
    slots = np.zeros((2,), np.int32)
    pxargs = (params, peng2._k, peng2._v, peng2._ks, peng2._vs,
              peng2._lens, peng2._last, slots, np.ones((2, 1), np.int32),
              np.full((2, 1), 2, np.int32), np.full((2,), 8, np.int32),
              np.zeros((2, 8), np.int32), np.full((2,), 4, np.int32),
              np.int32(1))
    findings += check_donation(peng2._prefill, *pxargs,
                               donated=(1, 2, 3, 4),
                               name="batcher_prefill_paged_prefix")

    # Sharded paged decode (shard_map island over tp): the pool/scale
    # shards and the replicated table must all be consumed through the
    # island boundary — donation now aliases per-chip buffers, and a
    # silent copy would double every chip's pool.
    teng = _sharded_tiny_engine()
    targs = (teng.params, teng._k, teng._v, teng._ks, teng._vs,
             jnp.asarray(teng._table_np), teng._lens, teng._last,
             np.asarray([True, True]), np.int32(1))
    findings += check_donation(teng._decode, *targs,
                               donated=(1, 2, 3, 4, 5),
                               name="batcher_decode_paged_tp")

    # Legacy replicated-weight island: the donation contract must hold
    # on BOTH island layouts (the wsharded default above rides sliced
    # params — NOT donated — next to the donated pool shards; the
    # legacy mode keeps the PR 12 arrangement covered).
    reng = _sharded_tiny_engine(weight_sharding=False)
    rargs = (reng.params, reng._k, reng._v, reng._ks, reng._vs,
             jnp.asarray(reng._table_np), reng._lens, reng._last,
             np.asarray([True, True]), np.int32(1))
    findings += check_donation(reng._decode, *rargs,
                               donated=(1, 2, 3, 4, 5),
                               name="batcher_decode_paged_tp_replicated")

    opt = optax.adamw(1e-3)
    state = jax.jit(opt.init)(params)
    step = make_train_step(cfg, None, opt)
    prompt = jnp.zeros((2, 8), jnp.int32)
    batch = {"tokens": prompt, "targets": prompt}
    # Pytree arguments: donation is per LEAF, so probe the flattened
    # params/opt-state buffers rather than argument positions.
    findings += check_donation_leaves(
        step, (params, state, batch), jax.tree.leaves((params, state)),
        name="llama_train_step")
    return findings


# -- shared-page (copy-on-write) scenarios ------------------------------------

def _prefix_engine(speculative: bool = False):
    """A warmed prefix-cache engine with one donated prefix page and a
    live request MOUNTING it: the state the alias scenarios audit
    against. Returns (engine, shared page ids)."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=32, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            prefix_cache=True, speculative=speculative,
                            gamma=2 if speculative else 4)
    rng = np.random.default_rng(0)
    sys_prefix = list(rng.integers(0, cfg.vocab, 8))
    eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab, 3)), max_new=2)
    eng.run()                        # reap donates the sys-prefix page
    # A live request mounted on the shared page, mid-decode.
    eng.submit(sys_prefix + list(rng.integers(0, cfg.vocab, 4)), max_new=9)
    eng.step()
    shared = sorted({p for pages in eng._slot_shared.values()
                     for p in pages})
    assert shared, "scenario must actually share a page"
    return eng, shared


def _alias_prefill_scenario() -> tuple:
    """The tail-prefill dispatch with a mounted shared prefix page: its
    page-granular scatter must touch only the entry's OWN pages."""
    eng, shared = _prefix_engine()
    own = eng._alloc.alloc(1)        # a throwaway tail page to scatter to
    eng._alloc.retain(shared)        # mirror admission's mount
    rng = np.random.default_rng(1)
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
            eng._last, np.ones((2,), np.int32),
            np.full((2, 1), own[0], np.int32),
            np.asarray([[shared[0]]] * 2, np.int32),
            np.full((2,), 8, np.int32),
            np.asarray([list(rng.integers(0, 256, 8))] * 2, np.int32),
            np.full((2,), 4, np.int32), np.int32(99))
    # _prefill returns (k, v, k_s, v_s, lens, last, firsts).
    return eng._prefill, args, (1, 2, 3, 4), (0, 1, 2, 3), shared


def _prefix_engine_decoded():
    """A warmed prefix-cache engine whose radix tree holds a DECODED-
    suffix page (turn-1 of a conversation reaped with donate_decoded),
    with a live turn-2 request MOUNTING the whole transcript — prompt
    pages AND the decoded page — mid-decode. The state the multi-turn
    alias scenario audits against. Returns (engine, shared page ids)."""
    import dataclasses

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=64, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8,
                            prefix_cache=True)
    rng = np.random.default_rng(0)
    p1 = list(rng.integers(0, cfg.vocab, 16))
    eng.submit(p1, max_new=12)                   # turn 1
    done: dict = {}
    while eng.pending:
        done.update(eng.step())
    (_, toks), = done.items()
    decoded = float(eng.pool_metrics()["decoded_pages_donated_total"])
    assert decoded >= 1, "scenario must actually donate a decoded page"
    # Turn 2 mounts prompt + decoded pages, then decodes on top of them.
    eng.submit(p1 + toks + list(rng.integers(0, cfg.vocab, 4)), max_new=9)
    eng.step()
    shared = sorted({p for pages in eng._slot_shared.values()
                     for p in pages})
    assert len(shared) >= 3, "turn 2 must mount prompt AND decoded pages"
    return eng, shared


def _alias_prefill_kernel_scenario() -> tuple:
    """The Pallas prefix-attention tail-prefill dispatch with a mounted
    shared prefix that INCLUDES a decoded-suffix page: the kernel
    streams those pages read-only through the table indirection and the
    page-granular scatter must touch only the entry's OWN pages — the
    copy-on-write proof for both halves of the multi-turn feature (the
    kernel body and the decoded donation) in one dispatch."""
    from ..models.paging import NULL_PAGE

    eng, shared = _prefix_engine_decoded()
    own = eng._alloc.alloc(1)        # a throwaway tail page to scatter to
    eng._alloc.retain(shared)        # mirror admission's mount
    rng = np.random.default_rng(1)
    hb = 4                           # _hb_bucket(3) — the real turn-2 rung
    prow = [shared[j] if j < len(shared) else NULL_PAGE for j in range(hb)]
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
            eng._last, np.ones((2,), np.int32),
            np.full((2, 1), own[0], np.int32),
            np.asarray([prow] * 2, np.int32),
            np.full((2,), len(shared) * 8, np.int32),
            np.asarray([list(rng.integers(0, 256, 8))] * 2, np.int32),
            np.full((2,), 4, np.int32), np.int32(99))
    # _prefill returns (k, v, k_s, v_s, lens, last, firsts).
    return eng._prefill, args, (1, 2, 3, 4), (0, 1, 2, 3), shared


def _alias_promoted_scenario() -> tuple:
    """A decode chunk over a block table whose mounted prefix pages came
    back through a DRAM demote→promote round trip: build time verifies
    the promoted pages hold exactly the originally-donated bytes (the
    relocation is byte-exact end to end — readback, host tier, re-upload
    into FRESH page ids), and the audit's byte-compare then proves the
    next dispatch leaves them untouched. The copy-on-write contract
    covers tier-promoted pages with no carve-out: they are shared tree
    pages like any other."""
    import dataclasses

    import jax

    from ..models.serving import ContinuousBatcher

    cfg, params = _tiny()
    eng = ContinuousBatcher(params, dataclasses.replace(cfg,
                                                        decode_attn="fused"),
                            n_slots=2, max_len=64, chunk=2,
                            prefill_bucket=8, kv_dtype="int8",
                            kv_layout="paged", page_size=8, n_pages=10,
                            prefix_cache=True, kv_tiering=True,
                            dram_pages=32)
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(0, cfg.vocab, 28)) for _ in range(3)]
    eng.submit(prompts[0], max_new=8)
    eng.run()                        # reap donates prompts[0]'s path
    path0 = eng._prefix.match(prompts[0])
    assert len(path0) >= 2, "scenario must donate a multi-page path"
    idx0 = np.asarray(path0, np.int32)
    # graftcheck: ignore[host-sync] — audit-harness capture of the donated bytes, before any demotion
    before = jax.device_get([eng._k[:, idx0], eng._v[:, idx0],
                             eng._ks[:, idx0], eng._vs[:, idx0]])
    for p in prompts[1:]:            # pool pressure → LRU demotion
        eng.submit(p, max_new=8)
        eng.run()
    assert eng.pool_metrics()["page_demotions_total"] > 0, \
        "scenario must actually demote"
    # Re-admission through the tier: promote + mount, then mid-decode.
    eng.submit(prompts[0], max_new=9)
    eng.step()
    assert eng.pool_metrics()["page_promotions_total"] > 0, \
        "scenario must serve through a promotion"
    path1 = eng._prefix.match(prompts[0])
    assert len(path1) == len(path0), "the full path must survive the tier"
    idx1 = np.asarray(path1, np.int32)
    # graftcheck: ignore[host-sync] — audit-harness byte-compare of the promoted pages against the donated originals
    after = jax.device_get([eng._k[:, idx1], eng._v[:, idx1],
                            eng._ks[:, idx1], eng._vs[:, idx1]])
    for b, a in zip(before, after):
        assert np.array_equal(np.asarray(b), np.asarray(a)), \
            "promoted pages must be byte-identical to the donated bytes"
    shared = sorted({p for pages in eng._slot_shared.values()
                     for p in pages})
    assert set(path1) <= set(shared), "the promoted path must be mounted"
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs,
            eng._table_np.copy(), eng._lens, eng._last,
            np.asarray([s in eng._slot_req for s in range(eng.n_slots)]),
            np.int32(99))
    # _decode returns (k, v, k_s, v_s, table, lens, last, toks).
    return eng._decode, args, (1, 2, 3, 4), (0, 1, 2, 3), shared


def _alias_decode_scenario() -> tuple:
    """A decode chunk over a block table whose prefix rows are shared:
    the per-slot scatter at ``lens`` must land past the mounted prefix,
    never inside it."""
    eng, shared = _prefix_engine()
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs,
            eng._table_np.copy(), eng._lens, eng._last,
            np.asarray([s in eng._slot_req for s in range(eng.n_slots)]),
            np.int32(99))
    # _decode returns (k, v, k_s, v_s, table, lens, last, toks).
    return eng._decode, args, (1, 2, 3, 4), (0, 1, 2, 3), shared


def _alias_verify_scenario() -> tuple:
    """A speculative VERIFY dispatch over a block table whose prefix rows
    are shared: the full 1+gamma window scatters at rows lens..lens+gamma
    — including the up-to-gamma overshoot a rejection will rewind — and
    every one of those rows must land past the mounted prefix. This is
    the teeth behind the rewind contract: a lens clamp can only be a
    correct rewind if the overshoot never touched a page another slot
    (or the tree) can read."""
    eng, shared = _prefix_engine(speculative=True)
    props = np.zeros((2, eng.gamma), np.int32)
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs,
            eng._table_np.copy(), eng._lens, eng._last, props,
            np.asarray([s in eng._slot_req for s in range(eng.n_slots)]),
            np.int32(1), np.full((eng.n_slots,), eng.gamma, np.int32))
    # _decode (spec) returns (k, v, k_s, v_s, table, lens, last, toks,
    # accepts).
    return eng._decode, args, (1, 2, 3, 4), (0, 1, 2, 3), shared


def alias_scenarios() -> List[Tuple[str, Callable[[], tuple]]]:
    """(name, build) pairs for the shared-page audit (analysis/alias.py):
    every real program that runs with aliased prefix pages in its pool."""
    return [
        ("batcher_prefill_paged_prefix", _alias_prefill_scenario),
        ("batcher_prefill_prefix_kernel", _alias_prefill_kernel_scenario),
        ("batcher_decode_paged_prefix", _alias_decode_scenario),
        ("batcher_decode_paged_promoted", _alias_promoted_scenario),
        ("batcher_verify_paged_prefix", _alias_verify_scenario),
    ]
