"""Executable entrypoints (C1 parity — cmd/scheduler/main.go registers the
plugin into kube-scheduler and runs it; ours wires the whole control plane).
Run with ``python -m k8s_gpu_scheduler_tpu.cmd.scheduler``."""
