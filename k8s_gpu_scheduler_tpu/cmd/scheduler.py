"""tpu-scheduler entrypoint — the C1 analogue (cmd/scheduler/main.go:15-28).

The reference's binary is upstream kube-scheduler with one plugin compiled
in; ours owns the whole control plane, so the entrypoint wires every layer:

  config (env) → registry client → recommender client → metrics client →
  reshaper → TPU + Gang plugins → Profile → Scheduler → metrics exporter

Every sidecar is OPTIONAL with graceful degradation (the reference
klog.Fatals when Redis or Prometheus is missing, gpu_plugins.go:852-867 —
SURVEY.md §5 lists that as the failure-handling gap): no registry →
metrics-fallback scoring, no recommender → utilization scoring, no
Prometheus → neutral scores.

``--demo N`` boots the in-memory API server with a demo topology (one v5e
host, one 4-host v5p slice) and N busybox-style pods, so the full binary is
drivable on a laptop: the deploy/ manifests run exactly this module in a
container.
"""
from __future__ import annotations

import argparse
import logging
import signal
import threading

from ..config import SchedulerConfig
from ..metrics.exporter import MetricsServer, Registry
from ..plugins import GangPlugin, PreemptionPlugin, TPUPlugin
from ..sched import Profile, Scheduler, SliceReshaper

log = logging.getLogger("tpu-scheduler")


def build_scheduler(server, config: SchedulerConfig,
                    metrics: Registry | None = None,
                    leader_elect: bool = False,
                    allow_simulated_reshape: bool = False) -> Scheduler:
    """Wire plugins + sidecar clients into a ready-to-start Scheduler."""
    elector = None
    if leader_elect:
        import os
        import socket

        from ..sched import LeaderElector

        elector = LeaderElector(
            server,
            identity=f"{socket.gethostname()}_{os.getpid()}",
            name=config.scheduler_name,
        )
    sched = Scheduler(server, profile=Profile(), config=config,
                      metrics=metrics, elector=elector)

    # Bounded-retry visibility: both control-plane clients count each
    # backoff retry here (utils/retry.py on_retry hook), labeled per
    # client — the flap-rate signal that distinguishes "the registry is
    # restarting" from "scoring went degraded" on one dashboard.
    rpc_retries = sched.metrics.counter(
        "tpu_sched_rpc_retries_total",
        "Bounded control-plane RPC retries, by client")

    registry = None
    try:
        from ..registry.client import Client as RegistryClient

        registry = RegistryClient(
            config.registry.host, config.registry.port,
            password=config.registry.password,
            on_retry=lambda: rpc_retries.inc(client="registry"),
        )
        registry.ping()
        log.info("registry connected at %s:%d",
                 config.registry.host, config.registry.port)
    except Exception as e:  # noqa: BLE001
        registry = None
        log.warning("registry unavailable (%s) — metrics-fallback scoring", e)

    recommender = None
    try:
        from ..recommender.client import Client as RecommenderClient

        recommender = RecommenderClient(
            config.recommender.host, config.recommender.port,
            timeout_s=config.recommender.timeout_s,
            on_retry=lambda: rpc_retries.inc(client="recommender"),
        )
        recommender.impute_configurations("startup-probe")
        log.info("recommender connected at %s:%d",
                 config.recommender.host, config.recommender.port)
    except Exception as e:  # noqa: BLE001
        recommender = None
        log.warning("recommender unavailable (%s) — utilization scoring", e)

    prom = None
    try:
        from ..metrics.client import PromClient

        prom = PromClient(config.metrics.url,
                          timeout_s=config.metrics.query_timeout_s)
    except Exception as e:  # noqa: BLE001
        log.warning("metrics endpoint unavailable (%s)", e)

    # Without a registry, reshape confirmation can only be SIMULATED.
    # Demo mode opts in (taking ~2 s so the applying→idle window shows);
    # in-cluster the reshaper refuses instead — a timer must never stand
    # in for a hardware observation (r3 weak #7).
    reshaper = SliceReshaper(sched.descriptor, registry=registry,
                             auto_confirm_delay_s=0.0 if registry else 2.0,
                             simulate_without_registry=allow_simulated_reshape)
    tpu = TPUPlugin(sched.handle, registry=registry, prom=prom,
                    recommender=recommender, reshaper=reshaper,
                    metrics=sched.metrics)
    gang = GangPlugin(sched.handle)
    preempt = PreemptionPlugin(sched.handle, filter_plugins=[tpu, gang], tpu=tpu)
    sched.profile = Profile(
        pre_filter=[tpu, gang],
        filter=[tpu, gang],
        post_filter=[preempt],
        score=[tpu, gang],
        reserve=[tpu, gang],
        permit=[gang],
        post_bind=[tpu, gang],
    )
    sched._reshaper = reshaper  # stopped alongside the scheduler
    return sched


def demo_cluster(n_pods: int):
    """In-memory cluster: one v5e-8 host + a 4-host v5p-16 slice + pods."""
    from ..api.objects import (
        ConfigMap, ConfigMapRef, Container, LABEL_SLICE_GROUP,
        LABEL_TPU_ACCELERATOR, LABEL_TPU_TOPOLOGY, LABEL_WORKER_INDEX, Node,
        NodeStatus, ObjectMeta, Pod, PodSpec, ResourceRequirements,
        TPU_RESOURCE,
    )
    from ..cluster import APIServer

    server = APIServer()
    server.create(Node(
        metadata=ObjectMeta(name="v5e-0", labels={
            LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            LABEL_TPU_TOPOLOGY: "2x4"}),
        status=NodeStatus(capacity={TPU_RESOURCE: 8},
                          allocatable={TPU_RESOURCE: 8}),
    ))
    for i in range(4):
        server.create(Node(
            metadata=ObjectMeta(name=f"v5p-w{i}", labels={
                LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
                LABEL_TPU_TOPOLOGY: "2x2x4",
                LABEL_SLICE_GROUP: "v5p-pool", LABEL_WORKER_INDEX: str(i)}),
            status=NodeStatus(capacity={TPU_RESOURCE: 4},
                              allocatable={TPU_RESOURCE: 4}),
        ))
    for i in range(n_pods):
        server.create(ConfigMap(metadata=ObjectMeta(name=f"demo-cm-{i}")))
        server.create(Pod(
            metadata=ObjectMeta(name=f"demo-{i}"),
            spec=PodSpec(containers=[Container(
                env_from=[ConfigMapRef(f"demo-cm-{i}")],
                resources=ResourceRequirements(requests={TPU_RESOURCE: 1}),
            )]),
        ))
    return server


def main(argv=None) -> int:
    # Process-level latency tuning (entrypoint, not library: it's an
    # interpreter-wide knob): the default 5 ms GIL switch interval lets one
    # thread hold the interpreter while a 1 ms bind waits — a direct
    # tail-latency tax under churn. kube-scheduler's goroutines preempt
    # far finer.
    import sys as _sys

    _sys.setswitchinterval(0.001)
    parser = argparse.ArgumentParser(prog="tpu-scheduler")
    parser.add_argument("--demo", type=int, metavar="N", default=None,
                        help="boot an in-memory demo cluster with N pods")
    parser.add_argument("--in-cluster", action="store_true",
                        help="schedule against the real kube-apiserver "
                             "(service-account auth)")
    parser.add_argument("--apiserver", default=None, metavar="URL",
                        help="explicit apiserver base URL (implies "
                             "--in-cluster; for dev/kind clusters)")
    parser.add_argument("--metrics-port", type=int, default=10251,
                        help="Prometheus exporter port (0 = disabled)")
    parser.add_argument("--leader-elect", action="store_true",
                        help="acquire a coordination Lease before scheduling "
                             "(run replicas: 2 for HA — parity with "
                             "deploy/scheduler.yaml:10-13 of the reference)")
    parser.add_argument("--once", action="store_true",
                        help="exit after the demo pods are all scheduled")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    if args.demo is None and not (args.in_cluster or args.apiserver):
        parser.error("pick a mode: --demo N (in-memory) or --in-cluster/"
                     "--apiserver URL (real kube-apiserver)")

    if args.demo is not None:
        server = demo_cluster(args.demo)
    else:
        from ..cluster.kubeapi import KubeAPIServer

        server = KubeAPIServer(base_url=args.apiserver)
        log.info("connected to kube-apiserver at %s", server.base_url)
    config = SchedulerConfig.from_env()
    sched = build_scheduler(server, config, leader_elect=args.leader_elect,
                            allow_simulated_reshape=args.demo is not None)

    exporter = None
    if args.metrics_port:
        exporter = MetricsServer(sched.metrics, port=args.metrics_port).start()
        log.info("metrics on :%d/metrics", exporter.port)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    sched.start()
    log.info("tpu-scheduler running (profile: %s)", config.scheduler_name)
    try:
        if args.once:
            import time

            # Monotonic deadline: a 60s WAIT is a duration — the wall
            # clock (NTP steps) must not stretch or collapse it.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not stop.is_set():
                pods = server.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    for p in pods:
                        log.info("scheduled %s -> %s", p.metadata.name,
                                 p.spec.node_name)
                    return 0
                time.sleep(0.1)
            log.error("demo pods not fully scheduled within 60s")
            return 1
        stop.wait()
        return 0
    finally:
        sched.stop()
        getattr(sched, "_reshaper", None) and sched._reshaper.stop()
        if exporter is not None:
            exporter.stop()


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
