"""gRPC imputation server — C9 parity, re-keyed for TPU slices.

Behavior parity with /root/reference/pkg/recommender/recom_server.py:
- two RPCs looking up the requested index by SUBSTRING match of a train-row
  label inside the ('-'→'_'-normalized) request (:67-71,155-156), imputing
  that row, and returning (values, columns);
- env-configured paths/port (CONFIGURATIONS_DATA_PATH / INTERFERENCE_DATA_PATH
  / PORT / JOB_DELAY, :30-52);
- a background thread that re-fits when a train file's md5 changes
  (:74-134), swapping the serving model atomically.

Data format: TSV, first column = row label, header = column labels, empty
cells = missing (to impute). Configuration columns are {parts}P_{gen}
(e.g. 4P_V5E); interference rows are {workload}_{gen}.
"""
from __future__ import annotations

import csv
import hashlib
import logging
import os
import threading
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import numpy as np

from .model import IterativeImputer
from .wire import (
    SERVICE,
    decode_request,
    encode_reply,
)

log = logging.getLogger(__name__)


def load_matrix(path: str) -> Tuple[List[str], List[str], np.ndarray]:
    """(row_labels, columns, values) from TSV; empty/non-numeric → nan."""
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter="\t")
        header = next(reader)
        columns = header[1:]
        labels: List[str] = []
        rows: List[List[float]] = []
        for rec in reader:
            if not rec or not rec[0].strip():
                continue
            labels.append(rec[0].strip())
            vals = []
            for cell in rec[1 : len(columns) + 1]:
                try:
                    vals.append(float(cell))
                except ValueError:
                    vals.append(float("nan"))
            vals += [float("nan")] * (len(columns) - len(vals))
            rows.append(vals)
    return labels, columns, np.array(rows, dtype=np.float64)


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


class _Table:
    """One train matrix + its fitted imputer, hot-swappable."""

    def __init__(self, path: str):
        self.path = path
        self.version = ""
        self.labels: List[str] = []
        self.columns: List[str] = []
        self.completed: Optional[np.ndarray] = None
        self._mu = threading.Lock()
        self.refresh(force=True)

    def refresh(self, force: bool = False) -> bool:
        try:
            version = _md5(self.path)
        except OSError:
            return False
        # self.version is lock-guarded state — snapshot it under _mu. Two
        # refreshers racing here at worst both retrain (idempotent); a torn
        # read against the locked writer is what the lock rules out.
        with self._mu:
            current = self.version
        if not force and version == current:
            return False
        labels, columns, X = load_matrix(self.path)
        completed = IterativeImputer().fit_transform(X)
        with self._mu:
            self.version = version
            self.labels, self.columns, self.completed = labels, columns, completed
        log.info("recommender: (re)trained %s (%d rows)", self.path, len(labels))
        return True

    def lookup(self, request_index: str) -> Tuple[List[float], List[str]]:
        """First train row whose label occurs inside the normalized request
        (parity: find_index_for_request, recom_server.py:67-71). Fallback for
        suffixed pod names: a label '{workload}_{gen}' also matches when the
        request ends with '_{gen}' and contains the workload — the reference
        breaks on 'llama3-8b-serve-0_V5E' vs row 'llama3_8b_serve_V5E'
        because the replica suffix interrupts the substring."""
        normalized = request_index.replace("-", "_")
        with self._mu:
            for i, label in enumerate(self.labels):
                if label in normalized:
                    return list(self.completed[i]), list(self.columns)
            for i, label in enumerate(self.labels):
                stem, _, suffix = label.rpartition("_")
                if stem and normalized.endswith("_" + suffix) and stem in normalized:
                    return list(self.completed[i]), list(self.columns)
        return [], []


class RecommenderServer:
    def __init__(
        self,
        configurations_path: str,
        interference_path: str,
        port: int = 0,
        retrain_interval_s: float = 30.0,
        workers: int = 10,
    ):
        self.configurations = _Table(configurations_path)
        self.interference = _Table(interference_path)
        self.retrain_interval_s = retrain_interval_s
        self._port = port
        self._workers = workers
        self._server = None
        self._stop = threading.Event()
        self._retrainer: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._port

    # -- RPC handlers ------------------------------------------------------
    def _impute(self, table: _Table, index: str, context) -> bytes:
        result, columns = table.lookup(index)
        return encode_reply(result, columns)

    def start(self) -> "RecommenderServer":
        import grpc

        handlers = grpc.method_handlers_generic_handler(
            SERVICE,
            {
                "ImputeConfigurations": grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx: self._impute(self.configurations, req, ctx),
                    request_deserializer=decode_request,
                    response_serializer=lambda b: b,
                ),
                "ImputeInterference": grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx: self._impute(self.interference, req, ctx),
                    request_deserializer=decode_request,
                    response_serializer=lambda b: b,
                ),
            },
        )
        self._server = grpc.server(futures.ThreadPoolExecutor(self._workers))
        self._server.add_generic_rpc_handlers((handlers,))
        self._port = self._server.add_insecure_port(f"[::]:{self._port}")
        self._server.start()
        self._retrainer = threading.Thread(target=self._retrain_loop, daemon=True)
        self._retrainer.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace=1)
        if self._retrainer is not None:
            self._retrainer.join(timeout=2)

    def _retrain_loop(self) -> None:
        while not self._stop.wait(self.retrain_interval_s):
            for table in (self.configurations, self.interference):
                try:
                    table.refresh()
                except Exception:  # noqa: BLE001 — bad data must not kill serving
                    log.exception("retrain failed for %s", table.path)


def main() -> None:  # pragma: no cover — exercised via the CLI
    logging.basicConfig(level=logging.INFO)
    here = os.path.dirname(os.path.abspath(__file__))
    configurations_path = os.environ.get(
        "CONFIGURATIONS_DATA_PATH",
        os.path.join(here, "data/configurations_train.tsv"))
    interference_path = os.environ.get(
        "INTERFERENCE_DATA_PATH",
        os.path.join(here, "data/interference_train.tsv"))
    server = RecommenderServer(
        configurations_path=configurations_path,
        interference_path=interference_path,
        port=int(os.environ.get("PORT", "32700")),
        retrain_interval_s=float(os.environ.get("JOB_DELAY", "30")),
    ).start()
    print(f"recommender serving on :{server.port}", flush=True)
    # Observation collector: when the registry is configured, measured
    # workload throughput flows back into the train matrix (the md5-watch
    # retrain above then picks it up). Optional with graceful degradation,
    # like every sidecar in this framework.
    collector = None
    try:
        from ..config import SchedulerConfig
        from ..registry.client import Client as RegistryClient
        from .collector import Collector

        rc = SchedulerConfig.from_env().registry
        reg = RegistryClient(rc.host, rc.port, password=rc.password)
        reg.ping()
        collector = Collector(
            reg, configurations_path,
            interval_s=float(os.environ.get("JOB_DELAY", "30")),
            interference_path=interference_path,
        ).start()
        print(f"collector polling registry at {rc.host}:{rc.port}",
              flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"collector disabled (no registry: {e})", flush=True)
    try:
        threading.Event().wait()
    finally:
        if collector is not None:
            collector.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
