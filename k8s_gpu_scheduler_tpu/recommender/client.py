"""gRPC client — C8 parity (go_client/pkg/client_call.go:11-37), returning
column→value dicts that satisfy plugins.tpu.PredictionClient directly.

Unlike the reference's dial-per-call clients, one channel persists for the
client's lifetime (the scoring hot loop makes 2 calls per resident pod —
re-dialing each would dominate the cycle), and replies are memoized for a
short TTL: predictions only move on the server's retrain cadence (30 s
md5 watch, server.py), so scoring many nodes against the same resident
pods within a cycle — or across back-to-back cycles — repeats identical
queries. The reference pays the full quadratic RPC cost every cycle
(gpu_plugins.go:577-590)."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from .wire import (
    METHOD_CONFIGURATIONS,
    METHOD_INTERFERENCE,
    decode_reply,
    encode_request,
)


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 32700,
                 timeout_s: float = 2.0, cache_ttl_s: float = 5.0):
        import grpc

        self._timeout = timeout_s
        self._ttl = cache_ttl_s
        # (method, index) -> (expiry, reply dict). Errors are never cached
        # (a transient server outage must not pin failures for a TTL).
        self._cache: Dict[Tuple[str, str], Tuple[float, Dict[str, float]]] = {}
        self._mu = threading.Lock()
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._conf = self._channel.unary_unary(
            METHOD_CONFIGURATIONS,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )
        self._intf = self._channel.unary_unary(
            METHOD_INTERFERENCE,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )

    def _cached(self, kind: str, index: str, call) -> Dict[str, float]:
        now = time.monotonic()
        key = (kind, index)
        if self._ttl > 0:
            with self._mu:
                hit = self._cache.get(key)
                if hit is not None and hit[0] > now:
                    # Copy: callers own their reply dict — handing out the
                    # cached object would let one caller's mutation poison
                    # every later hit.
                    return dict(hit[1])
        result, columns = call(index, timeout=self._timeout)
        reply = dict(zip(columns, result))
        if self._ttl > 0:
            with self._mu:
                if len(self._cache) > 4096:          # scoring-universe bound
                    self._cache.clear()
                self._cache[key] = (now + self._ttl, reply)
        return reply

    def impute_configurations(self, index: str) -> Dict[str, float]:
        return self._cached("conf", index, self._conf)

    def impute_interference(self, index: str) -> Dict[str, float]:
        return self._cached("intf", index, self._intf)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def find_max_index(predictions: Dict[str, float], substring: str = "") -> Optional[Tuple[str, float]]:
    """Highest-valued column (optionally filtered by substring) — parity with
    FindMaxIndForNode (go_client/utils/utils.go:9-18)."""
    best: Optional[Tuple[str, float]] = None
    for col, val in predictions.items():
        if substring and substring not in col:
            continue
        if best is None or val > best[1]:
            best = (col, val)
    return best
