"""gRPC client — C8 parity (go_client/pkg/client_call.go:11-37), returning
column→value dicts that satisfy plugins.tpu.PredictionClient directly.

Unlike the reference's dial-per-call clients, one channel persists for the
client's lifetime (the scoring hot loop makes 2 calls per resident pod —
re-dialing each would dominate the cycle)."""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .wire import (
    METHOD_CONFIGURATIONS,
    METHOD_INTERFERENCE,
    decode_reply,
    encode_request,
)


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 32700,
                 timeout_s: float = 2.0):
        import grpc

        self._timeout = timeout_s
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._conf = self._channel.unary_unary(
            METHOD_CONFIGURATIONS,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )
        self._intf = self._channel.unary_unary(
            METHOD_INTERFERENCE,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )

    def impute_configurations(self, index: str) -> Dict[str, float]:
        result, columns = self._conf(index, timeout=self._timeout)
        return dict(zip(columns, result))

    def impute_interference(self, index: str) -> Dict[str, float]:
        result, columns = self._intf(index, timeout=self._timeout)
        return dict(zip(columns, result))

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def find_max_index(predictions: Dict[str, float], substring: str = "") -> Optional[Tuple[str, float]]:
    """Highest-valued column (optionally filtered by substring) — parity with
    FindMaxIndForNode (go_client/utils/utils.go:9-18)."""
    best: Optional[Tuple[str, float]] = None
    for col, val in predictions.items():
        if substring and substring not in col:
            continue
        if best is None or val > best[1]:
            best = (col, val)
    return best
