"""gRPC client — C8 parity (go_client/pkg/client_call.go:11-37), returning
column→value dicts that satisfy plugins.tpu.PredictionClient directly.

Unlike the reference's dial-per-call clients, one channel persists for the
client's lifetime (the scoring hot loop makes 2 calls per resident pod —
re-dialing each would dominate the cycle), and replies are memoized for a
short TTL: predictions only move on the server's retrain cadence (30 s
md5 watch, server.py), so scoring many nodes against the same resident
pods within a cycle — or across back-to-back cycles — repeats identical
queries. The reference pays the full quadratic RPC cost every cycle
(gpu_plugins.go:577-590).

Failure handling (the robustness PR): each RPC retries transient gRPC
failures under a bounded ``RetryPolicy`` (utils/retry.py — attempt cap,
jittered exponential backoff, wall-clock deadline), then raises to the
caller; the TPU plugin's Score path catches that, counts it, and scores
WITHOUT the recommender signal for the cycle (degraded scoring) instead
of failing the pod. ``on_retry`` feeds
``tpu_sched_rpc_retries_total{client="recommender"}`` and
``fault_injector`` exposes the ``recommender.call`` hook to the chaos
harness (testing/faults.py)."""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..utils.retry import RetryPolicy, retry_call
from .wire import (
    METHOD_CONFIGURATIONS,
    METHOD_INTERFERENCE,
    decode_reply,
    encode_request,
)


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 32700,
                 timeout_s: float = 2.0, cache_ttl_s: float = 5.0,
                 retry: Optional[RetryPolicy] = None,
                 on_retry: Optional[Callable[[], None]] = None,
                 fault_injector=None):
        import grpc

        self._timeout = timeout_s
        self._ttl = cache_ttl_s
        # Bounded: 3 tries, ~20/40 ms jittered backoff, whole-call
        # deadline — the Score hot loop makes 2 calls per resident pod,
        # so a dead recommender must cost milliseconds-bounded failures
        # the plugin can degrade around, never a hang per call.
        self._retry = retry or RetryPolicy(attempts=3, base_s=0.02,
                                           max_s=0.2, deadline_s=1.5)
        self.on_retry = on_retry
        self._faults = fault_injector
        self._retryable: tuple = (grpc.RpcError,)
        # (method, index) -> (expiry, reply dict). Errors are never cached
        # (a transient server outage must not pin failures for a TTL).
        self._cache: Dict[Tuple[str, str], Tuple[float, Dict[str, float]]] = {}
        self._mu = threading.Lock()
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._conf = self._channel.unary_unary(
            METHOD_CONFIGURATIONS,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )
        self._intf = self._channel.unary_unary(
            METHOD_INTERFERENCE,
            request_serializer=encode_request,
            response_deserializer=decode_reply,
        )

    def _cached(self, kind: str, index: str, call) -> Dict[str, float]:
        now = time.monotonic()
        key = (kind, index)
        if self._ttl > 0:
            with self._mu:
                hit = self._cache.get(key)
                if hit is not None and hit[0] > now:
                    # Copy: callers own their reply dict — handing out the
                    # cached object would let one caller's mutation poison
                    # every later hit.
                    return dict(hit[1])
        result, columns = self._call_bounded(call, index)
        reply = dict(zip(columns, result))
        if self._ttl > 0:
            with self._mu:
                if len(self._cache) > 4096:          # scoring-universe bound
                    self._cache.clear()
                self._cache[key] = (now + self._ttl, reply)
        return reply

    def _call_bounded(self, call, index: str):
        """One RPC under the bounded-retry policy: transient gRPC
        failures (server restarting, connection reset) and injected
        chaos faults retry with jittered backoff until the attempt or
        deadline bound, then raise to the caller — who degrades (the
        plugin scores without the signal) rather than hangs."""
        from ..testing.faults import InjectedFault

        def attempt():
            if self._faults is not None:
                self._faults.fire("recommender.call")
            return call(index, timeout=self._timeout)

        on_retry = self.on_retry

        def count(_attempt, _exc):
            if on_retry is not None:
                on_retry()

        return retry_call(attempt, self._retry,
                          retry_on=self._retryable + (InjectedFault,),
                          on_retry=count)

    def impute_configurations(self, index: str) -> Dict[str, float]:
        return self._cached("conf", index, self._conf)

    def impute_interference(self, index: str) -> Dict[str, float]:
        return self._cached("intf", index, self._intf)

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def find_max_index(predictions: Dict[str, float], substring: str = "") -> Optional[Tuple[str, float]]:
    """Highest-valued column (optionally filtered by substring) — parity with
    FindMaxIndForNode (go_client/utils/utils.go:9-18)."""
    best: Optional[Tuple[str, float]] = None
    for col, val in predictions.items():
        if substring and substring not in col:
            continue
        if best is None or val > best[1]:
            best = (col, val)
    return best
