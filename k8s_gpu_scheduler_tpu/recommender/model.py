"""Iterative ridge-regression imputer (MICE-style), numpy only.

Fills missing cells of the throughput/interference matrices — the job the
reference delegates to sklearn's IterativeImputer behind a 27-line wrapper
(C10, /root/reference/pkg/recommender/recommender/recommender.py:15-28).
Ours is self-contained: round-robin regress each incomplete column on the
others over a mean-initialized completion, repeat until convergence, keep
the per-column regressors so ``transform`` can impute unseen rows without
refitting.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class IterativeImputer:
    def __init__(self, max_iter: int = 10, ridge: float = 1e-3, tol: float = 1e-4):
        self.max_iter = max_iter
        self.ridge = ridge
        self.tol = tol
        self.means_: Optional[np.ndarray] = None
        self.weights_: Dict[int, np.ndarray] = {}  # col -> [d] (bias last)

    def fit(self, X: np.ndarray) -> "IterativeImputer":
        X = np.asarray(X, dtype=np.float64)
        n, d = X.shape
        mask = np.isnan(X)
        with np.errstate(all="ignore"):
            means = np.nanmean(X, axis=0)
        means = np.where(np.isfinite(means), means, 0.0)
        self.means_ = means

        Xc = np.where(mask, means, X)
        for _ in range(self.max_iter):
            prev = Xc.copy()
            for j in range(d):
                w = self._fit_column(X, Xc, mask, j)
                if w is None:
                    continue
                self.weights_[j] = w
                miss = mask[:, j]
                if miss.any():
                    Xc[miss, j] = self._predict_column(Xc[miss], j, w)
            if np.abs(Xc - prev).max() <= self.tol:
                break
        self.train_completed_ = Xc
        return self

    def _fit_column(self, X, Xc, mask, j) -> Optional[np.ndarray]:
        obs = ~mask[:, j]
        if obs.sum() < 2:
            return None  # not enough signal; mean fill stands
        others = np.delete(np.arange(X.shape[1]), j)
        A = Xc[obs][:, others]
        A = np.hstack([A, np.ones((A.shape[0], 1))])  # bias
        y = X[obs, j]
        # ridge normal equations — tiny d, direct solve is exact enough
        G = A.T @ A + self.ridge * np.eye(A.shape[1])
        return np.linalg.solve(G, A.T @ y)

    def _predict_column(self, rows: np.ndarray, j: int, w: np.ndarray) -> np.ndarray:
        others = np.delete(np.arange(rows.shape[1]), j)
        A = np.hstack([rows[:, others], np.ones((rows.shape[0], 1))])
        return A @ w

    def transform(self, rows: np.ndarray) -> np.ndarray:
        """Impute nan cells of ``rows`` [m, d] using the fitted regressors."""
        if self.means_ is None:
            raise RuntimeError("transform before fit")
        rows = np.asarray(rows, dtype=np.float64)
        mask = np.isnan(rows)
        out = np.where(mask, self.means_, rows)
        for _ in range(self.max_iter):
            prev = out.copy()
            for j in range(rows.shape[1]):
                miss = mask[:, j]
                if not miss.any():
                    continue
                w = self.weights_.get(j)
                if w is None:
                    continue
                out[miss, j] = self._predict_column(out[miss], j, w)
            if np.abs(out - prev).max() <= self.tol:
                break
        return out

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        self.fit(X)
        return self.train_completed_
