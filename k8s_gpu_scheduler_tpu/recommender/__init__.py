"""Prediction service — throughput/interference imputation over gRPC.

Parity with the reference's recommender stack (C8-C13, SURVEY.md §2):
same wire protocol (protos/recom.proto — package/service ``recommender``,
``ImputeConfigurations``/``ImputeInterference``), same serving behavior
(substring index lookup with '-'→'_' normalization, md5-watched background
retrain with atomic model swap), re-keyed for TPUs: configuration columns
are ``{parts}P_{gen}`` (e.g. ``4P_V5E`` = 4-way-partitioned v5e host) and
interference rows are ``{workload}_{gen}``.

Original implementation differences (deliberate):
- messages are encoded with a 40-line hand-rolled proto3 wire codec
  (wire.py) served through grpc generic handlers — no codegen toolchain in
  the serving path, still byte-compatible with the reference's stubs;
- the imputer is a numpy iterative ridge-regression (MICE-style) model
  (model.py) instead of a scikit-learn import — deterministic, hermetic,
  dependency-free.
"""
from .client import Client, find_max_index
from .model import IterativeImputer
from .server import RecommenderServer

__all__ = ["Client", "find_max_index", "IterativeImputer", "RecommenderServer"]
