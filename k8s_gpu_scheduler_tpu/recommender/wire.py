"""Hand-rolled proto3 wire codec for the two recommender messages.

Replaces generated stubs (the reference ships 420 lines of protoc output,
C12 in SURVEY.md §2) with direct encoding of the same bytes:

- ``Request``: field 1 string (tag 0x0A, LEN).
- ``Reply``: field 1 repeated float — packed fixed32 (tag 0x0A, LEN) as
  proto3 emits, though the decoder also accepts unpacked (tag 0x0D);
  field 2 repeated string (tag 0x12, LEN per element).
"""
from __future__ import annotations

import struct
from typing import List, Tuple


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        if i >= len(buf):
            raise ValueError("truncated varint")
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def encode_request(index: str) -> bytes:
    data = index.encode()
    return b"\x0a" + _varint(len(data)) + data


def decode_request(buf: bytes) -> str:
    i, index = 0, ""
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:
            ln, i = _read_varint(buf, i)
            index = buf[i : i + ln].decode()
            i += ln
        else:
            i = _skip(buf, i, wt)
    return index


def encode_reply(result: List[float], columns: List[str]) -> bytes:
    out = bytearray()
    if result:
        packed = b"".join(struct.pack("<f", v) for v in result)
        out += b"\x0a" + _varint(len(packed)) + packed
    for c in columns:
        data = c.encode()
        out += b"\x12" + _varint(len(data)) + data
    return bytes(out)


def decode_reply(buf: bytes) -> Tuple[List[float], List[str]]:
    result: List[float] = []
    columns: List[str] = []
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if field == 1 and wt == 2:  # packed floats
            ln, i = _read_varint(buf, i)
            result.extend(
                struct.unpack_from("<f", buf, i + off)[0] for off in range(0, ln, 4)
            )
            i += ln
        elif field == 1 and wt == 5:  # unpacked float
            result.append(struct.unpack_from("<f", buf, i)[0])
            i += 4
        elif field == 2 and wt == 2:
            ln, i = _read_varint(buf, i)
            columns.append(buf[i : i + ln].decode())
            i += ln
        else:
            i = _skip(buf, i, wt)
    return result, columns


def _skip(buf: bytes, i: int, wire_type: int) -> int:
    if wire_type == 0:
        _, i = _read_varint(buf, i)
        return i
    if wire_type == 1:
        return i + 8
    if wire_type == 2:
        ln, i = _read_varint(buf, i)
        return i + ln
    if wire_type == 5:
        return i + 4
    raise ValueError(f"unsupported wire type {wire_type}")


SERVICE = "recommender.recommender"
METHOD_CONFIGURATIONS = f"/{SERVICE}/ImputeConfigurations"
METHOD_INTERFERENCE = f"/{SERVICE}/ImputeInterference"
