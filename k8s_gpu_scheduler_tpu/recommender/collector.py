"""Observation collector — folds measured throughput into the train matrix.

Closes the feedback loop the reference only gestures at: its train matrices
are hand-measured offline (.ods files, SURVEY.md §2 C11) and its retrain
thread (recom_server.py:74-134) only ever re-reads the same file. Here:

  workload (models/llama.py) → Observation in the registry
      → Collector (this module) updates the configurations TSV
          → RecommenderServer's md5-watch retrains (server.py _Table.refresh)
              → next ImputeConfigurations reply is observation-anchored

Cell update policy: a blank (imputed-only) cell takes the observation
verbatim; a measured cell moves by EWMA (``alpha`` on the new sample) so one
noisy run cannot wreck a row. New workloads append a row; observations for
unknown columns are dropped (the column set IS the schema — slice shapes ×
generations).

Each registry sample is folded at most once: the collector remembers the
``Observation.at`` timestamp it last folded per key and skips samples that
haven't advanced. Without the gate, a workload that stops publishing would
leave its final sample in the registry and every 30 s pass would re-EWMA it
until the cell converged to that raw sample — defeating the damping — while
rewriting the TSV (and retraining the server) forever.

The TSV write is atomic (tmp + rename) so the server never reads a torn
file; its md5 check makes the handoff race-free.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

from ..registry.inventory import OBSERVED_KEY_PREFIX, Observation
from .server import load_matrix

log = logging.getLogger(__name__)


class Collector:
    def __init__(self, registry, configurations_path: str,
                 interval_s: float = 30.0, alpha: float = 0.5,
                 interference_path: Optional[str] = None) -> None:
        """``interference_path``: when given, samples tagged with neighbors
        (TPU_NEIGHBORS-injected co-residents) fold their throughput DELTA
        vs the solo configurations cell into the interference matrix —
        closing the half of the loop r3 left open (VERDICT.md weak #6: the
        interference rows stayed offline seed data forever, the exact .ods
        weakness SURVEY flags in the reference)."""
        self.registry = registry
        self.path = configurations_path
        self.interference_path = interference_path
        self.interval_s = interval_s
        self.alpha = alpha
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # key -> Observation.at of the last sample folded from that key.
        self._folded_at: Dict[str, float] = {}

    # -- one pass ----------------------------------------------------------
    def collect_once(self) -> bool:
        """Fold all registry observations into the TSV. True iff the file
        changed (and therefore a retrain will trigger)."""
        try:
            keys = self.registry.get_keys(OBSERVED_KEY_PREFIX + "*")
        except Exception as e:  # noqa: BLE001 — registry outage is routine
            log.warning("collector: registry unavailable (%s)", e)
            return False
        observations: List["tuple[str, Observation]"] = []
        for key in keys:
            raw = self.registry.get(key)
            if not raw:
                continue
            try:
                obs = Observation.from_json(raw)
            except (ValueError, TypeError) as e:
                log.warning("collector: bad observation at %s: %s", key, e)
                continue
            # Fold each sample at most once: a key whose ``at`` hasn't
            # advanced since the last pass is the same sample still sitting
            # in the registry, not a new measurement.
            if obs.at <= self._folded_at.get(key, -math.inf):
                continue
            self._folded_at[key] = obs.at
            observations.append((key, obs))
        # Drop tracking for keys that vanished so the map can't grow forever.
        live = set(keys)
        for stale in [k for k in self._folded_at if k not in live]:
            del self._folded_at[stale]
        if not observations:
            return False

        solo = [o for _, o in observations if not o.neighbors]
        co = [(k, o) for k, o in observations if o.neighbors]
        changed = self._fold_configurations(solo)
        deferred_keys: set = set()
        if self.interference_path is not None and co:
            folded, deferred_keys = self._fold_interference(co)
            changed = folded or changed
            # A sample whose solo baseline doesn't exist yet is genuinely
            # DEFERRED: forget its fold timestamp so the next pass retries
            # it (by then the baseline may have landed).
            for key in deferred_keys:
                self._folded_at.pop(key, None)
        # Latencies fold AFTER the defer decision and skip deferred keys:
        # a deferred sample re-enters observations on every pass, and
        # re-EWMA-ing its p99 each time would give one sample the weight
        # of N (the interference matrices are protected by the timestamp
        # gate; the latency keys need the same discipline).
        self._fold_latencies(
            [o for k, o in observations if k not in deferred_keys])
        return changed

    def _fold_latencies(self, observations: List[Observation]) -> None:
        """Measured p99 samples → EWMA'd latency/<workload>/<column> keys
        (registry/inventory.py latency_key) — the read side is the TPU
        plugin's rightsize/score path, which must prefer partitions whose
        MEASURED latency meets the pod's SLO_P99_MS (VERDICT r4 #3: you
        cannot verify an SLO you never measure). Solo and co-located
        samples blend into one key: the pod's next placement should answer
        to the latency it actually experienced, neighbors included. The
        caller excludes interference-deferred samples (collect_once) — a
        deferred key re-enters every pass and would otherwise re-EWMA one
        sample with the weight of many."""
        from ..registry.inventory import latency_key

        for obs in observations:
            if obs.p99_ms <= 0 or not obs.workload or not obs.column:
                continue
            key = latency_key(obs.workload, obs.column)
            try:
                old_raw = self.registry.get(key)
                old = float(old_raw) if old_raw else float("nan")
                new = obs.p99_ms if math.isnan(old) else (
                    self.alpha * obs.p99_ms + (1 - self.alpha) * old)
                self.registry.set(key, f"{new:g}")
            except Exception as e:  # noqa: BLE001 — latency fold is advisory
                log.debug("latency fold failed for %s: %s", key, e)

    def _fold_configurations(self, observations: List[Observation]) -> bool:
        if not observations:
            return False
        labels, columns, X = load_matrix(self.path)
        rows = [list(r) for r in X]
        changed = False
        for obs in observations:
            if obs.qps <= 0 or not obs.workload:
                continue
            if obs.column not in columns:
                log.warning("collector: unknown column %r (workload %s) — "
                            "dropped", obs.column, obs.workload)
                continue
            j = columns.index(obs.column)
            if obs.workload in labels:
                i = labels.index(obs.workload)
            else:
                labels.append(obs.workload)
                rows.append([float("nan")] * len(columns))
                i = len(labels) - 1
                changed = True
            old = rows[i][j]
            new = obs.qps if math.isnan(old) else (
                self.alpha * obs.qps + (1 - self.alpha) * old)
            if math.isnan(old) or abs(new - old) > 1e-9:
                rows[i][j] = new
                changed = True
        if changed:
            self._write(self.path, labels, columns, rows)
            log.info("collector: folded %d solo observation(s) into %s",
                     len(observations), self.path)
        return changed

    def _fold_interference(
        self, observations: List["tuple[str, Observation]"]
    ) -> "tuple[bool, set]":
        """Co-located samples → interference rows. The degradation is the
        solo configurations cell minus the observed co-located QPS, split
        evenly across the neighbors present (the reference's matrix stores
        pairwise deltas; with >1 neighbor the split is the unbiased
        first-order attribution). Row key is the reference's
        ``{workload}_{gen}`` convention (recom_server row labels); columns
        are neighbor workload names and may grow (every row pads with
        NaN — the imputer fills them). Takes (registry key, observation)
        pairs; returns (changed, keys of deferred observations — no
        baseline yet, retry next pass)."""
        deferred: set = set()
        labels, columns, X = load_matrix(self.path)

        def solo_qps(workload: str, column: str) -> Optional[float]:
            if workload in labels and column in columns:
                v = X[labels.index(workload)][columns.index(column)]
                return None if math.isnan(v) else v
            return None

        ilabels, icolumns, iX = load_matrix(self.interference_path)
        irows = [list(r) for r in iX]
        changed = False
        for key, obs in observations:
            if obs.qps < 0 or not obs.workload:
                continue
            base = solo_qps(obs.workload, obs.column)
            if base is None:
                log.info("collector: no solo baseline for %s/%s — "
                         "interference sample deferred",
                         obs.workload, obs.column)
                deferred.add(key)
                continue
            delta = max(0.0, base - obs.qps) / max(len(obs.neighbors), 1)
            gen = obs.column.rsplit("_", 1)[-1]
            row_label = f"{obs.workload}_{gen}"
            if row_label in ilabels:
                i = ilabels.index(row_label)
            else:
                ilabels.append(row_label)
                irows.append([float("nan")] * len(icolumns))
                i = len(ilabels) - 1
                changed = True
            for nb in obs.neighbors:
                if nb not in icolumns:
                    icolumns.append(nb)
                    for r in irows:
                        r.append(float("nan"))
                    changed = True
                j = icolumns.index(nb)
                old = irows[i][j]
                new = delta if math.isnan(old) else (
                    self.alpha * delta + (1 - self.alpha) * old)
                if math.isnan(old) or abs(new - old) > 1e-9:
                    irows[i][j] = new
                    changed = True
        if changed:
            self._write(self.interference_path, ilabels, icolumns, irows)
            log.info("collector: folded %d co-location observation(s) "
                     "into %s", len(observations) - len(deferred),
                     self.interference_path)
        return changed, deferred

    @staticmethod
    def _write(path: str, labels: List[str], columns: List[str],
               rows: List[List[float]]) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", newline="") as f:
            f.write("workload\t" + "\t".join(columns) + "\n")
            for label, row in zip(labels, rows):
                cells = ["" if math.isnan(v) else f"{v:g}" for v in row]
                f.write(label + "\t" + "\t".join(cells) + "\n")
        os.replace(tmp, path)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Collector":
        self._thread = threading.Thread(
            target=self._run, name="recom-collector", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("collector pass failed")


def publish_observation(registry, workload: str, column: str,
                        qps: float, neighbors: Optional[List[str]] = None,
                        p99_ms: float = 0.0) -> None:
    """Workload-side helper: push one throughput sample (models call this
    after each measured interval; failures are swallowed — observability
    must never kill the workload). ``neighbors``: co-residents from the
    injected TPU_NEIGHBORS — tags the sample as an interference
    measurement. ``p99_ms``: measured per-request p99 latency when the
    workload has one (serving engines do — llama --serve folds it from
    ContinuousBatcher.pop_request_metrics)."""
    from ..registry.inventory import observed_key

    try:
        neighbors = sorted(neighbors or [])
        registry.set(
            observed_key(workload, column, co_located=bool(neighbors)),
            Observation(workload, column, qps, time.time(),
                        neighbors=neighbors, p99_ms=p99_ms).to_json())
    except Exception as e:  # noqa: BLE001
        log.debug("observation publish failed: %s", e)


def make_workload_publisher(n_devices: int = 1):
    """Build a ``publish(qps)`` callable from the scheduler-injected
    workload env (WORKLOAD_NAME row label, TPU_VISIBLE_CHIPS column,
    registry address), or None when publishing isn't configured. The ONE
    wiring shared by every model entrypoint (llama/resnet/bert mains) —
    each publish reads the LIVE neighbor list so samples are tagged
    solo vs co-located correctly as tenants come and go."""
    import os

    workload_name = os.environ.get("WORKLOAD_NAME", "")
    if not workload_name:
        return None
    try:
        from ..api.topology import TPUGen
        from ..config import SchedulerConfig
        from ..registry.client import Client as RegistryClient

        rc = SchedulerConfig.from_env().registry
        reg = RegistryClient(rc.host, rc.port, password=rc.password)
        reg.ping()
        chips = len([c for c in
                     os.environ.get("TPU_VISIBLE_CHIPS", "").split(",")
                     if c]) or n_devices
        try:
            gen = TPUGen(os.environ.get("TPU_ACCELERATOR_TYPE", "")).name
        except ValueError:
            gen = "V5E"
        column = f"{chips}P_{gen}"
        pod_name = os.environ.get("HOSTNAME", "")
        env_neighbors = os.environ.get("TPU_NEIGHBORS", "")

        def publish(qps: float, p99_ms: float = 0.0) -> None:
            publish_observation(
                reg, workload_name, column, qps,
                neighbors=current_neighbors(reg, pod_name, env_neighbors),
                p99_ms=p99_ms)

        return publish
    except Exception as e:  # noqa: BLE001 — observability never kills work
        log.warning("observation publishing disabled: %s", e)
        return None


def current_neighbors(registry, pod_name: str, env_value: str = "") -> List[str]:
    """The LIVE neighbor list for a pod: the scheduler refreshes
    ``neighbors/<pod>`` at every bind that changes the pod's partition
    co-residency, so workloads read it per publish interval instead of
    trusting the bind-time TPU_NEIGHBORS env (static — a tenant that was
    alone at bind would otherwise keep tagging samples solo forever)."""
    try:
        raw = registry.get(f"neighbors/{pod_name}")
    except Exception:  # noqa: BLE001
        raw = None
    if raw is None:
        raw = env_value
    return sorted(n for n in raw.split(",") if n)
