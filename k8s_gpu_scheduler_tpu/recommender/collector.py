"""Observation collector — folds measured throughput into the train matrix.

Closes the feedback loop the reference only gestures at: its train matrices
are hand-measured offline (.ods files, SURVEY.md §2 C11) and its retrain
thread (recom_server.py:74-134) only ever re-reads the same file. Here:

  workload (models/llama.py) → Observation in the registry
      → Collector (this module) updates the configurations TSV
          → RecommenderServer's md5-watch retrains (server.py _Table.refresh)
              → next ImputeConfigurations reply is observation-anchored

Cell update policy: a blank (imputed-only) cell takes the observation
verbatim; a measured cell moves by EWMA (``alpha`` on the new sample) so one
noisy run cannot wreck a row. New workloads append a row; observations for
unknown columns are dropped (the column set IS the schema — slice shapes ×
generations).

Each registry sample is folded at most once: the collector remembers the
``Observation.at`` timestamp it last folded per key and skips samples that
haven't advanced. Without the gate, a workload that stops publishing would
leave its final sample in the registry and every 30 s pass would re-EWMA it
until the cell converged to that raw sample — defeating the damping — while
rewriting the TSV (and retraining the server) forever.

The TSV write is atomic (tmp + rename) so the server never reads a torn
file; its md5 check makes the handoff race-free.
"""
from __future__ import annotations

import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

from ..registry.inventory import OBSERVED_KEY_PREFIX, Observation
from .server import load_matrix

log = logging.getLogger(__name__)


class Collector:
    def __init__(self, registry, configurations_path: str,
                 interval_s: float = 30.0, alpha: float = 0.5) -> None:
        self.registry = registry
        self.path = configurations_path
        self.interval_s = interval_s
        self.alpha = alpha
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # key -> Observation.at of the last sample folded from that key.
        self._folded_at: Dict[str, float] = {}

    # -- one pass ----------------------------------------------------------
    def collect_once(self) -> bool:
        """Fold all registry observations into the TSV. True iff the file
        changed (and therefore a retrain will trigger)."""
        try:
            keys = self.registry.get_keys(OBSERVED_KEY_PREFIX + "*")
        except Exception as e:  # noqa: BLE001 — registry outage is routine
            log.warning("collector: registry unavailable (%s)", e)
            return False
        observations: List[Observation] = []
        for key in keys:
            raw = self.registry.get(key)
            if not raw:
                continue
            try:
                obs = Observation.from_json(raw)
            except (ValueError, TypeError) as e:
                log.warning("collector: bad observation at %s: %s", key, e)
                continue
            # Fold each sample at most once: a key whose ``at`` hasn't
            # advanced since the last pass is the same sample still sitting
            # in the registry, not a new measurement.
            if obs.at <= self._folded_at.get(key, -math.inf):
                continue
            self._folded_at[key] = obs.at
            observations.append(obs)
        # Drop tracking for keys that vanished so the map can't grow forever.
        live = set(keys)
        for stale in [k for k in self._folded_at if k not in live]:
            del self._folded_at[stale]
        if not observations:
            return False

        labels, columns, X = load_matrix(self.path)
        rows = [list(r) for r in X]
        changed = False
        for obs in observations:
            if obs.qps <= 0 or not obs.workload:
                continue
            if obs.column not in columns:
                log.warning("collector: unknown column %r (workload %s) — "
                            "dropped", obs.column, obs.workload)
                continue
            j = columns.index(obs.column)
            if obs.workload in labels:
                i = labels.index(obs.workload)
            else:
                labels.append(obs.workload)
                rows.append([float("nan")] * len(columns))
                i = len(labels) - 1
                changed = True
            old = rows[i][j]
            new = obs.qps if math.isnan(old) else (
                self.alpha * obs.qps + (1 - self.alpha) * old)
            if math.isnan(old) or abs(new - old) > 1e-9:
                rows[i][j] = new
                changed = True
        if not changed:
            return False
        self._write(labels, columns, rows)
        log.info("collector: folded %d observation(s) into %s",
                 len(observations), self.path)
        return True

    def _write(self, labels: List[str], columns: List[str],
               rows: List[List[float]]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", newline="") as f:
            f.write("workload\t" + "\t".join(columns) + "\n")
            for label, row in zip(labels, rows):
                cells = ["" if math.isnan(v) else f"{v:g}" for v in row]
                f.write(label + "\t" + "\t".join(cells) + "\n")
        os.replace(tmp, self.path)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Collector":
        self._thread = threading.Thread(
            target=self._run, name="recom-collector", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("collector pass failed")


def publish_observation(registry, workload: str, column: str,
                        qps: float) -> None:
    """Workload-side helper: push one throughput sample (models call this
    after each measured interval; failures are swallowed — observability
    must never kill the workload)."""
    from ..registry.inventory import observed_key

    try:
        registry.set(observed_key(workload, column),
                     Observation(workload, column, qps, time.time()).to_json())
    except Exception as e:  # noqa: BLE001
        log.debug("observation publish failed: %s", e)
