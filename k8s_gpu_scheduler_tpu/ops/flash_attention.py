"""Flash attention — Pallas TPU kernels, forward AND backward.

Dense attention materializes the [T, T] score matrix in HBM; these kernels
stream K/V blocks through VMEM keeping flash-style running softmax stats
(m, l) in scratch, so memory is O(block²) and the MXU sees back-to-back
[block_q, d]×[d, block_k] and [block_q, block_k]×[block_k, d] matmuls.

Forward: grid = (batch·heads, q_blocks, kv_blocks), kv innermost and
sequential ("arbitrary" semantics): scratch accumulators persist across the
kv sweep, reset at kv==0, normalized+written at the last kv block. The
per-row logsumexp (lse = m + log l) is written alongside the output —
broadcast across a 128-lane trailing dim so no cross-lane transpose is
needed — and is the only extra residual the backward needs.

Backward (flash-style, no [T, T] materialization): probabilities are
recomputed blockwise from the saved lse, so

    p_ij  = exp(s_ij − lse_i)            (already normalized)
    D_i   = Σ_j p_ij·(do_i·v_j) = do_i·o_i   (computed from do∘o, no pass
                                              over the scores needed)
    ds_ij = p_ij (do_i·v_j − D_i)
    dq_i  = scale·Σ_j ds_ij k_j          (kernel 1: kv sweep per q block)
    dk_j  = scale·Σ_i ds_ij q_i          (kernel 2: q sweep per kv block)
    dv_j  = Σ_i p_ij do_i                (kernel 2)

Fully-masked causal blocks are skipped with pl.when in all three kernels
(≈2× fewer FLOPs at long T). On CPU the wrappers transparently use
interpret mode, so tests run hermetically; gradient agreement with
dense_attention is asserted in tests/test_ops.py.

Replaces the round-2 recompute-through-dense backward (VERDICT.md weak #1):
training with attn_impl="flash" now runs flash cost in BOTH directions.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas import CompilerParams as _CompilerParams
from .attention import _repeat_kv

_NEG_INF = -1e30
_LANES = 128


# -- forward ------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale: float,
                      causal: bool, block_q: int, block_k: int,
                      with_lse: bool):
    # Outputs/scratch after o_ref: [lse_ref,] m_ref, l_ref, acc_ref. The lse
    # output exists only on the training path (with_lse) — forward-only
    # callers (serving) skip its HBM write entirely.
    lse_ref = rest[0] if with_lse else None
    m_ref, l_ref, acc_ref = rest[-3:]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: the whole block is masked when its lowest k position exceeds
    # the highest q position — skip the matmuls entirely.
    diag_reachable = (ki * block_k) <= (qi * block_q + block_q - 1)
    should_compute = diag_reachable if causal else True

    @pl.when(should_compute)
    def _update():
        q = q_ref[0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0].astype(jnp.float32)           # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:, :1]                       # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_k)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
        if with_lse:
            # lse rows broadcast across the 128 lanes (m/l scratch already
            # are), sidestepping a sublane→lane transpose the Mosaic
            # compiler dislikes.
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-20))


def _flash_forward(q3, k3, v3, causal, block_q, block_k, interpret,
                   with_lse=True):
    """[B·H, T, d] inputs → (out [B·H, T, d], lse [B·H, T, 128] f32 or
    None when with_lse=False — the forward-only path skips the write)."""
    bh, t, d = q3.shape
    grid = (bh, t // block_q, t // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=1.0 / math.sqrt(d), causal=causal,
        block_q=block_q, block_k=block_k, with_lse=with_lse,
    )
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0))
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q3.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, block_q, _LANES), lambda b, qi, ki: (b, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((bh, t, _LANES), jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return (out[0], out[1]) if with_lse else (out[0], None)


# -- backward -----------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
                         dq_acc, *, scale: float, causal: bool, block_q: int,
                         block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    diag_reachable = (ki * block_k) <= (qi * block_q + block_q - 1)
    should_compute = diag_reachable if causal else True

    @pl.when(should_compute)
    def _update():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)          # [bq, d]
        o = o_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                     # [bq, 1]
        delta = jnp.sum(do * o, axis=1, keepdims=True)  # D_i = do·o, [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [bq, bk]
        p = jnp.exp(s - lse)                        # normalized probs
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # [bq, bk]
        ds = p * (dp - delta)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == last_k)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref,
                          dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, block_q: int, block_k: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    last_q = pl.num_programs(2) - 1

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    diag_reachable = (ki * block_k) <= (qi * block_q + block_q - 1)
    should_compute = diag_reachable if causal else True

    @pl.when(should_compute)
    def _update():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = jnp.sum(do * o, axis=1, keepdims=True)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        # dv += pᵀ do — contract the q dim of both operands.
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == last_q)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q3, k3, v3, o3, lse, do3, causal, block_q, block_k,
                    interpret):
    """All [B·H, T, d] (+ lse [B·H, T, 128]) → (dq, dk, dv) in q3.dtype."""
    bh, t, d = q3.shape
    scale = 1.0 / math.sqrt(d)
    common = dict(
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    lse_spec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))

    # Kernel 1 — dq: grid (bh, q_blocks, kv_blocks), kv sweep innermost.
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, t // block_q, t // block_k),
        in_specs=[
            q_spec,                                                   # q
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),  # v
            q_spec,                                                   # do
            q_spec,                                                   # o
            lse_spec,                                                 # lse
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        **common,
    )(q3, k3, v3, do3, o3, lse)

    # Kernel 2 — dk/dv: grid (bh, kv_blocks, q_blocks), q sweep innermost.
    dkv_q_spec = pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, t // block_k, t // block_q),
        in_specs=[
            dkv_q_spec,                                               # q
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),  # k
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),  # v
            dkv_q_spec,                                               # do
            dkv_q_spec,                                               # o
            pl.BlockSpec((1, block_q, _LANES), lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        **common,
    )(q3, k3, v3, do3, o3, lse)
    return dq, dk, dv


# -- public API ---------------------------------------------------------------

def _bh(x):
    """[B, T, H, d] → [B·H, T, d]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unbh(x3, b, h):
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _resolve(t, block_q, block_k, interpret):
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} not divisible by blocks "
                         f"({block_q}/{block_k})")
    # Shared env/flag-driven toggle (ops.pallas_interpret — lazy import,
    # the package imports this module): interpret off-TPU or when
    # TPU_SCHED_PALLAS_INTERPRET forces it, so tier-1 runs the kernels.
    from . import pallas_interpret
    return block_q, block_k, pallas_interpret(interpret)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in for dense_attention: q [B, T, H, d], k/v [B, T, Hkv, d] →
    [B, T, H, d]. T must divide by the block sizes (pad upstream or use
    dense for ragged tails). GQA kv heads are repeated to H."""
    b, t, n_heads, d = q.shape
    block_q, block_k, interpret = _resolve(t, block_q, block_k, interpret)
    k = _repeat_kv(k, n_heads)
    v = _repeat_kv(v, n_heads)
    out, _ = _flash_forward(_bh(q), _bh(k), _bh(v), causal, block_q, block_k,
                            interpret, with_lse=False)
    return _unbh(out, b, n_heads)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_diff(q, k, v, causal: bool = True, block_q: int = 256,
                         block_k: int = 512):
    """Differentiable flash attention: flash cost forward AND backward.
    Same signature contract as flash_attention (GQA supported; dk/dv are
    summed back over the repeated head groups)."""
    return flash_attention(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k)


def _fwd(q, k, v, causal, block_q, block_k):
    b, t, n_heads, d = q.shape
    bq, bk, interpret = _resolve(t, block_q, block_k, interpret=None)
    k_rep = _repeat_kv(k, n_heads)
    v_rep = _repeat_kv(v, n_heads)
    out3, lse = _flash_forward(_bh(q), _bh(k_rep), _bh(v_rep), causal, bq, bk,
                               interpret)
    out = _unbh(out3, b, n_heads)
    # Keep residuals lean: lse rows are identical across the 128 lanes the
    # kernel wrote, so only [:, :, :1] is saved (the backward re-broadcasts);
    # the output is saved once (the returned layout), not as a second copy.
    return out, (q, k, v, out, lse[:, :, :1])


def _shrink_to_divisor(block, t):
    """Cap a backward block at 256 but never break t-divisibility (the
    original block already passed _resolve's check)."""
    capped = min(block, 256)
    return capped if t % capped == 0 else block


def _bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse1 = res
    b, t, n_heads, d = q.shape
    h_kv = k.shape[2]
    bq, bk, interpret = _resolve(t, block_q, block_k, interpret=None)
    # Backward prefers square-ish ≤256 blocks: dkv keeps two [block_k, d]
    # f32 accumulators in VMEM on top of the six input blocks.
    bq = _shrink_to_divisor(bq, t)
    bk = _shrink_to_divisor(bk, t)
    lse = jnp.broadcast_to(lse1, (*lse1.shape[:2], _LANES))
    dq3, dk3, dv3 = _flash_backward(
        _bh(q), _bh(_repeat_kv(k, n_heads)), _bh(_repeat_kv(v, n_heads)),
        _bh(out), lse, _bh(g), causal, bq, bk, interpret,
    )
    dq = _unbh(dq3, b, n_heads)
    dk = _unbh(dk3, b, n_heads)
    dv = _unbh(dv3, b, n_heads)
    if h_kv != n_heads:
        # jnp.repeat(axis=2) lays groups out contiguously: sum them back.
        r = n_heads // h_kv
        dk = dk.reshape(b, t, h_kv, r, d).sum(axis=3).astype(k.dtype)
        dv = dv.reshape(b, t, h_kv, r, d).sum(axis=3).astype(v.dtype)
    return dq, dk, dv


flash_attention_diff.defvjp(_fwd, _bwd)
