"""Flash attention — a Pallas TPU kernel for the serving hot path.

Dense attention materializes the [T, T] score matrix in HBM; this kernel
streams K/V blocks through VMEM keeping flash-style running softmax stats
(m, l) in scratch, so memory is O(block² ) and the MXU sees back-to-back
[block_q, d]×[d, block_k] and [block_q, block_k]×[block_k, d] matmuls.

Grid = (batch·heads, q_blocks, kv_blocks), kv innermost and sequential
("arbitrary" semantics): scratch accumulators persist across the kv sweep,
reset at kv==0, normalized+written at the last kv block. Fully-masked
causal blocks are skipped with pl.when (≈2× fewer FLOPs at long T).

Forward-only: the training path keeps dense/ring attention (those
differentiate through XLA); flash serves inference (models.llama --serve,
BASELINE config 5) where the backward pass never runs. On CPU the wrapper
transparently uses interpret mode, so tests run hermetically.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: the whole block is masked when its lowest k position exceeds
    # the highest q position — skip the matmuls entirely.
    diag_reachable = (ki * block_k) <= (qi * block_q + block_q - 1)
    should_compute = diag_reachable if causal else True

    @pl.when(should_compute)
    def _update():
        q = q_ref[0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0].astype(jnp.float32)           # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:, :1]                       # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == last_k)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in for dense_attention: q [B, T, H, d], k/v [B, T, Hkv, d] →
    [B, T, H, d]. T must divide by the block sizes (pad upstream or use
    dense for ragged tails). GQA kv heads are repeated to H."""
    b, t, n_heads, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"seq len {t} not divisible by blocks "
                         f"({block_q}/{block_k})")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    h_kv = k.shape[2]
    if h_kv != n_heads:
        k = jnp.repeat(k, n_heads // h_kv, axis=2)
        v = jnp.repeat(v, n_heads // h_kv, axis=2)

    # [B, T, H, d] → [B·H, T, d]
    def bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * n_heads, t, d)

    q3, k3, v3 = bh(q), bh(k), bh(v)
    grid = (b * n_heads, t // block_q, t // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=1.0 / math.sqrt(d), causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * n_heads, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),       # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, n_heads, t, d).transpose(0, 2, 1, 3)


# -- differentiable wrapper ---------------------------------------------------
#
# Pallas kernels don't autodiff; training with attn_impl="flash" gets the
# flash FORWARD (O(block²) memory, the long-context win is in activations
# saved for remat) and a recompute-through-dense BACKWARD (exact gradients,
# dense-cost bwd). Serving uses flash_attention directly.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_diff(q, k, v, causal: bool = True):
    return flash_attention(q, k, v, causal=causal)


def _fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal), (q, k, v)


def _bwd(causal, res, g):
    from .attention import dense_attention

    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: dense_attention(q, k, v, causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention_diff.defvjp(_fwd, _bwd)
