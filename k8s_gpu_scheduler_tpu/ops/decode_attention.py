"""Fused flash-decode attention — Pallas TPU kernel for the serving engine.

Decode attention is the KV-cache read: one query token per sequence against
a [B, S, Hkv, hd] cache. The dense formulation (serving.py round 5) was
bandwidth-HONEST about the irreducible cache read but wasteful around it:

- `_repeat_kv` materialized an H/Hkv-times bf16 copy of K and V in HBM
  every emitted token (GQA groups re-read `g` times);
- the int8 cache was dequantized through full-width [B, S, H, hd] einsum
  operands instead of inside the read;
- the read was dense over the PREALLOCATED S rows, O(max_seq) regardless
  of how little of the cache a request has filled;
- the masked softmax round-tripped f32 score/prob planes through HBM
  (profiled in bench.py's long-context leg as the bulk of the 15x gap
  between measured step time and theoretical cache-read time).

This kernel is the Flash-Decoding / vLLM-TPU shape instead:

- **grid (batch x kv_head, split, kv_block)**: each program streams its
  kv blocks through VMEM once, keeping flash-style running (m, l, acc)
  stats in scratch — scores never exist in HBM;
- **in-kernel GQA**: the query block is the whole [g = H/Hkv, hd] head
  group served by this kv head, so each cache row is read ONCE and the
  MXU contracts it against all g query heads — no repeated copy;
- **fused int8-KV dequant**: K/V blocks are DMA'd as int8 (plus the f32
  per-row scale plane from serving._kv_quant) and dequantized in
  registers after the VMEM load — HBM traffic stays int8;
- **traced length mask**: `lengths` rides as a scalar-prefetch operand;
  blocks past a sequence's filled prefix are compute-skipped with
  `pl.when` AND their BlockSpec index maps clamp to the last valid block,
  so the pipeline re-visits a resident block instead of streaming dead
  rows — cache traffic is O(pos), not O(max_seq);
- **split-K + log-sum-exp combine**: the sequence is cut into `n_splits`
  independent sweeps (parallel grid dim) whose partial (acc, m, l) are
  combined outside the kernel with the standard LSE merge — long contexts
  expose parallelism beyond B x Hkv cores.

`dense_decode_reference` is the grouped-einsum dense formulation of the
SAME contract (no `_repeat_kv` materialization either) — the numerical
reference the kernel is tested against and the automatic fallback for
shapes the blocking cannot cover. Both run under `JAX_PLATFORMS=cpu` via
interpret mode (the shared `ops.pallas_interpret` toggle), so tier-1
exercises the kernel hermetically.

**Paged variant** (`paged_decode_attention`): the same kernel body over a
vLLM-style paged cache — K/V live in a shared pool of fixed-size pages
`[n_pages, page_size, Hkv, hd]` and each sequence names its pages through
a `[B, n_blocks]` BLOCK TABLE that rides as a second scalar-prefetch
operand. The kv-block grid axis is indirected through the table in the
BlockSpec index maps (`block_table[b, j]` instead of `j`); the kernel
body is untouched because the online-softmax math only ever sees LOGICAL
block coordinates. Everything else carries over: O(pos) traffic via the
traced length mask with clamped index maps, in-kernel GQA, int8-KV
dequant in registers, split-K + LSE combine. `gather_paged_kv` is the
indirection as a dense gather — the reference/fallback path.

**Multi-query verify variant** (`paged_verify_attention`): the same paged
kernel body with a q block of ``t = 1+gamma`` rows per slot — the verify
window of speculative decoding (Leviathan et al. 2023; prompt-lookup
proposals in models/serving.py). Window row i sits at absolute position
``lengths[b] + i`` and attends the committed prefix plus the window
causally: cols < ``lengths[b] + i + 1``, a PER-ROW length mask instead of
the decode kernel's per-slot scalar. Everything else is unchanged —
block-table indirection in the index maps, O(pos) traffic via clamping
past ``lengths + t``, in-kernel GQA (the q block is the whole [t·g, hd]
row stack, so one cache read feeds every window row of every head in the
group), int8 dequant in registers, split-K + LSE combine.
``dense_verify_reference`` is the grouped-einsum formulation of the same
contract — numerical reference and automatic fallback.

**Prefix-attention prefill variant** (`paged_prefill_attention`): the
same paged kernel body generalized from the 1+gamma verify window to the
tb-bucket PREFILL TAIL — the hb>0 rung of the serving engine's
prefix-cache tail prefill (models/serving._prefill_multi_paged_fn). The
q block is the tail's tb query rows (at rope offset ``hit_lens``); the
kv grid axis streams TWO regimes: first the shared cached prefix,
page-indirected through ``prefix_table`` exactly like the decode/verify
kernels (int8 dequant in registers — the tail attends the SAME
dequantized bytes decode attends), then the tail's own K/V riding as a
dense [M, tb, Hkv, hd] operand (exact dtype — the rows this dispatch is
about to scatter into the pool, not yet resident). The mask is
two-regime: prefix columns fully visible below ``hit_lens``; tail
columns per-row causal (tail col j visible to tail query i iff j <= i).
This replaces the dense O(hit_len) HBM gather
(``pool[:, prefix_tables]`` → [L, M, hb·ps, Hkv, hd], dequantized to a
full-dtype buffer) with blockwise O(hit+tail) streaming — the gather
grew linearly with exactly the cache hits the prefix cache exists to
maximize. ``dense_prefill_reference`` is the gather+einsum formulation
of the same contract — numerical reference and automatic fallback
(``prefill_plan`` gates rungs whose tb·g q-row stack would overflow
VMEM; see analysis/vmem.py paged_prefill_attention_footprint).
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas import CompilerParams as _CompilerParams

_NEG_INF = -1e30
_LANES = 128


def decode_plan(s: int, block_k: Optional[int] = None,
                n_splits: Optional[int] = None) -> Optional[Tuple[int, int]]:
    """Legal (block_k, n_splits) for a cache of S rows, or None when no
    power-of-two block divides S (the caller falls back to the dense
    reference — raggedness lives in the length mask, so only the ALLOCATED
    S must divide). Splits engage at >= 8 blocks: below that the extra
    partial outputs cost more than the parallelism buys."""
    if block_k is None:
        for cand in (256, 128, 64, 32, 16, 8):
            if s % cand == 0:
                block_k = cand
                break
        else:
            return None
    elif s % block_k:
        return None
    n_blocks = s // block_k
    if n_splits is None:
        n_splits = 1
        if n_blocks >= 8:
            for cand in (8, 4, 2):
                if n_blocks % cand == 0:
                    n_splits = cand
                    break
    elif n_blocks % n_splits:
        return None
    return block_k, n_splits


DEFAULT_PAGE_SIZE = 64


def paged_plan(n_blocks: int, page_size: int,
               n_splits: Optional[int] = None) -> Optional[int]:
    """Legal split count for a paged cache of ``n_blocks`` logical pages of
    ``page_size`` rows each, or None when the shape is not pageable: the
    page IS the kv block, so it must be one of the power-of-two block
    sizes the kernel's tiling supports (8..256 — the same legal set as
    ``decode_plan``). Splits engage at >= 8 blocks, like the contiguous
    plan."""
    if page_size < 8 or page_size > 256 or page_size & (page_size - 1):
        return None
    if n_blocks < 1:
        return None
    if n_splits is None:
        n_splits = 1
        if n_blocks >= 8:
            for cand in (8, 4, 2):
                if n_blocks % cand == 0:
                    n_splits = cand
                    break
        return n_splits
    if n_blocks % n_splits:
        return None
    return n_splits


def verify_plan(n_blocks: int, page_size: int, t: int,
                n_splits: Optional[int] = None) -> Optional[int]:
    """Legal split count for a multi-query verify window of ``t`` rows
    over a paged cache, or None when not coverable. The kv side is
    exactly ``paged_plan`` (the page is the kv block); the q side only
    needs t >= 1 — the window rides as extra q rows, not extra grid, so
    it never changes the blocking. VMEM headroom for large t·g row
    stacks is the budgeter's contract (analysis/vmem.py
    paged_verify_attention_footprint), not a plan gate."""
    if t < 1:
        return None
    return paged_plan(n_blocks, page_size, n_splits)


def gather_paged_kv(pages: jax.Array, block_table: jax.Array) -> jax.Array:
    """Materialize a sequence-contiguous view of a paged pool: pages
    [n_pages, page_size, ...] gathered through block_table [B, n_blocks]
    → [B, n_blocks*page_size, ...]. The dense reference/fallback path —
    O(allocated S) traffic per call, exactly what the paged kernel's
    table-indirected index maps avoid."""
    g = pages[block_table]                       # [B, n_blocks, ps, ...]
    b, n_blocks, ps = g.shape[:3]
    return g.reshape(b, n_blocks * ps, *pages.shape[2:])


def _mask_from(lengths, bitmap, s):
    cols = jnp.arange(s)[None, :]                        # [1, S]
    mask = None
    if lengths is not None:
        mask = cols < jnp.asarray(lengths, jnp.int32)[:, None]
    if bitmap is not None:
        mask = bitmap if mask is None else jnp.logical_and(mask, bitmap)
    return mask


def dense_decode_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                           lengths=None, k_scale=None, v_scale=None,
                           bitmap=None) -> jax.Array:
    """Grouped-einsum dense decode attention: q [B, H, hd] against the full
    cache [B, S, Hkv, hd] → [B, H, hd]. GQA contracts through a [B, Hkv,
    g, ...] head-group axis — no `_repeat_kv` copy. int8-KV mode
    (`k_scale`/`v_scale` [B, S, Hkv, 1] from serving._kv_quant) factors
    the per-row scales out of the contractions — scores scale by k's rows,
    probs by v's — so dequant work is O(S), not O(S·hd), and the int8→
    dtype convert fuses into the einsum's cache read. Masking: `lengths`
    [B] keeps rows < length, `bitmap` [B, S] keeps set rows; both given =
    AND. A fully-masked row softmaxes uniform (garbage — callers only mask
    everything for slots whose output is never read)."""
    b, n_heads, hd = q.shape
    s, h_kv = k.shape[1], k.shape[2]
    if n_heads % h_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({h_kv})")
    g = n_heads // h_kv
    quant = k_scale is not None
    scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(b, h_kv, g, hd)
    kf = k.astype(q.dtype) if quant else k
    scores = jnp.einsum("bhgd,bkhd->bhgk", qg, kf).astype(jnp.float32) * scale
    if quant:
        # [B, S, Hkv, 1] -> [B, Hkv, 1, S]: constant along hd, so it
        # factors out of the contraction onto the scores.
        scores = scores * jnp.transpose(k_scale[..., 0], (0, 2, 1))[:, :, None]
    mask = _mask_from(lengths, bitmap, s)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if quant:
        probs = probs * jnp.transpose(
            v_scale[..., 0], (0, 2, 1))[:, :, None].astype(q.dtype)
        vf = v.astype(q.dtype)
    else:
        vf = v
    out = jnp.einsum("bhgk,bkhd->bhgd", probs, vf)
    return out.reshape(b, n_heads, hd)


def dense_verify_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                           lengths, k_scale=None, v_scale=None) -> jax.Array:
    """Grouped-einsum multi-query verify attention: the t-row window
    q [B, t, H, hd] against the cache [B, S, Hkv, hd] → [B, t, H, hd].

    ``lengths`` (scalar or [B] int32) counts the COMMITTED rows — the
    filled prefix BEFORE the window; the window's own K/V must already
    sit at rows lengths..lengths+t-1 (the serving verify pass writes them
    first). Window row i attends cols < lengths + i + 1: the committed
    prefix plus itself and earlier window rows — causal inside the
    window. GQA/int8 factoring matches ``dense_decode_reference``
    (grouped head axis, per-row scales on scores/probs); at t == 1 this
    is exactly ``dense_decode_reference`` with ``lengths + 1``."""
    b, t, n_heads, hd = q.shape
    s, h_kv = k.shape[1], k.shape[2]
    if n_heads % h_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({h_kv})")
    g = n_heads // h_kv
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(b, t, h_kv, g, hd)
    kf = k.astype(q.dtype) if quant else k
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, kf).astype(jnp.float32) * scale
    if quant:
        scores = scores * jnp.transpose(
            k_scale[..., 0], (0, 2, 1))[:, :, None, None, :]
    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.full((b,), lengths, jnp.int32)
    bound = lengths[:, None] + jnp.arange(t)[None, :] + 1      # [B, t]
    mask = jnp.arange(s)[None, None, :] < bound[..., None]     # [B, t, S]
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if quant:
        probs = probs * jnp.transpose(
            v_scale[..., 0], (0, 2, 1))[:, :, None, None, :].astype(q.dtype)
        vf = v.astype(q.dtype)
    else:
        vf = v
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
    return out.reshape(b, t, n_heads, hd)


# -- kernel -------------------------------------------------------------------

def _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, *rest, scale: float,
                   block_k: int, n_kv: int, bps: int, quant: bool,
                   with_bitmap: bool):
    if quant:
        ks_ref, vs_ref, *rest = rest
    if with_bitmap:
        bm_ref, *rest = rest
    o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref = rest

    bh = pl.program_id(0)
    split = pl.program_id(1)
    j = pl.program_id(2)
    b = bh // n_kv
    blk = split * bps + j                      # UNclamped global kv block
    length = lengths_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks entirely past the filled prefix: compute skipped here, DMA
    # skipped by the clamped index maps (they re-name the last valid block,
    # which the pipeline recognizes as already resident).
    @pl.when(blk * block_k < length)
    def _update():
        q = q_ref[0].astype(jnp.float32)                   # [g, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # [bk, hd]
        if quant:
            k = k * ks_ref[0, :, 0, :]                     # dequant in regs
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                          # [g, bk]
        col = blk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = col < length                                # [1, bk]
        if with_bitmap:
            mask = jnp.logical_and(mask, bm_ref[:] != 0)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]                              # [g, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero at masked columns: a bitmap-empty block leaves
        # m_new at -inf and exp(s - m_new) == 1 everywhere without it.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)       # [g, bk]
        alpha = jnp.exp(m_prev - m_new)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            v = v * vs_ref[0, :, 0, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        # UNNORMALIZED partials: the split-K combine outside the kernel
        # does the single LSE-weighted normalization.
        o_ref[0, 0] = acc_ref[:]
        mo_ref[0, 0] = m_ref[:]
        lo_ref[0, 0] = l_ref[:]


def flash_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    bitmap: Optional[jax.Array] = None,
    block_k: Optional[int] = None,
    n_splits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused flash-decode attention: q [B, H, hd] (one decode step) against
    the cache k/v [B, S, Hkv, hd] → [B, H, hd].

    ``lengths`` (scalar or [B] int32, REQUIRED): rows < length are
    attendable; blocks past it are skipped (compute AND traffic), so the
    step costs O(pos). ``k_scale``/``v_scale`` [B, S, Hkv, 1] switch the
    cache operands to int8-KV mode (serving._kv_quant layout). ``bitmap``
    [B, S] bool refines the length mask to exactly the valid rows (the
    ContinuousBatcher's slot-window validity map); its set bits must lie
    below ``lengths``. Raises ValueError when ``decode_plan`` has no legal
    blocking for S — callers that want silent degradation check the plan
    first and fall back to ``dense_decode_reference``."""
    b, n_heads, hd = q.shape
    if k.shape[0] != b or k.shape[3] != hd or v.shape != k.shape:
        raise ValueError(f"cache shape {k.shape}/{v.shape} does not match "
                         f"q {q.shape}")
    s, n_kv = k.shape[1], k.shape[2]
    if n_heads % n_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({n_kv})")
    g = n_heads // n_kv
    plan = decode_plan(s, block_k, n_splits)
    if plan is None:
        raise ValueError(f"no legal decode blocking for S={s} "
                         f"(block_k={block_k}, n_splits={n_splits})")
    block_k, n_splits = plan
    bps = s // block_k // n_splits
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    from . import pallas_interpret
    interpret = pallas_interpret(interpret)

    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.full((b,), lengths, jnp.int32)
    # [B, H, hd] with H = Hkv*g laid out group-major (matches _repeat_kv's
    # jnp.repeat ordering) → fold (B, Hkv) into the grid axis.
    q3 = q.reshape(b * n_kv, g, hd)

    def kv_map(bh, split, j, lens):
        bb = bh // n_kv
        blk = split * bps + j
        last = jnp.maximum(
            jax.lax.div(lens[bb] + block_k - 1, block_k) - 1, 0)
        return (bb, jnp.minimum(blk, last), bh % n_kv, 0)

    def bm_map(bh, split, j, lens):
        bb = bh // n_kv
        blk = split * bps + j
        last = jnp.maximum(
            jax.lax.div(lens[bb] + block_k - 1, block_k) - 1, 0)
        return (bb, jnp.minimum(blk, last))

    kv_spec = pl.BlockSpec((1, block_k, 1, hd), kv_map)
    in_specs = [
        pl.BlockSpec((1, g, hd), lambda bh, split, j, lens: (bh, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    inputs = [q3, k, v]
    if quant:
        sc_spec = pl.BlockSpec((1, block_k, 1, 1), kv_map)
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    if bitmap is not None:
        in_specs.append(pl.BlockSpec((1, block_k), bm_map))
        inputs.append(bitmap.astype(jnp.int8))

    part_spec = lambda lanes: pl.BlockSpec(                      # noqa: E731
        (1, 1, g, lanes), lambda bh, split, j, lens: (bh, split, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * n_kv, n_splits, bps),
        in_specs=in_specs,
        out_specs=[part_spec(hd), part_spec(_LANES), part_spec(_LANES)],
        scratch_shapes=[
            pltpu.VMEM((g, hd), jnp.float32),     # acc
            pltpu.VMEM((g, _LANES), jnp.float32),  # m
            pltpu.VMEM((g, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=1.0 / math.sqrt(hd), block_k=block_k,
        n_kv=n_kv, bps=bps, quant=quant, with_bitmap=bitmap is not None)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, _LANES),
                                 jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths, *inputs)

    return _combine_splits(acc, m, l, b, n_heads, hd, q.dtype)


def _combine_splits(acc, m, l, b, n_heads, hd, dtype):
    """Split-K combine: standard LSE merge of the per-split partials. An
    all-masked split contributes (acc=0, m=-inf, l=0) and drops out; a
    fully-masked ROW (length 0 / empty bitmap) yields zeros, unlike the
    dense reference's uniform softmax — both are garbage by contract."""
    m1, l1 = m[..., :1], l[..., :1]                  # [BH, ns, g, 1]
    m_tot = jnp.max(m1, axis=1, keepdims=True)
    w = jnp.exp(m1 - m_tot)
    l_tot = jnp.sum(l1 * w, axis=1)                  # [BH, g, 1]
    out = jnp.sum(acc * w, axis=1) / jnp.maximum(l_tot, 1e-20)
    return out.reshape(b, n_heads, hd).astype(dtype)


def _paged_kernel(lengths_ref, table_ref, *rest, **kw):
    """The paged entry's kernel body IS `_decode_kernel`: the block table
    only exists in the BlockSpec index maps (physical page naming); the
    online-softmax math sees logical block coordinates either way."""
    del table_ref
    _decode_kernel(lengths_ref, *rest, **kw)


def paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_table: jax.Array,
    lengths,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    n_splits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused flash-decode attention over a PAGED KV cache: q [B, H, hd]
    against a shared page pool k/v [n_pages, page_size, Hkv, hd], each
    sequence's pages named by ``block_table`` [B, n_blocks] int32 (logical
    block j of sequence b lives in physical page ``block_table[b, j]``).

    The table rides as a second scalar-prefetch operand and is consumed
    ONLY by the BlockSpec index maps — logical block blk streams page
    ``table[b, blk]`` through VMEM, so the pipeline reads exactly the
    pages a sequence owns, in logical order, with no contiguity
    requirement on the pool. ``lengths`` (scalar or [B] int32) bounds the
    filled LOGICAL prefix exactly as in ``flash_decode_attention``: blocks
    past it are compute-skipped and their index maps clamp to the last
    valid block (re-naming a resident page — no dead DMA), so traffic is
    O(pos). ``k_scale``/``v_scale`` [n_pages, page_size, Hkv, 1] switch to
    int8-KV mode (serving._kv_quant layout, dequant in registers). Rows
    past ``lengths`` inside the last page may be garbage (stale pages from
    a freed request) — they are masked, never contributing.

    Raises ValueError when (n_blocks, page_size) has no legal paged plan —
    callers that want silent degradation check ``paged_plan`` first and
    fall back to ``gather_paged_kv`` + ``dense_decode_reference``."""
    b, n_heads, hd = q.shape
    if k_pages.shape[3] != hd or v_pages.shape != k_pages.shape:
        raise ValueError(f"page pool shape {k_pages.shape}/{v_pages.shape} "
                         f"does not match q {q.shape}")
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(f"block_table must be [B={b}, n_blocks], got "
                         f"{block_table.shape}")
    ps, n_kv = k_pages.shape[1], k_pages.shape[2]
    n_blocks = block_table.shape[1]
    if n_heads % n_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({n_kv})")
    g = n_heads // n_kv
    n_splits = paged_plan(n_blocks, ps, n_splits)
    if n_splits is None:
        raise ValueError(f"no legal paged blocking for n_blocks={n_blocks}, "
                         f"page_size={ps}")
    bps = n_blocks // n_splits
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    from . import pallas_interpret
    interpret = pallas_interpret(interpret)

    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.full((b,), lengths, jnp.int32)
    block_table = jnp.asarray(block_table, jnp.int32)
    q3 = q.reshape(b * n_kv, g, hd)

    def kv_map(bh, split, j, lens, table):
        bb = bh // n_kv
        blk = split * bps + j                        # LOGICAL kv block
        last = jnp.maximum(
            jax.lax.div(lens[bb] + ps - 1, ps) - 1, 0)
        # The table indirection: the physical page named for this logical
        # block (clamped past the filled prefix, like the contiguous map).
        return (table[bb, jnp.minimum(blk, last)], 0, bh % n_kv, 0)

    kv_spec = pl.BlockSpec((1, ps, 1, hd), kv_map)
    in_specs = [
        pl.BlockSpec((1, g, hd), lambda bh, split, j, lens, table: (bh, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    inputs = [q3, k_pages, v_pages]
    if quant:
        sc_spec = pl.BlockSpec((1, ps, 1, 1), kv_map)
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    part_spec = lambda lanes: pl.BlockSpec(                      # noqa: E731
        (1, 1, g, lanes),
        lambda bh, split, j, lens, table: (bh, split, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * n_kv, n_splits, bps),
        in_specs=in_specs,
        out_specs=[part_spec(hd), part_spec(_LANES), part_spec(_LANES)],
        scratch_shapes=[
            pltpu.VMEM((g, hd), jnp.float32),     # acc
            pltpu.VMEM((g, _LANES), jnp.float32),  # m
            pltpu.VMEM((g, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _paged_kernel, scale=1.0 / math.sqrt(hd), block_k=ps,
        n_kv=n_kv, bps=bps, quant=quant, with_bitmap=False)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, g, _LANES),
                                 jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths, block_table, *inputs)
    return _combine_splits(acc, m, l, b, n_heads, hd, q.dtype)


# -- multi-query verify kernel ------------------------------------------------

def _verify_kernel(lengths_ref, table_ref, q_ref, k_ref, v_ref, *rest,
                   scale: float, block_k: int, n_kv: int, bps: int,
                   quant: bool, t: int, g: int):
    """Multi-query body: the q block is the whole [t·g, hd] row stack of
    one slot's verify window for one kv head group (row i·g+j = window
    token i, group head j). The only change from `_decode_kernel` is the
    PER-ROW mask — window token i attends cols < base + i + 1 — and the
    skip bound growing by t; the online-softmax math is row-independent
    either way, so each window row accumulates exactly what the t = 1
    kernel would at its own length bound."""
    del table_ref                # consumed by the BlockSpec index maps only
    if quant:
        ks_ref, vs_ref, *rest = rest
    o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref = rest

    bh = pl.program_id(0)
    j = pl.program_id(2)
    split = pl.program_id(1)
    b = bh // n_kv
    blk = split * bps + j                      # UNclamped LOGICAL kv block
    base = lengths_ref[b]                      # committed rows pre-window

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks entirely past the furthest row ANY window token may attend
    # (base + t): compute skipped, DMA skipped by the clamped index maps.
    @pl.when(blk * block_k < base + t)
    def _update():
        q = q_ref[0].astype(jnp.float32)                   # [t*g, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # [bk, hd]
        if quant:
            k = k * ks_ref[0, :, 0, :]                     # dequant in regs
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                          # [t*g, bk]
        col = blk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (t * g, block_k), 1)
        row_tok = jax.lax.broadcasted_iota(
            jnp.int32, (t * g, block_k), 0) // g           # window token idx
        mask = col < base + row_tok + 1                    # [t*g, bk]
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]                              # [t*g, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero at masked columns: a row whose window hasn't
        # reached this block yet leaves m_new at -inf and exp(s - m_new)
        # == 1 everywhere without it.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)       # [t*g, bk]
        alpha = jnp.exp(m_prev - m_new)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            v = v * vs_ref[0, :, 0, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0, 0] = acc_ref[:]
        mo_ref[0, 0] = m_ref[:]
        lo_ref[0, 0] = l_ref[:]


def paged_verify_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_table: jax.Array,
    lengths,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    n_splits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused multi-query verify attention over a PAGED KV cache: the
    speculative verify window q [B, t, H, hd] (t = 1+gamma) against the
    page pool k/v [n_pages, page_size, Hkv, hd] through ``block_table``
    [B, n_blocks] — one batched dispatch verifies every slot's window.

    ``lengths`` (scalar or [B] int32) counts the COMMITTED rows — the
    filled logical prefix BEFORE the window. The window's own K/V must
    already sit at logical rows lengths..lengths+t-1 of each slot (the
    serving verify pass scatters them before attending, exactly like the
    decode step writes its row first). Window row i attends cols <
    lengths + i + 1 — committed prefix plus the window causally — via a
    per-row mask inside the kernel; blocks past lengths + t are
    compute-skipped with index maps clamped to the last valid block, so
    traffic stays O(pos). Rows above each row's bound may be garbage
    (rejected overshoot of a previous verify, stale pages) — they are
    masked, never contributing. At t == 1 this is ``paged_decode_
    attention`` with ``lengths + 1`` exactly (same body, scalar mask).

    ``k_scale``/``v_scale`` [n_pages, page_size, Hkv, 1] switch to
    int8-KV mode. Raises ValueError when ``verify_plan`` has no legal
    covering — callers that want silent degradation check the plan first
    and fall back to ``gather_paged_kv`` + ``dense_verify_reference``."""
    b, t, n_heads, hd = q.shape
    if k_pages.shape[3] != hd or v_pages.shape != k_pages.shape:
        raise ValueError(f"page pool shape {k_pages.shape}/{v_pages.shape} "
                         f"does not match q {q.shape}")
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(f"block_table must be [B={b}, n_blocks], got "
                         f"{block_table.shape}")
    ps, n_kv = k_pages.shape[1], k_pages.shape[2]
    n_blocks = block_table.shape[1]
    if n_heads % n_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({n_kv})")
    g = n_heads // n_kv
    n_splits = verify_plan(n_blocks, ps, t, n_splits)
    if n_splits is None:
        raise ValueError(f"no legal verify blocking for n_blocks={n_blocks},"
                         f" page_size={ps}, t={t}")
    bps = n_blocks // n_splits
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    from . import pallas_interpret
    interpret = pallas_interpret(interpret)

    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.full((b,), lengths, jnp.int32)
    block_table = jnp.asarray(block_table, jnp.int32)
    # [B, t, H, hd] → [B·Hkv, t·g, hd]: fold (B, Hkv) into the grid axis
    # and stack the window rows of one head GROUP — each streamed cache
    # row feeds all t·g q rows through one MXU contraction.
    q4 = q.reshape(b, t, n_kv, g, hd).transpose(0, 2, 1, 3, 4) \
        .reshape(b * n_kv, t * g, hd)

    def kv_map(bh, split, j, lens, table):
        bb = bh // n_kv
        blk = split * bps + j                        # LOGICAL kv block
        # The furthest attendable row is lens + t - 1 (the window's own
        # last row), so clamp past ceil((lens + t)/ps) — the verify-window
        # analog of the decode map's lens bound.
        last = jnp.maximum(
            jax.lax.div(lens[bb] + t + ps - 1, ps) - 1, 0)
        return (table[bb, jnp.minimum(blk, last)], 0, bh % n_kv, 0)

    kv_spec = pl.BlockSpec((1, ps, 1, hd), kv_map)
    in_specs = [
        pl.BlockSpec((1, t * g, hd),
                     lambda bh, split, j, lens, table: (bh, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    inputs = [q4, k_pages, v_pages]
    if quant:
        sc_spec = pl.BlockSpec((1, ps, 1, 1), kv_map)
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    part_spec = lambda lanes: pl.BlockSpec(                      # noqa: E731
        (1, 1, t * g, lanes),
        lambda bh, split, j, lens, table: (bh, split, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * n_kv, n_splits, bps),
        in_specs=in_specs,
        out_specs=[part_spec(hd), part_spec(_LANES), part_spec(_LANES)],
        scratch_shapes=[
            pltpu.VMEM((t * g, hd), jnp.float32),     # acc
            pltpu.VMEM((t * g, _LANES), jnp.float32),  # m
            pltpu.VMEM((t * g, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _verify_kernel, scale=1.0 / math.sqrt(hd), block_k=ps,
        n_kv=n_kv, bps=bps, quant=quant, t=t, g=g)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * n_kv, n_splits, t * g, hd),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, t * g, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, n_splits, t * g, _LANES),
                                 jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths, block_table, *inputs)
    # _combine_splits' "head" axis is just the per-program row count; undo
    # the (Hkv, t, g) fold back to window-major [B, t, H, hd].
    out = _combine_splits(acc, m, l, b, n_kv * t * g, hd, q.dtype)
    return out.reshape(b, n_kv, t, g, hd).transpose(0, 2, 1, 3, 4) \
        .reshape(b, t, n_heads, hd)


# -- prefix-attention prefill kernel ------------------------------------------

# Cap on the q-row stack (tb tail rows x g group heads) one prefill
# program may carry: beyond it the [rows, hd] q block, three [rows, *]
# partial outputs and the (acc, m, l) scratch brush the 16 MiB/core VMEM
# budget on the large presets (the precise per-preset accounting is
# analysis/vmem.py paged_prefill_attention_footprint — this is the
# coarse runtime gate; rungs past it fall back to the dense gather,
# counted). Production long prompts ride chunked prefill, whose chunk
# buckets sit far below the cap.
PREFILL_MAX_Q_ROWS = 2048


def prefill_plan(n_blocks: int, page_size: int, rows: int,
                 n_splits: Optional[int] = None) -> Optional[int]:
    """Legal split count for a prefix-attention prefill of ``rows`` q
    rows (tb tail tokens x g group heads) over ``n_blocks`` logical kv
    blocks (prefix pages ++ tail pages, each ``page_size`` rows), or
    None when not coverable: the kv side is exactly ``paged_plan`` (the
    page is the kv block); the q side is capped at ``PREFILL_MAX_Q_ROWS``
    — the VMEM wall the multi-row q stack hits long before the kv
    traffic does."""
    if rows < 1 or rows > PREFILL_MAX_Q_ROWS:
        return None
    return paged_plan(n_blocks, page_size, n_splits)


def dense_prefill_reference(q: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, prefix_table: jax.Array,
                            hit_lens, tail_k: jax.Array, tail_v: jax.Array,
                            k_scale=None, v_scale=None) -> jax.Array:
    """Gather+einsum prefix-attention prefill: the tail window q
    [M, tb, H, hd] against [the cached prefix gathered from the page
    pool through ``prefix_table`` [M, hb]] ++ [the tail's own K/V
    [M, tb, Hkv, hd]] → [M, tb, H, hd].

    ``hit_lens`` (scalar or [M] int32) counts each entry's cached
    prefix rows (page-aligned, <= hb·page_size; ``prefix_table`` may be
    null-padded past them). Prefix column c is visible iff c < hit_len
    — fully visible, no causal order (the whole prefix precedes every
    tail query); tail column j is visible to tail query i iff j <= i —
    causal inside the window. Tail query i sits at absolute position
    hit_len + i; rope must already be applied to q and tail_k at those
    offsets (this function only contracts). int8-KV mode
    (``k_scale``/``v_scale`` [n_pages, ps, Hkv, 1]) dequantizes the
    GATHERED prefix only — the tail K/V are the exact-dtype rows this
    dispatch computes, the same asymmetry the serving gather path has
    always had (its parity note in models/serving.py). This is the
    materializing formulation the kernel replaces: the numerical
    reference and the automatic fallback."""
    m, tb, n_heads, hd = q.shape
    ps, h_kv = k_pages.shape[1], k_pages.shape[2]
    if n_heads % h_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({h_kv})")
    hb = prefix_table.shape[1]
    hp = hb * ps
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    hit_lens = jnp.asarray(hit_lens, jnp.int32)
    if hit_lens.ndim == 0:
        hit_lens = jnp.full((m,), hit_lens, jnp.int32)

    def gather(pool):
        return pool[prefix_table].reshape(m, hp, *pool.shape[2:])

    if quant:
        pk = (gather(k_pages).astype(jnp.float32)
              * gather(k_scale)).astype(q.dtype)
        pv = (gather(v_pages).astype(jnp.float32)
              * gather(v_scale)).astype(q.dtype)
    else:
        pk, pv = gather(k_pages), gather(v_pages)
    kf = jnp.concatenate([pk, tail_k], axis=1)       # [M, hp+tb, Hkv, hd]
    vf = jnp.concatenate([pv, tail_v], axis=1)
    kcol = jnp.arange(hp + tb)[None, None, :]
    valid = jnp.where(
        kcol < hp, kcol < hit_lens[:, None, None],
        (kcol - hp) <= jnp.arange(tb)[None, :, None])  # [M, tb, hp+tb]
    g = n_heads // h_kv
    scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(m, tb, h_kv, g, hd)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, kf).astype(jnp.float32) * scale
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
    return out.reshape(m, tb, n_heads, hd)


def _prefill_kernel(hit_lens_ref, table_ref, q_ref, pk_ref, pv_ref,
                    tk_ref, tv_ref, *rest, scale: float, ps: int,
                    n_kv: int, bps: int, hb: int, quant: bool, tb: int,
                    g: int):
    """Prefix-attention prefill body: the q block is one slot's whole
    [tb·g, hd] tail-row stack for one kv head group (row i·g+j = tail
    token i, group head j — the verify kernel's fold at t = tb). The
    logical kv axis has TWO regimes split at the static block index
    ``hb``: blocks < hb stream cached prefix pages through the table
    indirection (int8 dequant in registers, mask col < hit_len — fully
    visible, no causal order); blocks >= hb stream the tail's own dense
    K/V (exact dtype, per-row causal mask tail-col <= tail-row). Both
    regimes feed the SAME online-softmax update, so each tail row
    accumulates exactly what the dense two-regime mask admits."""
    del table_ref                # consumed by the BlockSpec index maps only
    if quant:
        pks_ref, pvs_ref, *rest = rest
    o_ref, mo_ref, lo_ref, acc_ref, m_ref, l_ref = rest

    bh = pl.program_id(0)
    split = pl.program_id(1)
    j = pl.program_id(2)
    b = bh // n_kv
    blk = split * bps + j                      # UNclamped LOGICAL kv block
    hit = hit_lens_ref[b]                      # cached prefix rows

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def accum(kb, vb, mask):
        # One flash update with this block's [ps, hd] K/V under ``mask``
        # [rows-or-1, ps] — shared verbatim by both regimes, so the
        # running (m, l, acc) stats cannot drift between them.
        q = q_ref[0].astype(jnp.float32)                   # [tb*g, hd]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                          # [tb*g, ps]
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]                              # [tb*g, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Explicit zero at masked columns: a block no row attends yet
        # leaves m_new at -inf and exp(s - m_new) == 1 without it.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    # Prefix regime: blocks past ceil(hit/ps) are compute-skipped (their
    # index maps clamp to the last valid prefix page — resident, no dead
    # DMA), the last partial page is column-masked.
    @pl.when(jnp.logical_and(blk < hb, blk * ps < hit))
    def _prefix_update():
        k = pk_ref[0, :, 0, :].astype(jnp.float32)         # [ps, hd]
        v = pv_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            k = k * pks_ref[0, :, 0, :]                    # dequant in regs
            v = v * pvs_ref[0, :, 0, :]
        col = blk * ps + jax.lax.broadcasted_iota(
            jnp.int32, (1, ps), 1)
        accum(k, v, col < hit)                             # fully visible

    # Tail regime: per-row causal inside the window. Every tail block is
    # live (the bucket's padded rows attend their own causal prefix and
    # are discarded by the caller), so no skip bound.
    @pl.when(blk >= hb)
    def _tail_update():
        k = tk_ref[0, 0, :, 0, :].astype(jnp.float32)      # [ps, hd]
        v = tv_ref[0, 0, :, 0, :].astype(jnp.float32)
        tcol = (blk - hb) * ps + jax.lax.broadcasted_iota(
            jnp.int32, (tb * g, ps), 1)                    # tail col idx
        trow = jax.lax.broadcasted_iota(
            jnp.int32, (tb * g, ps), 0) // g               # tail token idx
        accum(k, v, tcol <= trow)                          # causal window

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0, 0] = acc_ref[:]
        mo_ref[0, 0] = m_ref[:]
        lo_ref[0, 0] = l_ref[:]


def paged_prefill_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    prefix_table: jax.Array,
    hit_lens,
    tail_k: jax.Array,
    tail_v: jax.Array,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    n_splits: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused prefix-attention PREFILL over a paged KV cache: the tail
    window q [M, tb, H, hd] (tail query i at absolute position
    hit_len + i, rope already applied) against [each entry's cached
    prefix, streamed from the page pool k/v [n_pages, ps, Hkv, hd]
    through ``prefix_table`` [M, hb] int32] ++ [the tail's own K/V
    [M, tb, Hkv, hd], a dense operand — these rows are computed BY the
    prefill dispatch and are not in the pool yet]. One dispatch prefills
    every entry's tail — the hb>0 rung body of the serving engine's
    prefix-cache tail prefill.

    ``hit_lens`` (scalar or [M] int32) counts each entry's cached
    prefix rows; it must be <= hb·ps (``prefix_table`` may be
    null-padded past ceil(hit_len/ps) — those entries are never
    streamed: the prefix index maps clamp to the last valid page, and
    the mask bounds columns at hit_len). Prefix columns are FULLY
    visible below hit_len (the whole prefix precedes every tail query —
    no causal order, the decode kernels' length mask at a per-entry
    bound); tail columns are per-row causal (col j visible to query i
    iff j <= i — the verify kernel's in-kernel iota mask with the
    window grown to tb rows). ``tb`` must be a multiple of ps (the
    engine's buckets are page-quantized); padded tail rows beyond a
    real tail compute garbage the caller discards, exactly like the
    dense path's bucket padding.

    ``k_scale``/``v_scale`` [n_pages, ps, Hkv, 1] switch the POOL
    operands to int8-KV mode — the prefix is dequantized in registers
    (the same bytes decode attends); the tail K/V stay exact dtype,
    mirroring the gather path's asymmetry. hb == 0 (nothing cached) is
    the degenerate pure-causal window: internally one null prefix block
    rides masked-out so the program shape stays uniform.

    Raises ValueError when ``prefill_plan`` has no legal covering (tb·g
    q rows past PREFILL_MAX_Q_ROWS, or an unpageable shape) — callers
    that want silent degradation check the plan first and fall back to
    ``dense_prefill_reference``."""
    m, tb, n_heads, hd = q.shape
    if k_pages.shape[3] != hd or v_pages.shape != k_pages.shape:
        raise ValueError(f"page pool shape {k_pages.shape}/{v_pages.shape} "
                         f"does not match q {q.shape}")
    if prefix_table.ndim != 2 or prefix_table.shape[0] != m:
        raise ValueError(f"prefix_table must be [M={m}, hb], got "
                         f"{prefix_table.shape}")
    if tail_k.shape != (m, tb, k_pages.shape[2], hd) \
            or tail_v.shape != tail_k.shape:
        raise ValueError(f"tail K/V {tail_k.shape}/{tail_v.shape} must be "
                         f"[M={m}, tb={tb}, Hkv={k_pages.shape[2]}, "
                         f"hd={hd}]")
    ps, n_kv = k_pages.shape[1], k_pages.shape[2]
    if n_heads % n_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({n_kv})")
    if tb % ps:
        raise ValueError(f"tail bucket tb={tb} must be a multiple of the "
                         f"page size {ps}")
    g = n_heads // n_kv
    hb = prefix_table.shape[1]
    if hb == 0:
        # Degenerate pure-causal window: one null prefix block, fully
        # masked (hit_lens must be 0), keeps the two-regime program
        # shape without a second kernel body.
        prefix_table = jnp.zeros((m, 1), jnp.int32)
        hb = 1
    ntb = tb // ps
    n_blocks = hb + ntb
    n_splits = prefill_plan(n_blocks, ps, tb * g, n_splits)
    if n_splits is None:
        raise ValueError(f"no legal prefill blocking for hb={hb}, tb={tb}, "
                         f"page_size={ps}, g={g}")
    bps = n_blocks // n_splits
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("int8-KV mode needs both k_scale and v_scale")
    from . import pallas_interpret
    interpret = pallas_interpret(interpret)

    hit_lens = jnp.asarray(hit_lens, jnp.int32)
    if hit_lens.ndim == 0:
        hit_lens = jnp.full((m,), hit_lens, jnp.int32)
    prefix_table = jnp.asarray(prefix_table, jnp.int32)
    # [M, tb, H, hd] → [M·Hkv, tb·g, hd]: the verify kernel's fold at
    # t = tb — one cache sweep feeds every tail row of a head group.
    q4 = q.reshape(m, tb, n_kv, g, hd).transpose(0, 2, 1, 3, 4) \
        .reshape(m * n_kv, tb * g, hd)
    # Tail K/V blocked along tb: [M, ntb, ps, Hkv, hd] so a tail block
    # is addressable by its logical index like a page.
    tk5 = tail_k.reshape(m, ntb, ps, n_kv, hd)
    tv5 = tail_v.reshape(m, ntb, ps, n_kv, hd)

    def pool_map(bh, split, j, hits, table):
        bb = bh // n_kv
        blk = split * bps + j
        # Prefix regime naming: clamp into [0, hb) AND past the filled
        # prefix (ceil(hit/ps) pages) — tail-regime steps re-name the
        # last valid prefix page, which is resident: no dead DMA.
        last = jnp.maximum(jax.lax.div(hits[bb] + ps - 1, ps) - 1, 0)
        pblk = jnp.minimum(jnp.minimum(blk, hb - 1), last)
        return (table[bb, pblk], 0, bh % n_kv, 0)

    def tail_map(bh, split, j, hits, table):
        bb = bh // n_kv
        blk = split * bps + j
        # Tail regime naming: clamp into [0, ntb) — prefix-regime steps
        # re-name tail block 0 (resident after its first fetch).
        tblk = jnp.clip(blk - hb, 0, ntb - 1)
        return (bb, tblk, 0, bh % n_kv, 0)

    pool_spec = pl.BlockSpec((1, ps, 1, hd), pool_map)
    tail_spec = pl.BlockSpec((1, 1, ps, 1, hd), tail_map)
    in_specs = [
        pl.BlockSpec((1, tb * g, hd),
                     lambda bh, split, j, hits, table: (bh, 0, 0)),
        pool_spec,
        pool_spec,
        tail_spec,
        tail_spec,
    ]
    inputs = [q4, k_pages, v_pages, tk5, tv5]
    if quant:
        sc_spec = pl.BlockSpec((1, ps, 1, 1), pool_map)
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    part_spec = lambda lanes: pl.BlockSpec(                      # noqa: E731
        (1, 1, tb * g, lanes),
        lambda bh, split, j, hits, table: (bh, split, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(m * n_kv, n_splits, bps),
        in_specs=in_specs,
        out_specs=[part_spec(hd), part_spec(_LANES), part_spec(_LANES)],
        scratch_shapes=[
            pltpu.VMEM((tb * g, hd), jnp.float32),     # acc
            pltpu.VMEM((tb * g, _LANES), jnp.float32),  # m
            pltpu.VMEM((tb * g, _LANES), jnp.float32),  # l
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, scale=1.0 / math.sqrt(hd), ps=ps, n_kv=n_kv,
        bps=bps, hb=hb, quant=quant, tb=tb, g=g)
    acc, mm, ll = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((m * n_kv, n_splits, tb * g, hd),
                                 jnp.float32),
            jax.ShapeDtypeStruct((m * n_kv, n_splits, tb * g, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((m * n_kv, n_splits, tb * g, _LANES),
                                 jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(hit_lens, prefix_table, *inputs)
    out = _combine_splits(acc, mm, ll, m, n_kv * tb * g, hd, q.dtype)
    return out.reshape(m, n_kv, tb, g, hd).transpose(0, 2, 1, 3, 4) \
        .reshape(m, tb, n_heads, hd)


def contiguous_as_paged(cache: jax.Array, block_k: int):
    """View a contiguous cache [B, S, ...] as a page pool + block table
    with NO data movement the compiler can't elide: block j of batch b is
    \"page\" b·(S/block_k)+j, so the pool is just the cache reshaped and
    the table is an iota. Lets the multi-query verify kernel serve the
    CONTIGUOUS serving path (generate_speculative's 1+gamma window)
    without a second kernel body."""
    b, s = cache.shape[:2]
    nb = s // block_k
    pool = cache.reshape(b * nb, block_k, *cache.shape[2:])
    table = (jnp.arange(b, dtype=jnp.int32)[:, None] * nb
             + jnp.arange(nb, dtype=jnp.int32)[None, :])
    return pool, table
