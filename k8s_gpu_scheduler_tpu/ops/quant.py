"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound: each emitted token re-reads every matmul
weight while the activations are a [B, 1, D] sliver, so halving the weight
bytes halves the dominant memory traffic (the MXU is idle either way —
maxtext and vLLM-TPU ship the same weight-only int8 mode for this reason).
The reference has no serving engine at all (SURVEY.md §0); this extends
BASELINE config 5's workload side.

Scheme: symmetric per-output-channel int8. For a weight ``w [..., K, N]``
(K = contraction dim), ``s = max|w| / 127`` over K gives ``s [..., 1, N]``
and ``q = round(w / s)``; by linearity ``(x @ q) * s == x @ (q * s)``, so
``qdot`` applies the scale AFTER the matmul — XLA fuses the int8→bf16
convert into the dot's weight read and the HBM transfer stays int8.

Quantized leaves are ``{"q": int8, "s": float}`` dicts, which ride
``lax.scan`` over layer-stacked blocks like any other pytree. ``qdot``
passes plain arrays through untouched, so shared call sites (swiglu, the
serving blocks) serve both precisions with one code path and training is
unaffected.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

# Leaves of params["blocks"] / top-level params that hold matmul weights —
# everything else (norms, embed gather, f32 router) stays in model dtype.
_BLOCK_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> Dict[str, jax.Array]:
    """w [..., K, N] → {"q": int8, "s": f32 [..., 1, N]} per-output-channel
    symmetric; exact for the all-zero channel (scale floored)."""
    s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def dequantize_weight(wq: Dict[str, jax.Array], dtype) -> jax.Array:
    return (wq["q"].astype(jnp.float32) * wq["s"]).astype(dtype)


def qdot(x: jax.Array, w) -> jax.Array:
    """x @ w for a plain array OR a quantized {"q","s"} leaf. The int8
    operand converts to x.dtype inside the dot (fused weight-read convert);
    the per-channel scale applies to the [..., N] result."""
    if isinstance(w, dict):
        y = x @ w["q"].astype(x.dtype)
        return (y.astype(jnp.float32) * w["s"]).astype(x.dtype)
    return x @ w


def qeinsum(spec: str, x: jax.Array, w) -> jax.Array:
    """einsum(spec, x, w) for a plain array OR quantized leaf. Valid for
    specs whose output keeps the weight's non-contracted dims as the
    TRAILING axes in order (the MoE dispatch shapes "btd,edf->betf" /
    "betf,efd->betd"), so the scale's [..., 1, N] broadcast lines up with
    the result."""
    if isinstance(w, dict):
        y = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        return (y.astype(jnp.float32) * w["s"]).astype(x.dtype)
    return jnp.einsum(spec, x, w)


def quantize_llama_params(params: Dict) -> Dict:
    """Quantize a Llama param tree's matmul weights for serving. Covers
    dense AND MoE blocks: expert tensors ([L, E, D, F] etc.) quantize with
    the same axis=-2 per-output-channel rule, giving per-(layer, expert,
    channel) scales, and flow through qeinsum in the dropless serving
    path. The f32 router is deliberately untouched (tiny, and expert
    placement is precision-sensitive)."""
    blocks = dict(params["blocks"])
    for name in _BLOCK_WEIGHTS:
        if name in blocks:
            blocks[name] = quantize_weight(blocks[name])
    out = dict(params)
    out["blocks"] = blocks
    out["lm_head"] = quantize_weight(params["lm_head"])
    return out
