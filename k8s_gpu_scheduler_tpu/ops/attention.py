"""Attention: dense, ring (sequence-parallel), and Ulysses (head-parallel).

Long-context is first-class in this framework (SURVEY.md §5: the reference
has no sequence dimension at all; our scheduler gang-places jobs that DO).
Two sequence-parallel schemes, both pure XLA collectives over ICI:

- **ring_attention**: K/V blocks rotate around the ``sp`` axis via
  ``ppermute`` while each device keeps flash-style running softmax stats
  (m, l) — O(T/n) memory per device, communication overlapped by XLA with
  the per-block matmuls. The blockwise-softmax recurrence follows the
  public blockwise/ring attention formulation (Liu et al.; PAPERS.md).
- **ulysses_attention**: two ``all_to_all``s re-shard [B, T/n, H, d] →
  [B, T, H/n, d] so each device runs DENSE attention on full sequence for
  a head subset — cheaper at moderate T, requires H % n == 0.

Both are called inside ``shard_map`` (the model wraps them); dense_attention
is the single-device reference the tests check them against.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() exact zero
                  # without inf-inf → NaN when a whole row is masked


def _repeat_kv(k: jax.Array, n_heads: int) -> jax.Array:
    """GQA: tile kv heads up to n_heads. k: [B, T, Hkv, d]."""
    h_kv = k.shape[2]
    if h_kv == n_heads:
        return k
    if n_heads % h_kv:
        raise ValueError(
            f"GQA needs n_heads ({n_heads}) divisible by kv heads ({h_kv})")
    return jnp.repeat(k, n_heads // h_kv, axis=2)


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Reference attention. q: [B, Tq, H, d]; k/v: [B, Tk, Hkv, d]."""
    *_, n_heads, head_dim = q.shape
    k = _repeat_kv(k, n_heads)
    v = _repeat_kv(v, n_heads)
    scale = 1.0 / math.sqrt(head_dim)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention inside shard_map over ``axis_name``.

    Shapes are PER-DEVICE: q/k/v [B, T/n, H(kv), d]. After ``s`` rotations
    device ``i`` holds the K/V block that started on device ``(i-s) mod n``,
    so global causal masking only needs the block indices.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_local, n_heads, head_dim = q.shape
    k = _repeat_kv(k, n_heads)
    v = _repeat_kv(v, n_heads)
    scale = 1.0 / math.sqrt(head_dim)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my_idx * t_local + jnp.arange(t_local)  # global query positions

    def step(s, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my_idx - s) % n
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        )
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            allowed = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(allowed, scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o0 = jnp.zeros((b, n_heads, t_local, head_dim), jnp.float32)
    m0 = jnp.full((b, n_heads, t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_heads, t_local), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): re-shard
    seq→heads, dense-attend full sequence locally, re-shard heads→seq.
    Per-device in/out: [B, T/n, H, d]; requires H divisible by n (GQA kv
    heads are replicated up to H first — the scatter must split heads)."""
    n = jax.lax.psum(1, axis_name)
    n_heads = q.shape[2]
    if n_heads % n:
        raise ValueError(f"ulysses needs heads ({n_heads}) divisible by sp ({n})")
    k = _repeat_kv(k, n_heads)
    v = _repeat_kv(v, n_heads)
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    q_full = a2a(q)  # [B, T, H/n, d]
    k_full = a2a(k)
    v_full = a2a(v)
    out = dense_attention(q_full, k_full, v_full, causal=causal)
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )
