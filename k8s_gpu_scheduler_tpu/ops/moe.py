"""Mixture-of-Experts FFN with expert parallelism — the ep mesh axis.

TPU-native MoE: static shapes end to end. Routing is top-k gating with a
fixed per-expert CAPACITY (the Switch/GShard formulation): every token
picks its k experts, tokens beyond an expert's capacity are dropped (their
combine weight is 0, so the residual connection passes them through
unchanged), and dispatch/combine are dense einsums over one-hot tensors —
no dynamic shapes, no host control flow, exactly what XLA wants.

Parallelism is declarative like everything else in this framework: expert
weights are stacked on a leading E axis carrying the logical axis
'expert', the rules table (parallel/sharding.py) maps it to the 'ep' mesh
axis, and the dispatch einsum's contraction over tokens×experts makes
GSPMD insert the all_to_all that hand-written MoE backends place
explicitly. Within one expert the mlp axis still shards over tp, so ep
composes with tensor parallelism.

The reference schedules pods and has no model code at all (SURVEY.md §2
parallelism checklist: DP/TP/PP/SP/EP all absent); this closes the one
axis (EP) VERDICT.md r3 left as a stretch item.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp


def expert_capacity(tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert queue length: perfectly balanced load times the slack
    factor, at least 1. Static — computed from trace-time shapes."""
    return max(1, int(capacity_factor * top_k * tokens / n_experts))


def moe_ffn(
    x: jax.Array,               # [B, T, D]
    router: jax.Array,          # [D, E]
    w_gate: jax.Array,          # [E, D, F]
    w_up: jax.Array,            # [E, D, F]
    w_down: jax.Array,          # [E, F, D]
    top_k: int = 2,
    capacity_factor: float = 1.25,
) -> "tuple[jax.Array, jax.Array]":
    """Top-k routed SwiGLU experts. Returns (out [B, T, D], balance aux);
    dropped tokens (over expert capacity) return zeros, so callers keep
    the residual add. The aux is the Switch balance loss computed from the
    SAME routing probabilities the dispatch uses — one source of truth, so
    gating changes can never desynchronize the two.

    Router math runs in f32 (softmax over experts is precision-sensitive);
    expert compute stays in the input dtype (bf16 on TPU: per-expert
    matmuls are MXU-shaped [C, D]x[D, F] batches).
    """
    B, T, D = x.shape
    E = router.shape[1]
    C = expert_capacity(T, E, top_k, capacity_factor)  # per batch row

    logits = (x.astype(jnp.float32) @ router.astype(jnp.float32))  # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)           # [B,T,k]
    aux = _balance_aux(probs, gate_idx, E, top_k)
    # Renormalize over the chosen k (Mixtral convention).
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # Queue position of each (token, choice) within its chosen expert:
    # flatten choices in (t, k) order, cumulative count per expert.
    oh = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)         # [B,T,k,E]
    flat = oh.reshape(B, T * top_k, E)
    pos_flat = jnp.cumsum(flat, axis=1) - flat                  # [B,T*k,E]
    pos = (pos_flat * flat).sum(-1).reshape(B, T, top_k)        # [B,T,k]
    keep = (pos < C).astype(jnp.float32)

    # combine [B,T,E,C]: gate weight at the (expert, queue slot) each
    # choice landed in; dispatch is its support.
    slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                             dtype=jnp.float32)                 # [B,T,k,C]
    combine = jnp.einsum(
        "btk,btke,btkc->btec", gate_vals * keep, oh, slot_oh)
    dispatch = (combine > 0.0).astype(x.dtype)

    # Dispatch → per-expert queues [E, B, C, D]; GSPMD turns the E-axis
    # sharding mismatch (activations batch-sharded, queues ep-sharded)
    # into the all_to_all.
    expert_in = jnp.einsum("btec,btd->ebcd", dispatch, x)
    g = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", expert_in, w_gate))
    u = jnp.einsum("ebcd,edf->ebcf", expert_in, w_up)
    expert_out = jnp.einsum("ebcf,efd->ebcd", g * u, w_down)
    # Combine back to token order, weighted by the (f32) gate values.
    out = jnp.einsum(
        "btec,ebcd->btd", combine.astype(x.dtype), expert_out)
    return out, aux


def _balance_aux(probs: jax.Array, idx: jax.Array, n_experts: int,
                 top_k: int) -> jax.Array:
    """Switch-style auxiliary loss from already-computed routing:
    E · Σ_e fraction_tokens(e)·mean_prob(e), minimized (=1) at uniform
    routing — added to the train loss with a small coefficient so experts
    stay balanced instead of collapsing."""
    T = probs.shape[1]
    frac = jax.nn.one_hot(
        idx, n_experts, dtype=jnp.float32).sum((1, 2)) / (T * top_k)
    mean_prob = probs.mean(axis=1)                               # [B,E]
    return n_experts * (frac * mean_prob).sum(-1).mean()


def moe_ffn_dropless(
    x: jax.Array,               # [B, T, D]
    router: jax.Array,          # [D, E]
    w_gate: jax.Array,          # [E, D, F]
    w_up: jax.Array,            # [E, D, F]
    w_down: jax.Array,          # [E, F, D]
    top_k: int = 2,
) -> jax.Array:
    """Dropless routing for SERVING: every token gets its top-k experts,
    computed as a gate-masked sum over ALL experts' FFN outputs — no
    capacity machinery at all. Identical output to moe_ffn whenever
    moe_ffn doesn't drop (and moe_ffn with capacity >= k·T never drops),
    but k× fewer expert FLOPs than the capacity formulation at that
    setting and no O(T²) dispatch tensors; the E/k-fold overcompute vs
    ideal routing is the price of staying gather-free (a per-token weight
    gather is only memory-feasible at t=1). Per-token function: output is
    independent of co-batched tokens and padding.

    Expert weights may be plain arrays or int8 {"q","s"} leaves
    (ops/quant.py) — qeinsum passes plain ones through."""
    from .quant import qeinsum

    logits = (x.astype(jnp.float32) @ router.astype(jnp.float32))  # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)              # [B,T,k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    # [B,T,E] combine weights: gate value where chosen, 0 elsewhere.
    weights = jnp.einsum(
        "btk,btke->bte", gate_vals,
        jax.nn.one_hot(gate_idx, router.shape[1], dtype=jnp.float32))
    g = jax.nn.silu(qeinsum("btd,edf->betf", x, w_gate))
    u = qeinsum("btd,edf->betf", x, w_up)
    out_e = qeinsum("betf,efd->betd", g * u, w_down)               # [B,E,T,D]
    return jnp.einsum("bte,betd->btd", weights.astype(x.dtype), out_e)


def load_balancing_loss(x: jax.Array, router: jax.Array,
                        top_k: int = 2) -> jax.Array:
    """Standalone balance loss for callers without a moe_ffn pass (the
    training path uses the aux moe_ffn returns, computed from the same
    probabilities it routes with)."""
    probs = jax.nn.softmax(
        (x.astype(jnp.float32) @ router.astype(jnp.float32)), axis=-1)
    _, idx = jax.lax.top_k(probs, top_k)
    return _balance_aux(probs, idx, router.shape[1], top_k)
