"""Shared Pallas compatibility bits for the kernel modules.

Kept out of ops/__init__.py (which hosts the user-facing
``pallas_interpret`` toggle) so kernel modules can import it at module
level without depending on package-init ordering.
"""
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; support both.
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")
