"""Transformer primitives: RMSNorm, RoPE, SwiGLU.

Numerics follow the common Llama-family conventions. Norms and softmax
statistics compute in f32 regardless of activation dtype (bf16 on TPU) —
the MXU takes bf16 inputs with f32 accumulation, so only the
bandwidth-bound elementwise stats need explicit upcasting.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .quant import qdot


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(dtype) * weight


def rope_freqs(head_dim: int, max_len: int, theta: float = 10000.0) -> jax.Array:
    """[max_len, head_dim//2] complex-free rotation angles."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(t, inv)  # [T, hd/2]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate pairs of channels. x: [..., T, H, hd]; angles: [T, hd/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate, w_up, w_down) -> jax.Array:
    """SwiGLU MLP: down( silu(x@gate) * (x@up) ). Three matmuls — the
    gate/up pair is column-parallel under tp, down row-parallel
    (parallel/sharding.py conventions). Weights may be plain arrays or
    int8 {"q","s"} leaves (ops/quant.py) — qdot passes plain ones through."""
    g = jax.nn.silu(qdot(x, w_gate))
    return qdot(g * qdot(x, w_up), w_down)
