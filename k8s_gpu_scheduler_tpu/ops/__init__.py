"""Core ops — TPU-friendly building blocks for the workload layer.

Everything here is jit-traceable with static shapes, keeps the FLOPs in
large bf16 matmuls (MXU-shaped), and uses `lax` control flow only. The
sequence-parallel attention variants (ring via ppermute, Ulysses via
all_to_all) are the long-context capability SURVEY.md §5 requires the
rebuild to treat as first-class; the Pallas kernels (flash training
attention, fused flash-decode serving attention) are the single-chip hot
paths.
"""
import os

import jax


def pallas_interpret(override=None) -> bool:
    """Shared interpret-mode toggle for every Pallas kernel in ops/.

    Resolution order: an explicit ``override`` (the kernel wrapper's
    ``interpret=`` argument) wins; else the ``TPU_SCHED_PALLAS_INTERPRET``
    env var (config.py's TPU_SCHED_* convention — "1"/"true" forces
    interpret even on TPU, "0" forces compiled; set-but-empty counts as
    unset, so a bare `ENV TPU_SCHED_PALLAS_INTERPRET=` in a manifest can't
    force compiled mode on a CPU host); else interpret exactly when the
    backend is not a TPU, so tier-1 (JAX_PLATFORMS=cpu) exercises every
    kernel hermetically instead of skipping them. Kernel modules import
    this lazily (inside their wrappers) to stay cycle-free.
    """
    if override is not None:
        return bool(override)
    env = os.environ.get("TPU_SCHED_PALLAS_INTERPRET", "").strip()
    if env:
        return env.lower() not in ("0", "false", "no")
    return jax.devices()[0].platform != "tpu"


from .layers import apply_rope, rms_norm, rope_freqs, swiglu  # noqa: E402
from .attention import dense_attention, ring_attention, ulysses_attention  # noqa: E402
from .flash_attention import flash_attention, flash_attention_diff  # noqa: E402
from .decode_attention import (  # noqa: E402
    DEFAULT_PAGE_SIZE, contiguous_as_paged, decode_plan,
    dense_decode_reference, dense_verify_reference, flash_decode_attention,
    gather_paged_kv, paged_decode_attention, paged_plan,
    paged_verify_attention, verify_plan,
)
from .moe import load_balancing_loss, moe_ffn, moe_ffn_dropless  # noqa: E402
from .quant import dequantize_weight, qdot, quantize_llama_params, quantize_weight  # noqa: E402

__all__ = [
    "pallas_interpret",
    "qdot",
    "quantize_weight",
    "dequantize_weight",
    "quantize_llama_params",
    "rms_norm",
    "rope_freqs",
    "apply_rope",
    "swiglu",
    "dense_attention",
    "ring_attention",
    "ulysses_attention",
    "flash_attention",
    "flash_attention_diff",
    "decode_plan",
    "dense_decode_reference",
    "flash_decode_attention",
    "DEFAULT_PAGE_SIZE",
    "paged_plan",
    "paged_decode_attention",
    "gather_paged_kv",
    "verify_plan",
    "paged_verify_attention",
    "dense_verify_reference",
    "contiguous_as_paged",
    "moe_ffn",
    "moe_ffn_dropless",
    "load_balancing_loss",
]
