"""Core ops — TPU-friendly building blocks for the workload layer.

Everything here is jit-traceable with static shapes, keeps the FLOPs in
large bf16 matmuls (MXU-shaped), and uses `lax` control flow only. The
sequence-parallel attention variants (ring via ppermute, Ulysses via
all_to_all) are the long-context capability SURVEY.md §5 requires the
rebuild to treat as first-class.
"""
from .layers import apply_rope, rms_norm, rope_freqs, swiglu
from .attention import dense_attention, ring_attention, ulysses_attention
from .flash_attention import flash_attention, flash_attention_diff
from .moe import load_balancing_loss, moe_ffn, moe_ffn_dropless
from .quant import dequantize_weight, qdot, quantize_llama_params, quantize_weight

__all__ = [
    "qdot",
    "quantize_weight",
    "dequantize_weight",
    "quantize_llama_params",
    "rms_norm",
    "rope_freqs",
    "apply_rope",
    "swiglu",
    "dense_attention",
    "ring_attention",
    "ulysses_attention",
    "flash_attention",
    "flash_attention_diff",
    "moe_ffn",
    "moe_ffn_dropless",
    "load_balancing_loss",
]
