"""Typed Kubernetes-style object model.

The reference consumes these objects through client-go
(/root/reference/pkg/resources/pods.go, nodes.go); we define our own minimal,
hermetic model so the whole framework — scheduler, agents, tests — runs with
no live cluster, while keeping field names aligned with the k8s API so a thin
REST shim can later map these onto a real apiserver.

Conventions:
- TPU chips are requested via the extended resource ``google.com/tpu``
  (the reference's analogue is ``nvidia.com/gpu`` / MIG instances).
- TPU generation/topology ride on the GKE node labels
  ``cloud.google.com/gke-tpu-accelerator`` and
  ``cloud.google.com/gke-tpu-topology`` (the reference encodes GPU model in
  the node *name* substring — gpu_plugins.go:478-499 — which we deliberately
  replace with labels).
"""
from __future__ import annotations

import copy
import time
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

TPU_RESOURCE = "google.com/tpu"

# GKE TPU node labels (public label schema).
LABEL_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
LABEL_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

# GKE groups the hosts of one multi-host slice into one node pool.
LABEL_NODEPOOL = "cloud.google.com/gke-nodepool"

# Our framework's own annotations/labels.
LABEL_POD_GROUP = "tpu.sched/pod-group"
LABEL_SLICE_GROUP = "tpu.sched/slice-group"    # falls back to LABEL_NODEPOOL
LABEL_WORKER_INDEX = "tpu.sched/worker-index"  # host's index within its slice
ANN_SLICE_CONFIG = "tpu.sched/slice.config"  # analogue of nvidia.com/mig.config
ANN_RESHAPE_STATE = "tpu.sched/slice.reshape-state"


def _now() -> float:
    return time.time()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=lambda: str(_uuid.uuid4()))
    resource_version: int = 0
    creation_timestamp: float = field(default_factory=_now)
    # "kind/name" of each ownerReference controller (StatefulSet/Job/...).
    # Empty = bare pod: deleting it is permanent, so preemption and gang
    # collapse must never evict it (no controller will recreate it).
    owner_references: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class EnvVar:
    name: str
    value: str = ""


@dataclass
class ConfigMapRef:
    name: str


@dataclass
class ResourceRequirements:
    requests: Dict[str, float] = field(default_factory=dict)
    limits: Dict[str, float] = field(default_factory=dict)

    def tpu_chips(self) -> int:
        return int(self.requests.get(TPU_RESOURCE, self.limits.get(TPU_RESOURCE, 0)))


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    env: List[EnvVar] = field(default_factory=list)
    env_from: List[ConfigMapRef] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)

    def get_env(self, name: str) -> Optional[str]:
        for e in self.env:
            if e.name == name:
                return e.value
        return None


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = "tpu-scheduler"
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[str] = field(default_factory=list)
    # StatefulSet pods carry hostname=<pod name> and subdomain=<serviceName>
    # (the governing headless Service), giving them the stable DNS name
    # <hostname>.<subdomain>.<ns>.svc — the address gang PostBind injects so
    # jax.distributed.initialize can rendezvous POD-to-POD (node addresses
    # are unreachable without hostNetwork).
    hostname: str = ""
    subdomain: str = ""

    def tpu_chips(self) -> int:
        return sum(c.resources.tpu_chips() for c in self.containers)


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: List[str] = field(default_factory=list)
    host_ip: str = ""
    pod_ip: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    def get_env(self, name: str) -> Optional[str]:
        """Env var of container[0] — parity with utils.GetEnv
        (/root/reference/utils/utils.go:124-131), which the reference uses to
        read the pod's ``SLO``."""
        if not self.spec.containers:
            return None
        return self.spec.containers[0].get_env(name)

    def pod_group(self) -> Optional[str]:
        return self.metadata.labels.get(LABEL_POD_GROUP)


@dataclass
class NodeStatus:
    capacity: Dict[str, float] = field(default_factory=dict)
    allocatable: Dict[str, float] = field(default_factory=dict)
    addresses: List[str] = field(default_factory=list)
    conditions: List[str] = field(default_factory=lambda: ["Ready"])


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    def tpu_capacity(self) -> int:
        return int(self.status.allocatable.get(TPU_RESOURCE, 0))

    def tpu_accelerator(self) -> Optional[str]:
        """e.g. 'tpu-v5-lite-podslice', 'tpu-v5p-slice'."""
        return self.metadata.labels.get(LABEL_TPU_ACCELERATOR)

    def tpu_topology(self) -> Optional[str]:
        """e.g. '2x4' (v5e host), '2x2x2' (v5p sub-slice)."""
        return self.metadata.labels.get(LABEL_TPU_TOPOLOGY)


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)

    kind = "ConfigMap"


@dataclass
class PodGroup:
    """Gang-scheduling unit — all-or-nothing admission of ``min_member`` pods.

    The reference has no gang scheduling at all (SURVEY.md §2: each pod is
    scored/bound independently); this is the new first-class capability needed
    for multi-host JAX jobs (a v5p-16 Llama pretrain is 4 pods that must land
    together or not at all).
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    # Desired slice topology for the whole gang, e.g. '4x4' → 4 hosts of 2x2.
    topology: str = ""
    schedule_timeout_s: float = 60.0

    kind = "PodGroup"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease — leader election for scheduler HA.

    The reference gets leader election from upstream kube-scheduler config
    (/root/reference/deploy/scheduler.yaml:10-13 ``leaderElection:
    leaderElect: true``); we own the framework, so the Lease object and the
    elector (sched/leaderelection.py) live here. Times are epoch seconds
    (converted to RFC3339 MicroTime at the REST boundary)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_s: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0

    kind = "Lease"

    def expired(self, now: float) -> bool:
        return not self.holder_identity or (
            self.renew_time + self.lease_duration_s <= now)


_KINDS = {"Pod": Pod, "Node": Node, "ConfigMap": ConfigMap,
          "PodGroup": PodGroup, "Lease": Lease}


def deepcopy_obj(obj: Any) -> Any:
    return copy.deepcopy(obj)


def kind_of(obj: Any) -> str:
    k = getattr(obj, "kind", None)
    if k is None:
        raise TypeError(f"not an API object: {obj!r}")
    return k
