"""TPU slice topology math — generations, torus coordinates, ICI distance.

This module is the TPU-native replacement for the reference's GPU-model
taxonomy (A30-with-MIG vs V100-with-MPS, selected by node-name substring at
gpu_plugins.go:478-499) and its MIG partition table
(configs = [all-4g.24gb, all-2g.12gb, all-1g.6gb] / partitions = [4,2,1],
gpu_plugins.go:52-53). Here the unit is a *slice*: an axb(xc) block of chips
connected by ICI. Placement quality is measured in ICI hops on the torus —
the quantity the scheduler's locality score minimizes for gangs.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from enum import Enum
from typing import Dict, Iterable, List, Sequence, Tuple


class TPUGen(str, Enum):
    V5E = "tpu-v5-lite-podslice"
    V5P = "tpu-v5p-slice"
    V4 = "tpu-v4-podslice"
    V6E = "tpu-v6e-slice"

    @property
    def chips_per_host(self) -> int:
        # v5e/v6e hosts expose a 2x4 board; v4/v5p hosts a 2x2x1 board.
        return {TPUGen.V5E: 8, TPUGen.V6E: 8, TPUGen.V5P: 4, TPUGen.V4: 4}[self]

    @property
    def host_topology(self) -> Tuple[int, ...]:
        return {
            TPUGen.V5E: (2, 4),
            TPUGen.V6E: (2, 4),
            TPUGen.V5P: (2, 2, 1),
            TPUGen.V4: (2, 2, 1),
        }[self]

    @property
    def torus_dims(self) -> int:
        return {TPUGen.V5E: 2, TPUGen.V6E: 2, TPUGen.V5P: 3, TPUGen.V4: 3}[self]

    @property
    def peak_bf16_tflops(self) -> float:
        # Per chip. Public numbers: v4 275, v5e 197, v5p 459, v6e 918.
        return {TPUGen.V4: 275.0, TPUGen.V5E: 197.0, TPUGen.V5P: 459.0, TPUGen.V6E: 918.0}[self]

    @property
    def hbm_gib(self) -> float:
        return {TPUGen.V4: 32.0, TPUGen.V5E: 16.0, TPUGen.V5P: 95.0, TPUGen.V6E: 32.0}[self]


def parse_topology(s: str) -> Tuple[int, ...]:
    """'2x4' → (2, 4); '2x2x2' → (2, 2, 2)."""
    try:
        dims = tuple(int(p) for p in s.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"bad topology string {s!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology string {s!r}")
    return dims


def format_topology(dims: Sequence[int]) -> str:
    return "x".join(str(d) for d in dims)


def chip_count(dims: Sequence[int]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def ici_hop_distance(
    a: Sequence[int], b: Sequence[int], dims: Sequence[int], wrap: bool = True
) -> int:
    """Manhattan distance between two chips on the slice torus.

    ``wrap`` models the wraparound links a full pod torus has; sub-slices of a
    pod are meshes (no wrap), which is the conservative default GKE gives a
    partial slice — callers pass wrap=True only for full-pod topologies.
    """
    if len(a) != len(b) or len(a) != len(dims):
        raise ValueError("coordinate rank mismatch")
    total = 0
    for x, y, d in zip(a, b, dims):
        delta = abs(x - y)
        if wrap and d > 2:
            delta = min(delta, d - delta)
        total += delta
    return total


def slice_diameter(dims: Sequence[int], wrap: bool = False) -> int:
    """Worst-case chip-to-chip hop count — the latency term in gang scoring."""
    return sum((d // 2 if wrap and d > 2 else d - 1) for d in dims)


def host_board(dims: Sequence[int], gen: TPUGen) -> Tuple[int, ...]:
    """Chip block owned by one host VM for a slice of shape ``dims``.

    v5e/v6e single-host slices (≤8 chips) live on one 2x4 board; *multi-host*
    v5e slices are carved into 2x2 four-chip VMs (GKE's ct5lp-hightower-4t),
    which is why v5e-16 = 4 hosts and v5e-256 = 64 hosts. v4/v5p hosts always
    own a 2x2x1 block.
    """
    if gen in (TPUGen.V5E, TPUGen.V6E):
        if chip_count(dims) <= 8 and _fits_within(dims, (2, 4)):
            return tuple(dims)  # whole slice on one host's 2x4 board
        return (2, 2)
    # v4/v5p: sub-host partitions ('2x1x1', '1x1x1' — SLICE_CONFIGS) fit on
    # one host's 2x2x1 board; anything larger tiles by whole boards. A shape
    # like 4x1x1 has a 4-long axis no board can hold, so it falls through to
    # whole-board tiling (2 hosts) instead of being accepted as one host.
    if chip_count(dims) <= 4 and _fits_within(dims, gen.host_topology):
        return tuple(dims)
    return gen.host_topology


def _fits_within(dims: Sequence[int], board: Sequence[int]) -> bool:
    return len(dims) == len(board) and all(d <= b for d, b in zip(dims, board))


def host_grid(dims: Sequence[int], gen: TPUGen) -> Tuple[int, ...]:
    """How many hosts along each axis for a slice of shape ``dims``."""
    host = host_board(dims, gen)
    grid = []
    for i, d in enumerate(dims):
        h = host[i] if i < len(host) else 1
        if d % h:
            # Every axis must tile exactly by the host board — '1x16' on v5e
            # (2x2 boards) is not a GKE topology and must be rejected, not
            # rounded up to 8 hosts.
            raise ValueError(f"topology {dims} not host-aligned for {gen.value}")
        grid.append(d // h)
    return tuple(grid)


def hosts_needed(dims: Sequence[int], gen: TPUGen) -> int:
    return chip_count(host_grid(dims, gen))


def host_coordinates(dims: Sequence[int], gen: TPUGen) -> List[Tuple[int, ...]]:
    """Torus coordinates (in host units) of every host in the slice."""
    grid = host_grid(dims, gen)
    return [tuple(c) for c in itertools.product(*(range(g) for g in grid))]


@dataclass(frozen=True)
class SliceTopology:
    """A concrete slice shape on a given TPU generation."""

    gen: TPUGen
    dims: Tuple[int, ...]

    @staticmethod
    def parse(gen: str | TPUGen, topo: str) -> "SliceTopology":
        g = TPUGen(gen) if not isinstance(gen, TPUGen) else gen
        return SliceTopology(g, parse_topology(topo))

    @property
    def chips(self) -> int:
        return chip_count(self.dims)

    @property
    def hosts(self) -> int:
        return hosts_needed(self.dims, self.gen)

    @property
    def is_multi_host(self) -> bool:
        return self.hosts > 1

    @property
    def has_wraparound(self) -> bool:
        # 3D tori (v4/v5p): sub-slices with every axis a multiple of 4 get
        # wrapped rings (GKE grants twisted-torus wrap at cube granularity).
        # 2D tori (v5e/v6e): only the full 16x16 pod has wrapped rings —
        # partial slices are meshes.
        if self.gen.torus_dims == 3:
            return all(d >= 4 and d % 4 == 0 for d in self.dims)
        return all(d >= 16 for d in self.dims)

    def diameter(self) -> int:
        return slice_diameter(self.dims, wrap=self.has_wraparound)

    def __str__(self) -> str:  # pragma: no cover
        return f"{self.gen.value}:{format_topology(self.dims)}"


# --- Dynamic slice partitioning (the MIG-reconfigure analogue) --------------
#
# The reference repartitions an idle A30 among {1,2,4} MIG instances by
# relabeling the node (gpu_plugins.go:357-452). The TPU analogue partitions a
# host's board into equal sub-slices that independent pods can own; the table
# below mirrors configs/partitions (gpu_plugins.go:52-53) per generation.

SLICE_CONFIGS: Dict[TPUGen, List[Tuple[str, int]]] = {
    # (sub-slice topology per pod, pods per host)
    TPUGen.V5E: [("2x4", 1), ("2x2", 2), ("1x2", 4), ("1x1", 8)],
    TPUGen.V6E: [("2x4", 1), ("2x2", 2), ("1x2", 4), ("1x1", 8)],
    TPUGen.V5P: [("2x2x1", 1), ("2x1x1", 2), ("1x1x1", 4)],
    TPUGen.V4: [("2x2x1", 1), ("2x1x1", 2), ("1x1x1", 4)],
}


def partitions_for(gen: TPUGen) -> List[int]:
    """Partition counts available on ``gen`` — analogue of partitions=[4,2,1]."""
    return [p for _, p in SLICE_CONFIGS[gen]]


def config_for_partitions(gen: TPUGen, parts: int) -> str:
    for topo, p in SLICE_CONFIGS[gen]:
        if p == parts:
            return topo
    raise ValueError(f"{gen.value} has no {parts}-way partitioning")
