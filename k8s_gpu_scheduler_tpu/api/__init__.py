from .objects import (  # noqa: F401
    Container,
    ConfigMap,
    ConfigMapRef,
    EnvVar,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    PodStatus,
    ResourceRequirements,
    TPU_RESOURCE,
)
from .topology import SliceTopology, TPUGen, ici_hop_distance  # noqa: F401
