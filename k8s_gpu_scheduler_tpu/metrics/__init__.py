"""Metrics layer (SURVEY.md L2): Prometheus instant-query client with
concurrent TPU-series fan-out (consumer side, pkg/prom parity) and a
text-exposition exporter for the scheduler's own metrics (producer side —
new; the reference exports nothing, SURVEY.md §5)."""
from .client import (
    HBM_BANDWIDTH_UTIL,
    HBM_TOTAL,
    HBM_USED,
    MXU_DUTY_CYCLE,
    MetricsError,
    PromClient,
    Sample,
    TENSORCORE_UTIL,
    TPU_SERIES,
    parse_response,
)
from .exporter import (
    Counter, FLEET_AFFINITY_HITS_TOTAL, FLEET_COUNTERS,
    FLEET_EXPIRED_TOTAL, FLEET_FAILOVERS_TOTAL, FLEET_GAUGES,
    FLEET_JOURNAL_SIZE, FLEET_LOST_TOTAL, FLEET_MIGRATED_TOTAL,
    FLEET_REPLAYED_TOKENS_TOTAL, FLEET_REPLICA_STATE,
    FLEET_ROUTED_TOTAL, FLEET_SHED_TOTAL, Gauge, Histogram,
    MetricsServer, PHASE_BUCKETS, PHASE_HISTOGRAM, Registry,
    SERVING_POOL_GAUGES, export_serving_pool,
)

__all__ = [
    "HBM_BANDWIDTH_UTIL",
    "HBM_TOTAL",
    "HBM_USED",
    "MXU_DUTY_CYCLE",
    "MetricsError",
    "PromClient",
    "Sample",
    "TENSORCORE_UTIL",
    "TPU_SERIES",
    "parse_response",
    "Counter",
    "FLEET_AFFINITY_HITS_TOTAL",
    "FLEET_COUNTERS",
    "FLEET_EXPIRED_TOTAL",
    "FLEET_FAILOVERS_TOTAL",
    "FLEET_GAUGES",
    "FLEET_JOURNAL_SIZE",
    "FLEET_LOST_TOTAL",
    "FLEET_MIGRATED_TOTAL",
    "FLEET_REPLAYED_TOKENS_TOTAL",
    "FLEET_REPLICA_STATE",
    "FLEET_ROUTED_TOTAL",
    "FLEET_SHED_TOTAL",
    "Gauge",
    "Histogram",
    "MetricsServer",
    "PHASE_BUCKETS",
    "PHASE_HISTOGRAM",
    "Registry",
    "SERVING_POOL_GAUGES",
    "export_serving_pool",
]
