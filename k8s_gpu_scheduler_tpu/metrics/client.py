"""Prometheus instant-query client — parity with pkg/prom.

The reference has a generic HTTP-GET layer with context timeout
(pkg/prom/requests/metrics_request.go:30-80) and a DCGM fan-out that fires 5
instant queries concurrently via goroutines+channels
(pkg/prom/fetch_prom_metrics/prom_metrics.go:63-118), parsing each vector
response into Response{MetricName, Exporter, Value, GPU_I_ID, UUID}
(prom_metrics.go:14-61). This module is the TPU re-design: same instant-query
API (`/api/v1/query`), concurrent multi-series fan-out on a thread pool, and
TPU series instead of DCGM's (see TPU_SERIES below).
"""
from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# TPU metric series — replaces the reference's 5 DCGM series
# (prom_metrics.go:64-70: GR_ENGINE_ACTIVE, MEM_COPY_UTIL, GPU_TEMP,
# FB_USED, FB_FREE). Names follow the GKE tpu-device-plugin /
# libtpu-exporter convention (memory in bytes, utilizations in percent).
MXU_DUTY_CYCLE = "tpu_duty_cycle_percent"            # ≈ GR_ENGINE_ACTIVE
TENSORCORE_UTIL = "tpu_tensorcore_utilization"       # ≈ MEM_COPY_UTIL slot
HBM_BANDWIDTH_UTIL = "tpu_memory_bandwidth_utilization"
HBM_USED = "tpu_hbm_memory_usage_bytes"              # ≈ FB_USED
HBM_TOTAL = "tpu_hbm_memory_total_bytes"             # ≈ FB_FREE (inverted)

TPU_SERIES = [MXU_DUTY_CYCLE, TENSORCORE_UTIL, HBM_BANDWIDTH_UTIL, HBM_USED, HBM_TOTAL]


class MetricsError(Exception):
    pass


@dataclass
class Sample:
    """One vector sample — parity with prom Response (prom_metrics.go:14-26):
    MetricName/Exporter/Value/GPU_I_ID/UUID become
    metric_name/exporter/value/device_id/node."""

    metric_name: str
    value: float
    node: str = ""
    device_id: str = ""
    exporter: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


def parse_response(raw: Optional[bytes]) -> List[Sample]:
    """Parse a Prometheus instant-query vector response into samples —
    parity with ParseResponse (prom_metrics.go:28-61), including its
    nil-input and empty-result cases."""
    if not raw:
        return []
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise MetricsError(f"bad metrics JSON: {e}") from e
    if doc.get("status") != "success":
        raise MetricsError(f"query failed: {doc.get('error', 'unknown error')}")
    data = doc.get("data", {})
    if data.get("resultType") not in (None, "vector"):
        raise MetricsError(f"unexpected resultType {data.get('resultType')!r}")
    out: List[Sample] = []
    for item in data.get("result", []):
        metric = item.get("metric", {})
        value = item.get("value", [None, "nan"])
        try:
            v = float(value[1])
        except (TypeError, ValueError, IndexError):
            continue
        out.append(
            Sample(
                metric_name=metric.get("__name__", ""),
                value=v,
                node=metric.get("node", metric.get("kubernetes_node", "")),
                device_id=metric.get("device_id", metric.get("chip", "")),
                exporter=metric.get("pod", metric.get("exported_pod", "")),
                labels=dict(metric),
            )
        )
    return out


class PromClient:
    """Instant-query client with concurrent fan-out.

    ``base_url`` points at a Prometheus-compatible API (the reference talks
    to prometheus-0 on NodePort 30090 — gpu_plugins.go:185).
    """

    def __init__(self, base_url: str, timeout_s: float = 2.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def query_url(self, query: str) -> str:
        """Parity with requests.CreateURL (metrics_request.go:30-48)."""
        return f"{self.base_url}/api/v1/query?{urllib.parse.urlencode({'query': query})}"

    def instant_query(self, query: str) -> List[Sample]:
        try:
            with urllib.request.urlopen(self.query_url(query), timeout=self.timeout_s) as r:
                return parse_response(r.read())
        except urllib.error.URLError as e:
            raise MetricsError(f"metrics endpoint unreachable: {e}") from e

    def fan_out(self, queries: List[str]) -> Dict[str, List[Sample]]:
        """Run all queries concurrently — parity with the goroutine+channel
        fan-out in DcgmPromInstantQuery (prom_metrics.go:74-107). A failed
        series yields [] rather than failing the batch (the reference sends
        nil through its channel on error)."""
        def one(q: str) -> List[Sample]:
            try:
                return self.instant_query(q)
            except MetricsError:
                return []

        with ThreadPoolExecutor(max_workers=max(2, len(queries))) as pool:
            results = list(pool.map(one, queries))
        return dict(zip(queries, results))

    # -- TPU-specific entry points ----------------------------------------
    def tpu_metrics_for_node(self, node_name: str) -> Dict[str, List[Sample]]:
        """All TPU series restricted to one node — parity with
        GetDcgmMetricsForNode (gpu_plugins.go:238-300), used by the
        no-registry fallback scoring path (gpu_plugins.go:508-527)."""
        queries = [f'{s}{{node="{node_name}"}}' for s in TPU_SERIES]
        raw = self.fan_out(queries)
        return {s: raw[q] for s, q in zip(TPU_SERIES, queries)}

    def tpu_metrics(self) -> Dict[str, List[Sample]]:
        """Cluster-wide fan-out of all TPU series — parity with
        DcgmPromInstantQuery (prom_metrics.go:63-118)."""
        return {s: r for s, r in zip(TPU_SERIES, self.fan_out(list(TPU_SERIES)).values())}

    def node_duty_cycle(self, node_name: str) -> Optional[float]:
        """Mean MXU duty cycle across a node's chips, 0..100, or None if the
        series is absent — the Score fallback input (the reference computes
        100*(1-GR_ENGINE_ACTIVE) at gpu_plugins.go:508-527)."""
        samples = self.instant_query(f'{MXU_DUTY_CYCLE}{{node="{node_name}"}}')
        if not samples:
            return None
        return sum(s.value for s in samples) / len(samples)
