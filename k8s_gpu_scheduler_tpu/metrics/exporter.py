"""Prometheus-text-format metrics exporter for the scheduler itself.

The reference *consumes* metrics but exports none of its own beyond what
upstream kube-scheduler provides (SURVEY.md §5 "Metrics / observability":
"The scheduler exposes no metrics of its own... the BASELINE north-star
metric (p50 schedule latency) will require adding an exporter in the
rebuild"). This module is that exporter: counters, gauges and histograms
registered in a Registry, served as Prometheus text exposition on /metrics.
The scheduler records its cycle/bind latencies here (scheduler.py), and
bench.py reads the histogram back for the p50-schedule-latency number.
"""
from __future__ import annotations

import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, List, Optional, Tuple

# Default latency buckets (seconds) — kube-scheduler's
# scheduling_attempt_duration ladder, shortened.
DEFAULT_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._mu = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._mu:
            return self._values.get(key, 0.0)

    def expose(self) -> List[str]:
        with self._mu:
            items = list(self._values.items())
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, val in sorted(items):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


class Gauge:
    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._mu = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            self._values[key] = float(value)

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._mu:
            return self._values.get(key, 0.0)

    def expose(self) -> List[str]:
        with self._mu:
            items = list(self._values.items())
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, val in sorted(items):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


class _HistState:
    """Per-label-set histogram accumulator (bucket counts, sum, total,
    bounded raw window)."""

    __slots__ = ("counts", "sum", "total", "observations")

    def __init__(self, n_buckets: int, window: int) -> None:
        self.counts = [0] * (n_buckets + 1)  # +Inf bucket last
        self.sum = 0.0
        self.total = 0
        self.observations: Deque[float] = deque(maxlen=window)


class Histogram:
    # Raw observations kept for quantile() are bounded: a long-running
    # scheduler daemon observes every cycle, and an unbounded list would be
    # a slow memory leak. 100k covers any bench run; beyond that the window
    # slides (recent observations win, which is what a latency quantile
    # should reflect anyway).
    MAX_RAW_OBSERVATIONS = 100_000

    def __init__(self, name: str, help_: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._mu = threading.Lock()
        # Keyed by sorted label items; () is the unlabeled series, so the
        # no-label API (the scheduler's cycle/e2e histograms) is unchanged
        # while labeled series (tpu_serve_phase_duration_seconds{phase=})
        # ride the same metric. The unlabeled series exists EAGERLY:
        # a registered-but-unobserved histogram must keep exposing its
        # zeroed _bucket/_sum/_count lines (pre-label behavior — alerting
        # distinguishes "zero observations" from "metric absent").
        self._states: Dict[Tuple[Tuple[str, str], ...], _HistState] = {
            (): _HistState(len(self.buckets), self.MAX_RAW_OBSERVATIONS)}

    def _state_locked(self, key) -> _HistState:
        st = self._states.get(key)
        if st is None:
            st = _HistState(len(self.buckets), self.MAX_RAW_OBSERVATIONS)
            self._states[key] = st
        return st

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            st = self._state_locked(key)
            st.sum += value
            st.total += 1
            st.observations.append(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st.counts[i] += 1
                    return
            st.counts[-1] += 1

    @property
    def count(self) -> int:
        with self._mu:
            return sum(st.total for st in self._states.values())

    def count_for(self, **labels: str) -> int:
        key = tuple(sorted(labels.items()))
        with self._mu:
            st = self._states.get(key)
            return st.total if st else 0

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Exact quantile over the (bounded window of) raw observations —
        bench convenience; real Prometheus would estimate from buckets.
        With labels, the quantile of that one series; without, of the
        unlabeled series (the pre-label behavior)."""
        key = tuple(sorted(labels.items()))
        with self._mu:
            st = self._states.get(key)
            if st is None or not st.observations:
                return None
            xs = sorted(st.observations)
        idx = min(len(xs) - 1, max(0, int(q * len(xs))))
        return xs[idx]

    def expose(self) -> List[str]:
        with self._mu:
            states = [(key, list(st.counts), st.total, st.sum)
                      for key, st in sorted(self._states.items())]
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key, counts, total, s in states:
            labels = dict(key)
            cumulative = 0
            for b, c in zip(self.buckets, counts):
                cumulative += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': str(b)})} {cumulative}")
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels({**labels, 'le': '+Inf'})} {total}")
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {s}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {total}")
        return lines


class Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_create(self, name, factory, klass):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, klass):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def expose(self) -> str:
        with self._mu:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"


# Serving page-pool / prefix-cache gauges (ContinuousBatcher.pool_metrics
# key -> help text). Published via export_serving_pool below so the pool
# numbers that previously lived only in pool_metrics()/bench ride the same
# /metrics endpoint the scheduler's own latency histograms use.
SERVING_POOL_GAUGES = {
    "pages_total": "usable KV pages in the serving pool",
    "pages_free": "KV pages currently on the free list",
    "pages_in_use": "KV pages with at least one live reference",
    "pages_cached": "KV pages held (possibly shared) by the prefix tree",
    "pages_watermark": "high-water mark of referenced KV pages",
    "page_allocs": "cumulative page allocations",
    "page_frees": "cumulative page reference drops",
    "page_denied": "admissions denied for lack of free pages",
    "page_utilization": "referenced pages / usable pool (instantaneous)",
    "prefix_cached_pages": "pages (= radix-tree nodes) in the prefix cache",
    "prefix_hit_rate": "token-weighted prefix-cache hit rate",
    "prefix_request_hit_rate": "fraction of lookups matching any prefix",
    # NOTE: the pool_metrics key "prefix_hit_tokens" (cumulative) stays
    # available to host-side consumers (bench, fleet router), but its
    # Prometheus surface is now the tpu_serve_prefix_hit_tokens
    # HISTOGRAM below — per-admission hit lengths, whose _sum series IS
    # the old cumulative gauge and whose buckets show the distribution
    # (8-token system prompts vs whole mounted conversations).
    "prefix_lookup_tokens": "cumulative prompt tokens looked up",
    "prefix_lookups": "cumulative prefix-cache lookups (admissions)",
    "prefix_lookup_hits": "cumulative lookups that matched any prefix",
    "prefix_inserted_pages": "cumulative pages adopted into the tree",
    "prefix_evictions": "cumulative prefix-cache pages evicted (LRU)",
    # Decoded-suffix donations (multi-turn serving): adopted pages whose
    # token chunk extends past the donor's prompt — the reuse that lets
    # turn N+1 of a conversation mount turn N's whole transcript.
    "decoded_pages_donated_total":
        "decoded-suffix pages donated into the prefix tree at reap",
    "prefill_tokens_skipped": "prefill rows skipped via prefix reuse",
    # Chunked prefill (serving.ContinuousBatcher prefill_chunk_tokens):
    # backlog = admitted-but-unfinished prefill tokens (the fleet
    # router's prefill-pressure input), chunks = cumulative budgeted
    # chunk dispatches.
    "prefill_backlog_tokens":
        "prompt tokens admitted but not yet prefilled (chunked prefill)",
    "prefill_chunks_total":
        "cumulative chunked-prefill dispatches (per-slot chunks)",
    # Multi-chip sharded serving (shard_map islands over tp): island
    # width and the PER-CHIP pool residency — the 1/tp scaling the
    # sharded_decode bench leg CI-asserts.
    "tp": "tensor-parallel island width (1 = single-chip)",
    "kv_pool_device_bytes":
        "per-chip KV pool residency (pool + scale-plane shard bytes)",
    # Megatron-sliced weights (serving weight_sharding): per-chip weight
    # residency — total, and the WEIGHT_SPECS-sliced subset, which is
    # exactly 1/tp of its unsharded size by construction (the
    # sharded_weights bench leg CI-asserts it). Build-time constants,
    # never live-array reads (the kv_pool_device_bytes contract).
    "weight_device_bytes":
        "per-chip model-weight residency (sliced + replicated leaves)",
    "weight_sliced_device_bytes":
        "per-chip bytes of the Megatron-sliced weight leaves "
        "(exactly 1/tp of their unsharded total)",
    # KV tiering (serving kv_tiering=): host-DRAM second tier + optional
    # disk third tier behind the radix tree (models/paging.py
    # HostTierStore). These keys exist only on tiered engines, so the
    # exposition of every untiered caller stays byte-identical.
    "tier_dram_pages": "KV pages demoted to the host-DRAM tier",
    "tier_dram_capacity": "host-DRAM tier capacity (pages)",
    "tier_disk_pages": "KV pages spilled to the disk tier",
    "tier_pending_demotions":
        "pages reserved for demotion, awaiting step-boundary readback",
    "page_demotions_total": "cumulative KV pages demoted HBM -> host DRAM",
    "page_promotions_total": "cumulative KV pages promoted host DRAM -> HBM",
    "prefix_demoted_pages": "radix-tree nodes whose page is demoted off-pool",
    "tier_spills_total": "cumulative DRAM-tier pages spilled to the disk tier",
    "tier_forgotten_total":
        "cumulative demoted pages forgotten at DRAM capacity (no disk tier)",
    "tier_cancelled_demotions":
        "pending demotions cancelled by a mid-match retain (pins win)",
    "spec_accept_rate": "speculative proposals accepted / proposed",
    "spec_tokens_per_dispatch":
        "tokens committed per active slot per verify dispatch",
    "spec_rewound_tokens_total":
        "cumulative rejected overshoot rows rewound by the lens clamp",
    # Lifecycle robustness (drain/snapshot/restore + watchdog —
    # models/serving.py drain()/restore(), models/snapshot.py).
    "drain_duration_seconds":
        "wall time of the last engine drain (flush + page gather)",
    "restore_duration_seconds":
        "wall time of the last snapshot restore (re-layout + scatter)",
    "requests_resumed_total":
        "interrupted requests resumed by restore/absorb on this engine",
    "requests_shed_total":
        "requests shed to a peer replica via partial drain (fleet tier)",
    "request_errors_total":
        "poison requests failed in isolation (step loop error containment)",
    "last_step_age_seconds":
        "seconds since the last batcher step started (liveness watchdog)",
}


# Per-phase request-lifecycle latency histogram (obs/ tracing): observed
# from the phase durations ContinuousBatcher.pool_metrics() drains
# atomically with the gauges above (one lock snapshot — a scrape can
# never see a phase batch from one step next to a watchdog age from
# another). Sub-millisecond lower buckets: admit/reap are host-side
# bookkeeping phases far below the scheduler's cycle ladder.
PHASE_HISTOGRAM = "tpu_serve_phase_duration_seconds"
PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

# Per-admission prefix-cache hit lengths (tokens), fed from the
# ``prefix_hit_token_batch`` pool_metrics() drains in the same lock
# snapshot as the phase batch. Power-of-two token buckets spanning one
# page to whole mounted conversations; the 0-observations (misses) land
# below the first bucket, so hit-given-lookup is readable off the le=8
# edge. The _sum series is the cumulative hit-token count the old gauge
# carried.
PREFIX_HIT_HISTOGRAM = "tpu_serve_prefix_hit_tokens"
PREFIX_HIT_BUCKETS = (8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
                      1024.0, 2048.0, 4096.0, 8192.0)

# Promoted-hit lengths (tokens served through a DRAM->HBM promotion
# upload per admission), fed from ``promoted_hit_token_batch`` — drained
# in the SAME _obs_mu snapshot as the prefix-hit batch (the torn-read
# rule), present only on tiered engines. Same token buckets: the ratio
# promoted_sum / prefix_hit_sum is the fraction of cache hits the DRAM
# tier rescued from eviction.
PROMOTED_HIT_HISTOGRAM = "tpu_serve_promoted_hit_tokens"

# Info-style metric for the island weight-combine mode (pool_metrics()
# "tp_combine": "all_gather" | "psum" | "replicated" | "none"): value 1
# under {kind=} — the PromQL-friendly encoding of an enum that never
# changes after engine birth, so no stale one-hot cleanup is needed.
TP_COMBINE_INFO = "tpu_serve_tp_combine"

# Adaptive speculative gamma (serving spec_adaptive=True, pool_metrics()
# "spec_gamma_agg": {"min","mean","max"}): the effective verify-window
# spread across active slots under {slot_agg=} — one gauge, three
# aggregate series, the PromQL idiom for a small per-slot distribution
# whose slot cardinality must not leak into the exposition. Non-adaptive
# speculative engines publish the flat configured gamma on all three.
SPEC_GAMMA_GAUGE = "tpu_serve_spec_gamma"

# Per-dispatch speculative accept rates (pool_metrics()
# "spec_accept_batch", drained in the same _obs_mu snapshot as the phase
# batch — the torn-read rule), observed under {proposer=} so a fleet
# mixing bigram/ngram/draft replicas can compare sources side by side.
# Rate buckets are uniform in [0, 1]; the _sum/_count ratio is the mean
# accept rate the cumulative gauge also carries. Registered lazily only
# when a batch is present — non-speculative exposition stays
# byte-identical.
SPEC_ACCEPT_HISTOGRAM = "tpu_serve_spec_accept"
SPEC_ACCEPT_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                       1.0)


def export_serving_pool(registry: "Registry", pool_metrics: Dict[str, float],
                        prefix: str = "tpu_serve_",
                        labels: Optional[Dict[str, str]] = None) -> None:
    """Publish a ``ContinuousBatcher.pool_metrics()`` snapshot as gauges
    (``tpu_serve_page_utilization``, ``tpu_serve_prefix_hit_rate``, ...).
    Keys absent from the snapshot (contiguous layout → {}, prefix cache
    off → no prefix_* keys) are simply skipped, so callers can publish
    unconditionally on every scrape/step.

    The ``phase_durations`` key (present when the engine has a tracer
    attached) is a drained-once batch of ``(phase, seconds)`` pairs from
    the same lock snapshot as the gauges; it folds into the
    ``tpu_serve_phase_duration_seconds{phase=...}`` histogram rather
    than a gauge — durations are a distribution, not a level.

    ``labels`` stamps every gauge value and phase observation with a
    constant label set — the fleet tier publishes each replica under
    ``{replica="r0"}`` so one scrape shows N engines side by side
    (Gauge/Histogram per-label-set series, the same machinery the
    ``phase=`` label rides). ``labels=None`` (every pre-fleet caller)
    writes the unlabeled series — the text exposition stays
    byte-identical."""
    labels = labels or {}
    for key, help_ in SERVING_POOL_GAUGES.items():
        if key in pool_metrics:
            registry.gauge(prefix + key, help_).set(
                pool_metrics[key], **labels)
    phases = pool_metrics.get("phase_durations") or ()
    if phases:
        hist = registry.histogram(
            PHASE_HISTOGRAM,
            "Request-lifecycle phase durations (queue|admit|prefill|"
            "prefill_chunk|decode_chunk|verify|rewind|reap, plus "
            "demote|promote on tiered engines), by phase",
            buckets=PHASE_BUCKETS)
        for phase, seconds in phases:
            hist.observe(float(seconds), phase=str(phase), **labels)
    hits = pool_metrics.get("prefix_hit_token_batch") or ()
    if hits:
        hist = registry.histogram(
            PREFIX_HIT_HISTOGRAM,
            "Prefix-cache hit length per admission, in prompt tokens "
            "(0 = miss; whole mounted conversations land in the tail)",
            buckets=PREFIX_HIT_BUCKETS)
        for tokens in hits:
            hist.observe(float(tokens), **labels)
    promoted = pool_metrics.get("promoted_hit_token_batch") or ()
    if promoted:
        hist = registry.histogram(
            PROMOTED_HIT_HISTOGRAM,
            "Prefix-hit tokens served through a DRAM->HBM promotion "
            "upload, per admission (KV tiering)",
            buckets=PREFIX_HIT_BUCKETS)
        for tokens in promoted:
            hist.observe(float(tokens), **labels)
    combine = pool_metrics.get("tp_combine")
    if combine:
        registry.gauge(
            TP_COMBINE_INFO,
            "island weight-combine mode (Megatron-sliced weights), "
            "info-style: 1 under {kind=all_gather|psum|replicated|none}",
        ).set(1.0, kind=str(combine), **labels)
    gamma_agg = pool_metrics.get("spec_gamma_agg")
    if gamma_agg:
        gauge = registry.gauge(
            SPEC_GAMMA_GAUGE,
            "effective speculative verify window across active slots "
            "(adaptive gamma), under {slot_agg=min|mean|max}")
        for agg, value in gamma_agg.items():
            gauge.set(float(value), slot_agg=str(agg), **labels)
    accepts = pool_metrics.get("spec_accept_batch") or ()
    if accepts:
        proposer = str(pool_metrics.get("spec_proposer", "unknown"))
        hist = registry.histogram(
            SPEC_ACCEPT_HISTOGRAM,
            "Per-dispatch speculative accept rate (accepted / effective "
            "proposals), by proposal source",
            buckets=SPEC_ACCEPT_BUCKETS)
        for rate in accepts:
            hist.observe(float(rate), proposer=proposer, **labels)


# Decode fused→dense downgrade visibility (models/serving.py
# _note_decode_fallback): a config that asks for the Pallas decode kernel
# and silently gets the dense path is a quiet ~10x on cache traffic — the
# counter makes it a dashboard fact instead of a code-reading exercise.
DECODE_FALLBACK_TOTAL = "tpu_serve_decode_fallback_total"


def export_decode_fallbacks(registry: "Registry",
                            counts: Dict[str, float],
                            labels: Optional[Dict[str, str]] = None) -> None:
    """Publish ``serving.decode_fallback_counts()`` as the labeled
    counter ``tpu_serve_decode_fallback_total{reason=}``. The source is
    an absolute process-level count (downgrade DECISIONS, taken at
    trace/engine-build time), so the export incs the delta since the
    last publish — idempotent across scrapes. The baseline is a
    watermark kept ON the registry's counter instance, NOT the counter
    value read back: the source can be RESET
    (serving.reset_decode_fallback_counts — a test-isolation
    affordance, not a production path), and a counter-read baseline
    would silently swallow every downgrade after a reset until the
    count re-exceeded the old watermark. With the watermark, a reset
    observed below the old mark re-bases and the new counts export as
    fresh increments; downgrades that both reset AND regrow past the
    old mark between two exports are indistinguishable from monotonic
    growth and export as the partial delta — the unavoidable limit of
    delta-exporting a resettable source, acceptable because nothing
    resets in production."""
    labels = labels or {}
    c = registry.counter(
        DECODE_FALLBACK_TOTAL,
        "decode_attn='fused' configs downgraded to the dense path, "
        "by reason")
    marks = getattr(c, "_export_watermark", None)
    if marks is None:
        marks = c._export_watermark = {}
    for reason, n in counts.items():
        key = tuple(sorted({**labels, "reason": str(reason)}.items()))
        last = marks.get(key, 0.0)
        delta = float(n) - last if float(n) >= last else float(n)
        if delta > 0:
            c.inc(delta, reason=str(reason), **labels)
        marks[key] = float(n)


# Fleet-router counters (fleet/router.py increments these; the names are
# the metrics contract the README documents). ``routed`` carries
# {replica=, policy=} — policy "affinity" (cache-aware scoring) vs
# "degraded" (stale/unreachable summaries → round-robin).
FLEET_ROUTED_TOTAL = "tpu_fleet_routed_requests_total"
FLEET_SHED_TOTAL = "tpu_fleet_shed_requests_total"
FLEET_MIGRATED_TOTAL = "tpu_fleet_migrated_requests_total"
FLEET_AFFINITY_HITS_TOTAL = "tpu_fleet_prefix_affinity_hits_total"
# Crash tolerance (fleet/health.py + fleet/journal.py): failovers =
# dead-replica declarations that replayed journaled requests; replayed
# tokens = the redundant re-decoded verify window per failover (bounded
# by journaled delivered tokens — the chaos CI leg asserts it); lost =
# requests that vanished without a journal record (MUST stay 0 — the
# zero-loss contract); expired = per-request deadlines enforced at the
# router (submit(deadline_s=)).
FLEET_FAILOVERS_TOTAL = "tpu_fleet_failovers_total"
FLEET_REPLAYED_TOKENS_TOTAL = "tpu_fleet_replayed_tokens_total"
FLEET_LOST_TOTAL = "tpu_fleet_requests_lost_total"
FLEET_EXPIRED_TOTAL = "tpu_fleet_deadline_expired_total"
# Disaggregated pools (fleet/router.py pools=): handoffs = completed
# prefill→decode phase-boundary migrations (partial drain → absorb),
# labeled {src=,dst=}. The duration histogram covers drain+absorb+
# re-point wall time and is registered LAZILY at the first handoff — a
# Histogram eagerly exposes zeroed unlabeled series at construction,
# and a colocated fleet's exposition must stay byte-identical to
# pre-disagg output (the PR 8 pin convention).
FLEET_HANDOFFS_TOTAL = "tpu_fleet_handoffs_total"
FLEET_HANDOFF_DURATION = "tpu_fleet_handoff_duration_seconds"
FLEET_COUNTERS = {
    FLEET_ROUTED_TOTAL:
        "requests admitted through the fleet router, by replica/policy",
    FLEET_SHED_TOTAL:
        "requests shed out of a hot replica (partial drain), by source",
    FLEET_MIGRATED_TOTAL:
        "shed requests successfully absorbed, by target replica",
    FLEET_AFFINITY_HITS_TOTAL:
        "routed requests whose chosen replica had a non-zero cached "
        "prefix match",
    FLEET_FAILOVERS_TOTAL:
        "replica deaths whose in-flight requests were replayed onto "
        "survivors, by (dead) replica",
    FLEET_REPLAYED_TOKENS_TOTAL:
        "journaled tokens re-decoded for replay verification "
        "(bounded rework: <= delivered tokens per failover)",
    FLEET_LOST_TOTAL:
        "requests lost without a journal record (zero-loss contract: "
        "must stay 0)",
    FLEET_EXPIRED_TOTAL:
        "requests failed at the router for exceeding their deadline",
    FLEET_HANDOFFS_TOTAL:
        "prefill→decode pool handoffs (drain→absorb at the phase "
        "boundary), by source/target replica",
}

# Histogram help texts live here (not inline at the registration site)
# for the same reason the counter/gauge catalogs do: the catalog test
# pins every tpu_fleet_* family to a non-empty HELP string.
FLEET_HISTOGRAMS = {
    FLEET_HANDOFF_DURATION:
        "wall seconds per handoff: partial drain + absorb + fleet-id "
        "re-point (lazily registered at the first handoff)",
}

# Fleet gauges: replica_state is a one-hot {replica=,state=} family (1
# on the current state, 0 elsewhere — the PromQL-friendly encoding of an
# enum); journal size is the router's open-entry count (in-flight
# requests whose delivery record would drive a replay right now).
FLEET_REPLICA_STATE = "tpu_fleet_replica_state"
FLEET_JOURNAL_SIZE = "tpu_fleet_journal_inflight_requests"
FLEET_REPLICA_ROLE = "tpu_fleet_replica_role"
FLEET_GAUGES = {
    FLEET_REPLICA_STATE:
        "replica health state (fleet/health.py), one-hot over "
        "{replica=,state=live|suspect|dead|quarantined|rejoining}",
    FLEET_JOURNAL_SIZE:
        "open request-journal entries (in-flight fleet requests)",
    FLEET_REPLICA_ROLE:
        "replica pool role (disaggregated serving), one-hot over "
        "{replica=,role=mixed|prefill|decode}",
}


class MetricsServer:
    """Serves a Registry at /metrics (Prometheus text exposition)."""

    def __init__(self, registry: Registry, host: str = "127.0.0.1", port: int = 0) -> None:
        self.registry = registry
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                body = reg.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request logging
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
