"""Workload-side enforcement of the scheduler-injected sharing limits.

The reference's co-location throttling works because the CUDA runtime
itself honors the MPS env the plugin injects —
CUDA_MPS_ACTIVE_THREAD_PERCENTAGE / CUDA_MPS_PINNED_DEVICE_MEM_LIMIT
(/root/reference/pkg/plugins/gpu_plugin/gpu_plugins.go:896-917). No TPU
runtime reads our analogues (TPU_HBM_LIMIT_BYTES /
TPU_DUTY_CYCLE_PERCENTAGE, plugins/tpu.py PostBind), so without this
module the caps were decorative (VERDICT r4 missing #1): a co-located pod
could eat the whole HBM and the whole duty cycle. Every workload
entrypoint (models/llama.py, resnet.py, bert.py mains) calls
``apply_env_limits()`` before touching the device:

- **HBM**: translate the partition's byte budget into
  ``XLA_PYTHON_CLIENT_MEM_FRACTION`` BEFORE the JAX backend initializes —
  the XLA client allocator then hard-caps this process's device arena at
  its share, so a pod that overflows OOMs itself instead of evicting its
  neighbor's working set. This is the enforcement seam TPU actually
  offers: there is no per-process device MMU partition to lean on, but
  every byte a JAX workload allocates goes through this client arena.
- **Duty cycle**: a host-side pacing throttle between dispatched steps —
  after each active interval of t seconds the workload sleeps
  t*(100-pct)/pct, so its duty ratio converges to pct/100 and the
  co-tenant gets the remaining compute windows. Inter-step host pacing is
  the TPU equivalent of MPS's thread-percentage cap: TPU programs are not
  preemptible mid-dispatch, so the grain is the step, exactly like the
  reference's grain is the kernel.
"""
from __future__ import annotations

import os
import time
from typing import Mapping, MutableMapping, Optional

ENV_HBM_LIMIT = "TPU_HBM_LIMIT_BYTES"
ENV_DUTY_PCT = "TPU_DUTY_CYCLE_PERCENTAGE"
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_ACCELERATOR = "TPU_ACCELERATOR_TYPE"
ENV_XLA_MEM_FRACTION = "XLA_PYTHON_CLIENT_MEM_FRACTION"

# XLA rejects a zero arena at init; a 1% floor keeps a fully-debited cap
# (hbm_limit_bytes == 0 on a saturated partition — tpu.py keys the inject
# on duty_pct for exactly this case) enforceable without bricking startup:
# the pod can initialize, and its first real allocation OOMs — the correct
# party fails.
MIN_FRACTION = 0.01


def _per_chip_hbm_bytes(env: Mapping[str, str]) -> Optional[int]:
    """Nameplate HBM per chip from the injected accelerator type — the
    same TPUGen table the scheduler used to compute the cap, so the
    fraction inverts the cap exactly."""
    from ..api.topology import TPUGen

    try:
        gen = TPUGen(env.get(ENV_ACCELERATOR, ""))
    except ValueError:
        return None
    return int(gen.hbm_gib * (1 << 30))


def apply_hbm_limit(
    env: Optional[MutableMapping[str, str]] = None,
) -> Optional[float]:
    """Translate TPU_HBM_LIMIT_BYTES into XLA_PYTHON_CLIENT_MEM_FRACTION.

    Returns the fraction set, or None when no cap applies (env absent or
    malformed, accelerator type unknown). Never overrides an explicit
    operator-set fraction. MUST run before the JAX backend initializes —
    the flag is read once at client creation."""
    if env is None:
        env = os.environ
    raw = env.get(ENV_HBM_LIMIT)
    if not raw:
        return None
    try:
        limit = int(raw)
    except ValueError:
        return None
    if limit < 0:
        return None
    per_chip = _per_chip_hbm_bytes(env)
    if per_chip is None:
        return None
    chips = len([c for c in env.get(ENV_VISIBLE_CHIPS, "").split(",") if c])
    chips = max(1, chips)
    # The scheduler's cap is the partition total; the XLA fraction is
    # per-device, and the runtime exposes exactly the partition's chips to
    # this pod (TPU_VISIBLE_CHIPS), so divide evenly.
    fraction = max(MIN_FRACTION, min(1.0, (limit / chips) / per_chip))
    if ENV_XLA_MEM_FRACTION in env:
        return None                       # operator override wins
    env[ENV_XLA_MEM_FRACTION] = f"{fraction:.4f}"
    return fraction


class DutyCycleThrottle:
    """Inter-step duty-cycle pacing: ``pace(active_s)`` (or the context
    manager) sleeps so that active time stays at ``pct`` percent of wall
    time. Sleep is computed from a running balance rather than per call,
    so many short steps throttle as accurately as few long ones — and
    NATURAL idle between pace() calls pays the debt down first: a loop
    that already sleeps (the 1 Hz publish pacing in the serve loops) is
    under its duty budget and must not be slowed further. Banked idle is
    capped (credit_cap_s) so a long warmup can't buy an unthrottled burst
    later."""

    def __init__(self, pct: int, credit_cap_s: float = 1.0) -> None:
        if not 1 <= pct <= 100:
            raise ValueError(f"duty pct must be in [1, 100], got {pct}")
        self.pct = pct
        self.credit_cap_s = credit_cap_s
        self._debt_s = 0.0
        self._last_mark: Optional[float] = None
        self._t0: Optional[float] = None

    def pace(self, active_s: float) -> float:
        """Record one active interval; sleep off the accumulated idle debt
        (returns the seconds slept)."""
        active_s = max(0.0, active_s)
        now = time.perf_counter()
        if self._last_mark is not None:
            idle = max(0.0, (now - self._last_mark) - active_s)
            self._debt_s = max(-self.credit_cap_s, self._debt_s - idle)
        self._debt_s += active_s * (100.0 - self.pct) / self.pct
        slept = 0.0
        if self._debt_s > 1e-4:
            slept = self._debt_s
            time.sleep(slept)
            self._debt_s = 0.0
        self._last_mark = time.perf_counter()
        return slept

    def __enter__(self) -> "DutyCycleThrottle":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t0, self._t0 = self._t0, None
        if t0 is not None:
            self.pace(time.perf_counter() - t0)


def duty_throttle(
    env: Optional[Mapping[str, str]] = None,
) -> Optional[DutyCycleThrottle]:
    """Build the throttle from TPU_DUTY_CYCLE_PERCENTAGE; None when the
    pod is unthrottled (absent, malformed, or >= 100)."""
    if env is None:
        env = os.environ
    raw = env.get(ENV_DUTY_PCT)
    if not raw:
        return None
    try:
        pct = int(raw)
    except ValueError:
        return None
    if pct >= 100 or pct < 1:
        return None
    return DutyCycleThrottle(pct)


def apply_env_limits(
    env: Optional[MutableMapping[str, str]] = None,
) -> Optional[DutyCycleThrottle]:
    """The one call every workload entrypoint makes before touching JAX:
    cap the XLA arena at the injected HBM share and return the duty-cycle
    throttle (None = run unthrottled)."""
    apply_hbm_limit(env)
    return duty_throttle(env)
