"""Service/pod discovery helpers — parity with /root/reference/utils/utils.go.

The reference locates its Redis and dcgm-exporter endpoints by pod-name
substring (FindNodesIPFromPod utils.go:59-70, GetNodesDcgmPod utils.go:72-99)
— a convention we keep as the *fallback* while preferring explicit config
(config.Registry/config.Metrics endpoints) because hardcoded substrings are
one of the reference's weaknesses (SURVEY.md §5 "Config / flag system").

No panic-on-error Check() (utils.go:18-22): errors are returned/raised and
handled by callers.
"""
from __future__ import annotations

from typing import List, Optional

from ..api.objects import Pod
from ..cluster.resources import Descriptor


def exists_substring(items: List[str], sub: str) -> bool:
    """Parity with utils.Exists (utils.go:101-108)."""
    return any(sub in s for s in items)


def find_node_from_pod(desc: Descriptor, pod_substring: str, namespace: str) -> Optional[str]:
    """Node name hosting the first pod whose name contains ``pod_substring``
    (parity: FindNodeFromPod utils.go:24-57)."""
    for pod in desc.list_pods(namespace=namespace):
        if pod_substring in pod.metadata.name:
            return pod.spec.node_name or None
    return None


def find_nodes_ip_from_pod(
    desc: Descriptor, pod_substring: str, namespace: str
) -> List[str]:
    """Addresses of nodes hosting pods whose name contains ``pod_substring``
    (parity: FindNodesIPFromPod utils.go:59-70 — how the reference discovers
    Redis by looking for a pod named '*-0' in namespace 'redis')."""
    out: List[str] = []
    for pod in desc.list_pods(namespace=namespace):
        if pod_substring in pod.metadata.name and pod.spec.node_name:
            try:
                node = desc.get_node(pod.spec.node_name)
            except Exception:
                continue
            if node.status.addresses:
                out.append(node.status.addresses[0])
            else:
                out.append(pod.spec.node_name)
    return out


def find_agent_pod_on_node(
    desc: Descriptor, node_name: str, agent_substring: str = "tpu-agent", namespace: Optional[str] = None
) -> Optional[Pod]:
    """Find the node's metrics-agent pod (parity: GetNodesDcgmPod
    utils.go:72-99, which looks for the 'dcgm' pod on a node)."""
    for pod in desc.list_pods(namespace=namespace, node_name=node_name):
        if agent_substring in pod.metadata.name:
            return pod
    return None
