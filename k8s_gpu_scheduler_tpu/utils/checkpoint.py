"""Checkpoint / resume — the aux subsystem the reference lacks entirely.

SURVEY.md §5: "Checkpoint / resume: none in-process" — the reference's only
durable state is Redis AOF + ConfigMaps. Our framework trains real models,
so the workload layer gets first-class checkpointing built on orbax (the
TPU-native checkpoint library: async, sharding-aware — a restore lands
shards directly on the same mesh layout that saved them):

    ckpt = TrainCheckpointer(dir, max_to_keep=3)
    step, state = ckpt.restore_or(init_fn)      # elastic restart
    ...
    ckpt.maybe_save(step, state, every=100)

Gang pods killed by the scheduler's all-or-nothing collapse (plugins/gang)
resume from the latest step when the controller recreates them — that pair
is the framework's elastic-recovery story.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Optional, Tuple

log = logging.getLogger(__name__)


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        from etils import epath

        self._ocp = ocp
        self._dir = epath.Path(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Async save of a pytree (params/opt_state/anything jax). Returns
        whether a save was performed."""
        saved = self._mgr.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        return bool(saved)

    def maybe_save(self, step: int, state: Any, every: int = 100) -> bool:
        if every <= 0 or step % every:
            return False
        return self.save(step, state)

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore the pytree saved at ``step`` (default: latest). ``like``
        (an abstract/concrete pytree) restores onto matching shardings —
        pass the freshly-initialized state for multi-host restores."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        args = (
            self._ocp.args.StandardRestore(like)
            if like is not None
            else self._ocp.args.StandardRestore()
        )
        return self._mgr.restore(step, args=args)

    def restore_or(self, init_fn: Callable[[], Any]) -> Tuple[int, Any]:
        """(step, state): latest checkpoint if one exists, else
        ``(0, init_fn())`` — the elastic-restart entrypoint. The fresh init
        is always built and used as the restore template: it carries the
        pytree STRUCTURE (orbax round-trips tuples/NamedTuples as lists
        otherwise) and the target shardings for multi-host restores."""
        step = self.latest_step()
        init = init_fn()
        if step is None:
            return 0, init
        log.info("resuming from checkpoint step %d under %s", step, self._dir)
        return step, self.restore(step, like=init)

    def wait(self) -> None:
        """Block until pending async saves land (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
