from .discovery import (  # noqa: F401
    exists_substring,
    find_agent_pod_on_node,
    find_node_from_pod,
    find_nodes_ip_from_pod,
)
