"""Bounded retry with exponential backoff, jitter and deadlines.

The control-plane clients (registry/client.py, recommender/client.py)
talk to services that flap under exactly the conditions this scheduler
exists for — spot preemption, node churn, rolling restarts. The failure
mode this module prevents is the one graftcheck's retry-lint flags: an
unbounded ``while True: try/except/continue`` loop that turns a dead
dependency into a hung scheduler thread. Every retry here is bounded
THREE ways — attempt count, per-attempt backoff cap, and a wall-clock
deadline — and backoff is jittered so a fleet of clients whose server
just restarted doesn't reconnect in lockstep (the thundering-herd
argument from the Google SRE book, the same reason client-go's
wait.Backoff carries a Jitter factor).

``RetryPolicy`` is data, not behavior: callers own their retry loop
(the registry client's is idempotency-aware — a command that died
mid-flight must NOT blindly re-send), and ``retry_call`` is the plain
wrapper for callers without such constraints (the recommender client).
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry shape: up to ``attempts`` tries in total, sleeping
    ``base_s * multiplier**(attempt-1)`` (capped at ``max_s``, jittered
    ±``jitter`` fraction) between them, never past ``deadline_s`` of
    wall clock from the first attempt. ``attempts=1`` means no retry."""

    attempts: int = 4
    base_s: float = 0.02
    multiplier: float = 2.0
    max_s: float = 1.0
    jitter: float = 0.5
    deadline_s: float = float("inf")

    def __post_init__(self) -> None:
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def backoff_s(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        """Sleep before retry number ``attempt`` (1-based: the sleep
        between the first failure and the second try is attempt 1)."""
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        delay = min(self.base_s * self.multiplier ** (attempt - 1),
                    self.max_s)
        if self.jitter:
            u = (rng.random() if rng is not None else random.random())
            delay *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return max(delay, 0.0)

    def deadline_from(self, now: float) -> float:
        return now + self.deadline_s

    def give_up(self, attempt: int, now: float, deadline: float,
                next_delay_s: float = 0.0) -> bool:
        """True when retry number ``attempt`` must NOT happen: the
        attempt bound is spent, or sleeping ``next_delay_s`` would land
        past the deadline (waking up only to time out is worse than
        failing now — the caller gets its error while there is still
        deadline budget to act on it)."""
        return attempt >= self.attempts or now + next_delay_s >= deadline


def retry_call(
    fn: Callable,
    policy: RetryPolicy,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    rng: Optional[random.Random] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` under ``policy``: retry on ``retry_on`` exceptions
    with jittered exponential backoff until the attempt bound or the
    deadline is spent, then re-raise the LAST failure. ``on_retry`` is
    invoked once per retry (after the failure, before the sleep) — the
    metrics hook behind ``tpu_sched_rpc_retries_total``."""
    deadline = policy.deadline_from(clock())
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            delay = policy.backoff_s(attempt, rng=rng)
            if policy.give_up(attempt, clock(), deadline, delay):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)
