"""Parallelism layer — device meshes, sharding rules, and collectives.

The reference has NO data-plane parallelism (SURVEY.md §2: "None of
DP/TP/PP/SP/EP/CP/ring-attention/Ulysses exist"); its scheduler places
single-GPU pods. Our framework schedules multi-host JAX jobs, so the
workloads it places — and benches with — need a real parallel substrate:
meshes with dp/fsdp/tp/sp axes, NamedSharding rules, and sequence-parallel
attention built on XLA collectives over ICI (ppermute ring, all_to_all
Ulysses) rather than NCCL/MPI.
"""
from .distributed import distributed_init_from_env, worker_addresses
from .mesh import MeshSpec, make_mesh, multislice_mesh, named_sharding
from .sharding import logical_axis_rules, shard_params_spec

__all__ = [
    "MeshSpec",
    "make_mesh",
    "multislice_mesh",
    "named_sharding",
    "logical_axis_rules",
    "shard_params_spec",
    "distributed_init_from_env",
    "worker_addresses",
]
