"""Multi-host bootstrap from the scheduler-injected environment.

Gang PostBind (plugins/gang.py) writes three env vars through each member's
EnvFrom ConfigMap:

  TPU_WORKER_HOSTNAMES  comma-separated pod-reachable addresses, worker order
  TPU_WORKER_ID         this member's index in that list
  TPU_WORKER_COUNT      gang size

``distributed_init_from_env`` turns them into a ``jax.distributed``
rendezvous: worker 0's address is the coordinator. This is the consuming
half of the contract — the producing half (stable pod DNS / pod IP instead
of node names) is tested end-to-end in tests/test_plugins.py and the
2-process CPU smoke in tests/test_distributed.py.

The reference has no analogue: its injected env (CUDA_VISIBLE_DEVICES,
gpu_plugins.go:910-920) is node-local, and its multi-node story is whatever
NCCL/MPI launcher the workload brings. Here the scheduler IS the launcher.
"""
from __future__ import annotations

import os
from typing import Mapping, Optional

COORDINATOR_PORT = 8476


def worker_addresses(env: Optional[Mapping[str, str]] = None) -> list:
    src = os.environ if env is None else env
    return [h for h in src.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]


def self_worker_id(
    addresses: list, env: Optional[Mapping[str, str]] = None
) -> Optional[int]:
    """This worker's index in the gang address list, derived from its OWN
    identity: the entry whose first DNS label equals this pod's hostname
    ($HOSTNAME == pod name inside the container).

    This is the authoritative id for gangs whose members share one EnvFrom
    ConfigMap (deploy/workloads/llama-gang.yaml): each member's PostBind
    writes its scalar TPU_WORKER_ID into the SAME map, so the last write
    wins and every worker would read an identical id — a guaranteed
    rendezvous deadlock. The address list, by contrast, is identical across
    members by construction (plugins/gang.py _member_address is a pure
    function of pod spec + node assignment), so matching ourselves against
    it is race-free. Returns None when no entry matches (plain-pod
    gangs injected with node addresses)."""
    src = os.environ if env is None else env
    hostname = src.get("HOSTNAME", "")
    if not hostname:
        return None
    for i, addr in enumerate(addresses):
        if addr == hostname or addr.split(".", 1)[0] == hostname:
            return i
    return None


def distributed_init_from_env(
    env: Optional[Mapping[str, str]] = None,
    coordinator_port: int = COORDINATOR_PORT,
    **initialize_kwargs,
) -> bool:
    """Initialize jax.distributed from the gang env. Returns True iff a
    multi-worker rendezvous was performed (single-worker / un-injected pods
    return False and stay single-process). Extra kwargs pass through to
    ``jax.distributed.initialize`` (tests pass ``cluster_detection_method``
    etc.).

    process_id preference: self-derived from $HOSTNAME vs the address list
    (shared-ConfigMap-safe — see self_worker_id), then the injected
    TPU_WORKER_ID scalar (per-pod-ConfigMap gangs, hostNetwork gangs)."""
    src = os.environ if env is None else env
    addresses = worker_addresses(src)
    if len(addresses) <= 1:
        return False
    worker_id = self_worker_id(addresses, src)
    if worker_id is None:
        worker_id = int(src.get("TPU_WORKER_ID", "0") or 0)
    count = int(src.get("TPU_WORKER_COUNT", "") or len(addresses))
    import jax

    jax.distributed.initialize(
        coordinator_address=f"{addresses[0]}:{coordinator_port}",
        num_processes=count,
        process_id=worker_id,
        **initialize_kwargs,
    )
    return True
