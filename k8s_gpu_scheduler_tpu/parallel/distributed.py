"""Multi-host bootstrap from the scheduler-injected environment.

Gang PostBind (plugins/gang.py) writes three env vars through each member's
EnvFrom ConfigMap:

  TPU_WORKER_HOSTNAMES  comma-separated pod-reachable addresses, worker order
  TPU_WORKER_ID         this member's index in that list
  TPU_WORKER_COUNT      gang size

``distributed_init_from_env`` turns them into a ``jax.distributed``
rendezvous: worker 0's address is the coordinator. This is the consuming
half of the contract — the producing half (stable pod DNS / pod IP instead
of node names) is tested end-to-end in tests/test_plugins.py and the
2-process CPU smoke in tests/test_distributed.py.

The reference has no analogue: its injected env (CUDA_VISIBLE_DEVICES,
gpu_plugins.go:910-920) is node-local, and its multi-node story is whatever
NCCL/MPI launcher the workload brings. Here the scheduler IS the launcher.
"""
from __future__ import annotations

import os
from typing import Mapping, Optional

COORDINATOR_PORT = 8476


def worker_addresses(env: Optional[Mapping[str, str]] = None) -> list:
    src = os.environ if env is None else env
    return [h for h in src.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]


def distributed_init_from_env(
    env: Optional[Mapping[str, str]] = None,
    coordinator_port: int = COORDINATOR_PORT,
    **initialize_kwargs,
) -> bool:
    """Initialize jax.distributed from the gang env. Returns True iff a
    multi-worker rendezvous was performed (single-worker / un-injected pods
    return False and stay single-process). Extra kwargs pass through to
    ``jax.distributed.initialize`` (tests pass ``cluster_detection_method``
    etc.)."""
    src = os.environ if env is None else env
    addresses = worker_addresses(src)
    if len(addresses) <= 1:
        return False
    worker_id = int(src.get("TPU_WORKER_ID", "0") or 0)
    count = int(src.get("TPU_WORKER_COUNT", "") or len(addresses))
    import jax

    jax.distributed.initialize(
        coordinator_address=f"{addresses[0]}:{coordinator_port}",
        num_processes=count,
        process_id=worker_id,
        **initialize_kwargs,
    )
    return True
