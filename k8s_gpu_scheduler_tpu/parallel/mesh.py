"""Device-mesh construction for dp/fsdp/tp/sp layouts.

TPU scaling rides `jax.sharding.Mesh` + NamedSharding: pick a mesh whose
axes map onto the slice's ICI torus, annotate shardings, and let XLA insert
the collectives. `make_mesh` uses `mesh_utils.create_device_mesh` so axis
order follows the physical torus (innermost axis = fastest ICI ring) —
model-parallel axes (tp, sp) should be innermost, data-parallel outermost,
mirroring how a gang placed by our scheduler spans hosts (outer axes cross
hosts over DCN/outer ICI, inner axes stay intra-host).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    """Named mesh shape, e.g. {'dp': 2, 'fsdp': 2, 'tp': 2}. Axis size 1 is
    legal and keeps the axis name addressable (so one model definition runs
    from 1 chip to a pod)."""

    axes: Dict[str, int] = field(default_factory=dict)

    @property
    def names(self) -> tuple:
        return tuple(self.axes.keys())

    @property
    def shape(self) -> tuple:
        return tuple(self.axes.values())

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.axes else 1

    @staticmethod
    def for_devices(n: int, fsdp: int = 1, sp: int = 1, tp: int = 1,
                    ep: int = 1) -> "MeshSpec":
        """Default 5-axis layout for n devices: fill fsdp/sp/ep/tp as asked,
        rest is dp. All five axis names always exist (size 1 where unused) so
        one set of PartitionSpecs works at any scale. ep (expert parallelism,
        ops/moe.py) sits between sp and tp: expert all_to_alls are bulkier
        than tp all-reduces but rarer, so tp keeps the innermost (fastest
        ICI) ring."""
        denom = fsdp * sp * ep * tp
        if n % denom:
            raise ValueError(
                f"{n} devices not divisible by fsdp*sp*ep*tp={denom}")
        return MeshSpec({"dp": n // denom, "fsdp": fsdp, "sp": sp, "ep": ep,
                         "tp": tp})


def multislice_mesh(n_slices: int, fsdp: int = 1, sp: int = 1, tp: int = 1,
                    ep: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh for a DCN-spanning gang (GKE multislice): the OUTER dp axis is
    the slice index — its collectives (the data-parallel gradient
    all-reduce) cross slices over DCN, while fsdp/sp/ep/tp stay inside each
    slice's ICI. Device order must be SLICE-MAJOR (slice 0's devices first),
    which is exactly the worker-id order the gang plugin injects
    (plugins/gang.py post_bind sorts members slice-group-major, and
    jax.devices() follows process ids). The standard multislice recipe: DP
    between slices, model parallelism within — DCN bandwidth is orders of
    magnitude below ICI, and DP's one all-reduce per step is the only
    traffic that tolerates it. Built directly from the reshaped device
    array, NOT mesh_utils (which optimizes for a single torus and would
    interleave devices across the slice boundary)."""
    devices = list(devices) if devices is not None else jax.devices()
    per_slice = fsdp * sp * ep * tp
    need = n_slices * per_slice
    if need > len(devices):
        raise ValueError(
            f"multislice mesh {n_slices}x{per_slice} needs {need} devices, "
            f"have {len(devices)}")
    grid = np.array(devices[:need], dtype=object).reshape(
        (n_slices, fsdp, sp, ep, tp))
    return Mesh(grid, ("dp", "fsdp", "sp", "ep", "tp"))


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    if spec.size > len(devices):
        raise ValueError(f"mesh {spec.axes} needs {spec.size} devices, have {len(devices)}")
    grid = mesh_utils.create_device_mesh(spec.shape, devices=devices[: spec.size])
    return Mesh(grid, spec.names)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
