"""Sharding rules: logical axis names → mesh axes → NamedShardings.

Megatron-style tensor parallelism expressed the JAX way: every parameter
declares logical axes ('embed', 'mlp', 'heads', 'vocab'...); one rules table
maps logical axes to mesh axes; `shard_params_spec` walks a params pytree of
`(path, shape)` and emits PartitionSpecs. XLA's GSPMD partitioner then
inserts the all-reduces a hand-written NCCL backend would need explicit
calls for.

Conventions (standard 1D-tp transformer):
- column-parallel inputs→hidden weights shard the OUTPUT axis on tp
  (q/k/v/gate/up projections, logical axis 'heads'/'mlp');
- row-parallel hidden→outputs shard the INPUT axis on tp (o/down
  projections) — the following psum is XLA-inserted;
- fsdp shards the remaining large axis ('embed') of every weight;
- activations: batch on ('dp','fsdp'), sequence on 'sp' (ring attention),
  heads on 'tp'.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
from jax.sharding import PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer jax exposes ``jax.shard_map``
    with ``check_vma``; 0.4.x ships ``jax.experimental.shard_map.
    shard_map`` with ``check_rep`` (same meaning, earlier name); a middle
    window promoted the function to top level while still naming the
    kwarg ``check_rep`` — so the kwarg is chosen from the actual
    SIGNATURE, never from where the function lives. Every shard_map
    island in models/ and the tests goes through this one shim, so a jax
    upgrade or downgrade is a one-line change instead of a 12-test
    breakage."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    kwarg = ("check_vma"
             if "check_vma" in inspect.signature(sm).parameters
             else "check_rep")
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{kwarg: check_vma})

# logical axis -> mesh axis (None = replicate). The sp axis never shards
# WEIGHTS — it only shards the sequence dimension of activations.
DEFAULT_RULES: Dict[str, object] = {
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "head_dim": None,
    "norm": None,
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    # MoE (ops/moe.py): the stacked expert dim shards over ep — GSPMD
    # turns the dispatch/combine einsums into all_to_alls over that axis.
    "expert": "ep",
    # Paged serving pool (models/serving.py): layer stack and page pool
    # replicated, kv heads on tp — the activation convention ("heads" on
    # tp) applied to the KV page pool, so each chip holds Hkv/tp heads of
    # every page. The graftcheck GSPMD pass (analysis/gspmd.py) audits
    # cache/pool annotations against these entries.
    "layers": None,
    "pages": None,
    "page": None,
}

# Logical axes of the paged KV pool [L, n_pages, page_size, Hkv, hd] —
# `spec_for(KV_POOL_AXES, DEFAULT_RULES)` is the pool PartitionSpec the
# serving islands and the GSPMD audit both derive from this one table.
KV_POOL_AXES: Tuple[str, ...] = ("layers", "pages", "page", "kv_heads",
                                 "head_dim")

# Megatron-sliced SERVING weights (models/serving.py weight_sharding):
# param-leaf name → slice kind over the layer-stacked [L, K, N] matmul
# view. "column" shards the OUTPUT axis N (q/k/v and MLP gate/up — each
# chip computes its own contiguous head/ffn family directly, no
# combine); "row" shards the INPUT axis K (o and MLP down — the shard
# contracts its 1/tp slice and a per-block combine reassembles:
# all_gather the weight+activation for a movement-only byte-identical
# result, or psum the partial products for less compute/traffic).
# Everything not named here (embed, norms, lm_head) replicates. The
# serving engine BUILDS its per-leaf PartitionSpecs from this table
# (models/llama.py serving_weight_specs) and the graftcheck GSPMD/
# traffic audits derive their expected island mappings from it, so the
# runtime and the guard rails cannot drift.
WEIGHT_SPECS: Dict[str, str] = {
    "wq": "column",
    "wk": "column",
    "wv": "column",
    "w_gate": "column",
    "w_up": "column",
    "wo": "row",
    "w_down": "row",
}
# Axis index of the slice inside the stacked [L, K, N] serving layout.
WEIGHT_COLUMN_DIM, WEIGHT_ROW_DIM = 2, 1


def weight_slice_spec(kind: str, rules: Dict[str, object] = None) -> P:
    """PartitionSpec of one stacked [L, K, N] serving weight for a
    WEIGHT_SPECS kind — the tp mesh axis comes from the SAME rules-table
    entry the pool derives its kv-heads mapping from."""
    rules = rules or DEFAULT_RULES
    tp = rules["kv_heads"]
    if kind == "column":
        return P(None, None, tp)
    if kind == "row":
        return P(None, tp, None)
    raise ValueError(f"unknown weight slice kind {kind!r}")


def logical_axis_rules(overrides: Dict[str, object] = None) -> Dict[str, object]:
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)
    return rules


def spec_for(logical_axes: Tuple[str, ...], rules: Dict[str, object]) -> P:
    return P(*(rules.get(a) for a in logical_axes))


def shard_params_spec(param_axes, rules: Dict[str, object] = None):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    import jax

    rules = rules or DEFAULT_RULES
    return jax.tree.map(
        lambda axes: spec_for(axes, rules),
        param_axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )
