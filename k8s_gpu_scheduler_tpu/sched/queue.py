"""Scheduling queue: active heap + unschedulable backoff.

Upstream kube-scheduler's PriorityQueue (active / backoff / unschedulable
pools with event-driven moves); the reference inherits it unmodified
(SURVEY.md §3.1). Ours keeps the same three-pool design:

- active: heap ordered by (−priority, creation time) — FIFO within equal
  priority (priority from the ``tpu.sched/priority`` annotation).
- backoff: unschedulable pods re-enter active after exponential backoff.
- cluster events (node add/update, pod delete) flush backoff early via
  ``move_all_to_active`` so capacity freed now is used now.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import Dict, List, Optional, Tuple

from ..api.objects import Pod
from ..obs import SYSTEM_CLOCK

ANN_PRIORITY = "tpu.sched/priority"


def pod_priority(pod: Pod) -> int:
    try:
        return int(pod.metadata.annotations.get(ANN_PRIORITY, "0"))
    except ValueError:
        return 0


class SchedulingQueue:
    def __init__(self, backoff_initial_s: float = 1.0, backoff_max_s: float = 10.0,
                 clock=None) -> None:
        # Injected time source (obs.Clock): backoff readiness and queue-wait
        # timestamps are DURATION math and ride the monotonic clock; tests
        # can pass a VirtualClock and step backoff deterministically.
        self._clock = clock or SYSTEM_CLOCK
        self._mu = threading.Condition()
        self._heap: List[Tuple[int, float, int, Pod]] = []
        self._queued_uids: Dict[str, int] = {}  # uid -> attempt count
        self._backoff: Dict[str, Tuple[float, Pod]] = {}  # uid -> (ready_at, pod)
        # uid -> first-enqueue monotonic timestamp: the scheduler's
        # sched_queue span measures pod-arrival -> pop from it (survives
        # backoff round-trips — queue wait is e2e, not per-attempt).
        self._enqueued: Dict[str, float] = {}
        self._seq = itertools.count()
        self._backoff_initial = backoff_initial_s
        self._backoff_max = backoff_max_s
        self._closed = False

    # -- producers ---------------------------------------------------------
    def add(self, pod: Pod) -> None:
        """New pending pod (informer on_add)."""
        with self._mu:
            if pod.metadata.uid in self._queued_uids or pod.metadata.uid in self._backoff:
                return
            self._queued_uids[pod.metadata.uid] = 0
            self._enqueued.setdefault(pod.metadata.uid,
                                      self._clock.monotonic())
            self._push_locked(pod)
            self._mu.notify()

    def add_unschedulable(self, pod: Pod) -> None:
        """Failed cycle → backoff pool with exponential delay."""
        with self._mu:
            attempts = self._queued_uids.get(pod.metadata.uid, 0) + 1
            self._queued_uids[pod.metadata.uid] = attempts
            delay = min(self._backoff_initial * (2 ** (attempts - 1)), self._backoff_max)
            self._backoff[pod.metadata.uid] = (
                self._clock.monotonic() + delay, pod)
            self._enqueued.setdefault(pod.metadata.uid,
                                      self._clock.monotonic())
            self._mu.notify()

    def remove(self, pod: Pod) -> None:
        """Pod deleted while queued."""
        with self._mu:
            self._queued_uids.pop(pod.metadata.uid, None)
            self._backoff.pop(pod.metadata.uid, None)
            self._enqueued.pop(pod.metadata.uid, None)
            # lazily dropped from the heap at pop time

    def move_all_to_active(self, _reason: str = "") -> None:
        """Cluster changed — give every backed-off pod another chance now
        (kube-scheduler's MoveAllToActiveOrBackoffQueue)."""
        with self._mu:
            for uid, (_ready, pod) in list(self._backoff.items()):
                del self._backoff[uid]
                self._push_locked(pod)
            self._mu.notify_all()

    def done(self, pod: Pod) -> None:
        """Pod left the scheduling pipeline (bound or abandoned)."""
        with self._mu:
            self._queued_uids.pop(pod.metadata.uid, None)
            self._backoff.pop(pod.metadata.uid, None)
            self._enqueued.pop(pod.metadata.uid, None)

    def enqueued_at(self, uid: str) -> Optional[float]:
        """First-enqueue monotonic timestamp of a still-pipelined pod
        (None once done/removed) — the t0 of the scheduler's queue-wait
        span."""
        with self._mu:
            return self._enqueued.get(uid)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()

    # -- consumer ----------------------------------------------------------
    def pop(self, timeout: Optional[float] = None) -> Optional[Pod]:
        """Next pod to schedule, honoring backoff readiness; None on timeout
        or close."""
        deadline = None if timeout is None \
            else self._clock.monotonic() + timeout
        with self._mu:
            while True:
                if self._closed:
                    return None
                self._promote_ready_locked()
                while self._heap:
                    _, _, _, pod = heapq.heappop(self._heap)
                    if pod.metadata.uid in self._queued_uids and pod.metadata.uid not in self._backoff:
                        return pod
                    # stale entry (removed or re-backed-off) — skip
                now = self._clock.monotonic()
                if deadline is not None and now >= deadline:
                    return None  # None strictly means timeout or close
                waits = []
                if deadline is not None:
                    waits.append(deadline - now)
                if self._backoff:
                    waits.append(min(r for r, _ in self._backoff.values()) - now)
                wait = min(waits) if waits else None
                if wait is not None and wait <= 0:
                    continue  # a backoff entry became ready — re-promote
                self._mu.wait(timeout=wait)

    def pending_count(self) -> int:
        with self._mu:
            return len(self._queued_uids)

    # -- internals (lock held) --------------------------------------------
    def _push_locked(self, pod: Pod) -> None:
        heapq.heappush(
            self._heap,
            (-pod_priority(pod), pod.metadata.creation_timestamp, next(self._seq), pod),
        )

    def _promote_ready_locked(self) -> None:
        now = self._clock.monotonic()
        for uid, (ready_at, pod) in list(self._backoff.items()):
            if ready_at <= now:
                del self._backoff[uid]
                self._push_locked(pod)

