"""Leader election over a coordination Lease — scheduler HA.

The reference inherits this from upstream kube-scheduler: its deploy config
turns it on (/root/reference/deploy/scheduler.yaml:10-13) and client-go's
leaderelection package does the work. Round 2 shipped a single replica with
no election at all (VERDICT.md missing #2): scheduler death meant no
scheduling until the Deployment restarted it, and two replicas would
double-bind every pod. This module is the client-go algorithm on our
APIServer interface:

- one Lease object names the scheduler; the holder renews every
  ``renew_period_s`` (default duration/3);
- challengers retry every ``retry_period_s``; they steal the lease only
  when ``renew_time + lease_duration_s`` has passed (the previous holder
  crashed or lost connectivity);
- acquisition and steal are compare-and-swap through
  ``APIServer.update(expect_rv=...)`` — two challengers race, one gets
  Conflict and backs off;
- the holder drops leadership LOCALLY when it has failed to renew for a
  full lease duration (its clock, no quorum needed): by the time a
  challenger can steal, the old leader has already stopped scheduling —
  the non-overlap argument client-go makes.

The Scheduler gates its cycle loop on ``is_leader()`` (standby replicas
keep informers warm, exactly like kube-scheduler), so ``replicas: 2`` in
deploy/scheduler/scheduler.yaml fails over in ~lease_duration_s.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..api.objects import Lease, ObjectMeta
from ..cluster.apiserver import AlreadyExists, Conflict, NotFound

log = logging.getLogger(__name__)


class LeaderElector:
    def __init__(
        self,
        server,
        identity: str,
        name: str = "tpu-scheduler",
        namespace: str = "default",
        lease_duration_s: float = 15.0,
        renew_period_s: Optional[float] = None,
        retry_period_s: Optional[float] = None,
        renew_deadline_s: Optional[float] = None,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.server = server
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s or lease_duration_s / 3.0
        self.retry_period_s = retry_period_s or lease_duration_s / 5.0
        # client-go's renewDeadline: the holder considers itself demoted
        # STRICTLY BEFORE a challenger can steal (which needs the full
        # lease_duration past the last server renew). The margin is what
        # lets an in-flight scheduling cycle on the old leader finish
        # before the new leader's term starts — with a single threshold,
        # demotion and steal are simultaneous and the terms can overlap
        # (found by the chaos failover test).
        self.renew_deadline_s = renew_deadline_s or 0.8 * lease_duration_s
        if self.renew_deadline_s >= lease_duration_s:
            # client-go errors on this exact misconfiguration: a deadline
            # at or past the lease duration voids the margin and reopens
            # the double-leadership window the margin exists to close.
            raise ValueError(
                f"renew_deadline_s ({self.renew_deadline_s}) must be < "
                f"lease_duration_s ({lease_duration_s})")
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self._leading = threading.Event()
        self._last_renew = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- public ------------------------------------------------------------
    def is_leader(self) -> bool:
        """Leading AND the last successful renew is inside the renew
        deadline — a partitioned leader demotes itself strictly before
        anyone can steal the lease (steal needs the FULL duration)."""
        return (self._leading.is_set()
                and self.clock() - self._last_renew < self.renew_deadline_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"leader-elector-{self.identity}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop electing; release the lease if held so a standby can take
        over immediately instead of waiting out the duration."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._leading.is_set():
            self._demote()
            try:
                lease = self.server.get("Lease", self.name, self.namespace)
                if lease.holder_identity == self.identity:
                    lease.holder_identity = ""
                    self.server.update(
                        lease, expect_rv=lease.metadata.resource_version)
            except Exception:  # noqa: BLE001 — best-effort release
                pass

    def wait_until_leader(self, timeout: float) -> bool:
        deadline = self.clock() + timeout
        while self.clock() < deadline:
            if self.is_leader():
                return True
            time.sleep(0.02)
        return False

    # -- loop --------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                if not self._leading.is_set():
                    log.info("%s became leader of %s/%s", self.identity,
                             self.namespace, self.name)
                    self._leading.set()
                    if self.on_started_leading:
                        self.on_started_leading()
                self._stop.wait(self.renew_period_s)
            else:
                was = self._leading.is_set()
                if was and self.clock() - self._last_renew >= self.renew_deadline_s:
                    self._demote()
                self._stop.wait(self.retry_period_s)

    def _demote(self) -> None:
        if self._leading.is_set():
            log.warning("%s lost leadership of %s/%s", self.identity,
                        self.namespace, self.name)
            self._leading.clear()
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def _try_acquire_or_renew(self) -> bool:
        now = self.clock()
        try:
            lease = self.server.get("Lease", self.name, self.namespace)
        except NotFound:
            return self._create_fresh(now)
        except Exception as e:  # noqa: BLE001 — transport flap, not fatal
            # A dropped connection to the lease store must NOT kill the
            # elector thread (found by the chaos harness: an injected
            # registry flap permanently disabled election for the
            # replica). Treat it like any failed renew: the retry loop
            # keeps trying, and a holder that stays partitioned demotes
            # itself via the staleness check in _run/is_leader.
            log.warning("lease read failed: %s", e)
            return False
        return self._renew_or_steal(lease, now)

    def _create_fresh(self, now: float) -> bool:
        try:
            self.server.create(Lease(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                holder_identity=self.identity,
                lease_duration_s=self.lease_duration_s,
                acquire_time=now, renew_time=now, lease_transitions=0,
            ))
            self._last_renew = now
            return True
        except AlreadyExists:
            return False
        except Exception as e:  # noqa: BLE001
            log.warning("lease create failed: %s", e)
            return False

    def _renew_or_steal(self, lease, now: float) -> bool:
        if lease.holder_identity == self.identity:
            lease.renew_time = now
            lease.lease_duration_s = self.lease_duration_s
        elif lease.expired(now):
            lease.holder_identity = self.identity
            lease.acquire_time = now
            lease.renew_time = now
            lease.lease_duration_s = self.lease_duration_s
            lease.lease_transitions += 1
        else:
            return False
        try:
            self.server.update(
                lease, expect_rv=lease.metadata.resource_version)
            self._last_renew = now
            return True
        except (Conflict, NotFound):
            return False
        except Exception as e:  # noqa: BLE001
            log.warning("lease update failed: %s", e)
            return False
