"""Scheduler control plane (SURVEY.md L4) — the framework the reference
inherits from kube-scheduler, implemented natively: queue, cache with TPU
chip accounting, plugin extension points, and the scheduling/binding cycle."""
from .cache import Cache, NodeInfo
from .framework import (
    CycleState,
    FilterPlugin,
    Handle,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    PermitPlugin,
    Plugin,
    PostBindPlugin,
    PostFilterPlugin,
    PreFilterPlugin,
    Profile,
    ReservePlugin,
    ScorePlugin,
    Status,
    WaitingPod,
)
from .leaderelection import LeaderElector
from .queue import SchedulingQueue, pod_priority
from .reshaper import SliceReshaper
from .scheduler import Scheduler

__all__ = [
    "Cache",
    "NodeInfo",
    "CycleState",
    "FilterPlugin",
    "Handle",
    "MAX_NODE_SCORE",
    "MIN_NODE_SCORE",
    "PermitPlugin",
    "Plugin",
    "PostBindPlugin",
    "PostFilterPlugin",
    "PreFilterPlugin",
    "Profile",
    "ReservePlugin",
    "ScorePlugin",
    "Status",
    "WaitingPod",
    "SchedulingQueue",
    "pod_priority",
    "SliceReshaper",
    "Scheduler",
    "LeaderElector",
]
