"""The scheduling cycle — what the reference inherits from kube-scheduler.

The reference's binary is upstream kube-scheduler with one plugin compiled in
(cmd/scheduler/main.go:20-22); queues, cache, the Filter/Score loop, binding
and the Permit machinery all come from k8s.io/kubernetes v1.21 (SURVEY.md
§3.1). This module is our implementation of that inherited core:

  pop → snapshot → PreFilter → Filter×nodes → Score×nodes → NormalizeScore →
  select → assume → Reserve → Permit (may WAIT) → bind → PostBind

with kube-scheduler's error contract: any failure after assume runs every
Reserve plugin's unreserve, forgets the assumed pod, and requeues with
backoff. Binding runs on a binder pool so a gang pod WAITing in Permit never
blocks the next pod's scheduling cycle (that concurrency is exactly what
gang admission needs).
"""
from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..api.objects import LABEL_POD_GROUP, Pod
from ..cluster.apiserver import APIServer
from ..cluster.informers import SharedInformerFactory
from ..cluster.resources import Descriptor
from ..config import SchedulerConfig
from ..metrics.exporter import Registry
from ..obs import SYSTEM_CLOCK
from .cache import Cache, NodeInfo
from .framework import (
    CycleState,
    Handle,
    Profile,
    Status,
    UNSCHEDULABLE,
    WAIT,
    WaitingPod,
)
from .queue import SchedulingQueue, pod_priority

log = logging.getLogger(__name__)


def pod_class(pod: Pod) -> str:
    """Latency class for the per-class e2e histograms: ``gang`` (pod-group
    label — e2e includes Permit quorum wait), ``preempting`` (non-zero
    priority per queue.pod_priority, the ONE parser of the annotation —
    e2e includes the victims' eviction), else ``single`` (the
    kube-comparable population)."""
    if pod.metadata.labels.get(LABEL_POD_GROUP):
        return "gang"
    if pod_priority(pod) > 0:
        return "preempting"
    return "single"


class Scheduler:
    def __init__(
        self,
        server: APIServer,
        profile: Optional[Profile] = None,
        config: Optional[SchedulerConfig] = None,
        metrics: Optional[Registry] = None,
        elector=None,
        fault_injector=None,
        tracer=None,
        clock=None,
    ) -> None:
        self.config = config or SchedulerConfig()
        # Observability (obs/): the injected clock is the one time source
        # for every cycle/e2e duration (virtual time in tests); the tracer
        # (None in production) records the control-plane half of the
        # request lifecycle — sched_queue (first enqueue -> pop, backoff
        # round-trips included), sched_cycle (Filter->Permit) and
        # sched_bind — on the "sched" lane, rid = the pod name, so a
        # serving caller that submits with trace_id=<pod name> gets one
        # correlated scheduler->engine timeline.
        self._clock = clock or SYSTEM_CLOCK
        self._tracer = tracer
        # Chaos harness hook (testing/faults.py): ``sched.cycle`` fires at
        # the top of every scheduling cycle — an injected drop unwinds the
        # cycle exactly like any plugin failure (the pod requeues with
        # backoff), which is the contract chaos tests verify. None in
        # production: one `is None` check per cycle.
        self._faults = fault_injector
        # Exported metrics — the BASELINE north-star (p50 schedule latency)
        # reads tpu_sched_e2e_duration_seconds; the reference exports nothing
        # of its own (SURVEY.md §5 "Metrics / observability").
        self.metrics = metrics or Registry()
        self._m_cycle = self.metrics.histogram(
            "tpu_sched_scheduling_cycle_seconds", "One Filter->Permit cycle duration"
        )
        self._m_e2e = self.metrics.histogram(
            "tpu_sched_e2e_duration_seconds", "Cycle start to successful bind"
        )
        # Per-class e2e split (VERDICT weak: one distribution for two
        # populations): gang members' e2e includes Permit quorum wait —
        # workload shape, not scheduler work — which buries the
        # kube-comparable singleton tail. Class is derived from the pod
        # itself (pod-group label / priority annotation), so the split
        # needs no bench-side cooperation.
        self._m_e2e_class = {
            cls: self.metrics.histogram(
                f"tpu_sched_e2e_duration_seconds_class_{cls}",
                f"Cycle start to successful bind, {cls} pods")
            for cls in ("single", "gang", "preempting")
        }
        self._m_attempts = self.metrics.counter(
            "tpu_sched_attempts_total", "Scheduling attempts by result"
        )
        self.server = server
        self.descriptor = Descriptor(server)
        self.factory = SharedInformerFactory(server)
        self.cache = Cache()
        self.queue = SchedulingQueue(
            backoff_initial_s=self.config.backoff_initial_s,
            backoff_max_s=self.config.backoff_max_s,
            clock=self._clock,
        )
        self.profile = profile or Profile()
        self.handle = Handle(self.factory, self.descriptor, self.cache, self.config)
        # Why the last cycle for a pod failed — introspection + tests.
        self.failure_reasons: Dict[str, str] = {}
        self._fail_mu = threading.Lock()
        # Binds are pure IO (one POST + PostBind writes) — a deeper pool
        # shortens the queue-wait share of e2e latency under churn bursts
        # (kube-scheduler spawns one goroutine per bind, i.e. unbounded).
        self._binder = ThreadPoolExecutor(max_workers=32, thread_name_prefix="binder")
        # Filter/Score fan-out pool (kube-scheduler's --parallelism); the
        # cycle thread blocks on each wave, so one pool serves all cycles.
        self._cycle_pool = ThreadPoolExecutor(
            max_workers=max(1, self.config.parallelism),
            thread_name_prefix="fanout",
        )
        self._scan_offset = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Optional LeaderElector (sched/leaderelection.py): the cycle loop
        # only pops while holding the lease; informers stay warm on standby
        # replicas — kube-scheduler's HA shape, which the reference turns on
        # via deploy config (deploy/scheduler.yaml:10-13).
        self.elector = elector
        self._wire_informers()

    # -- informer wiring ---------------------------------------------------
    def _wire_informers(self) -> None:
        nodes = self.factory.informer("Node")
        pods = self.factory.informer("Pod")
        nodes.add_event_handler(
            on_add=lambda n: (self.cache.add_node(n), self.queue.move_all_to_active("node-add")),
            on_update=self._on_node_update,
            on_delete=self.cache.delete_node,
        )
        pods.add_event_handler(
            on_add=self._on_pod_add, on_update=self._on_pod_update, on_delete=self._on_pod_delete
        )

    def _on_node_update(self, old, new) -> None:
        self.cache.update_node(old, new)
        # Flush the backoff pool only for changes that can make an
        # unschedulable pod schedulable. Unfiltered, EVERY node write —
        # status heartbeats, our own reshaper/agent annotations mid-flight —
        # reset every backed-off pod's wait, a retry-storm generator under
        # churn (kube-scheduler filters queue moves by event usefulness the
        # same way).
        if old is None or self._node_update_useful(old, new):
            self.queue.move_all_to_active("node-update")

    @staticmethod
    def _node_update_useful(old, new) -> bool:
        return (
            old.metadata.labels != new.metadata.labels
            or old.metadata.annotations != new.metadata.annotations
            or old.status.allocatable != new.status.allocatable
            or old.status.capacity != new.status.capacity
            or old.status.conditions != new.status.conditions
        )

    def _ours(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name == self.config.scheduler_name

    @staticmethod
    def _terminal(pod: Pod) -> bool:
        return pod.status.phase in ("Succeeded", "Failed")

    def _on_pod_add(self, pod: Pod) -> None:
        if pod.spec.node_name:
            if not self._terminal(pod):  # finished pods hold no chips
                self.cache.add_pod(pod)
        elif self._ours(pod) and pod.status.phase == "Pending":
            self.queue.add(pod)

    def _on_pod_update(self, old: Optional[Pod], new: Pod) -> None:
        if new.spec.node_name:
            if self._terminal(new):
                # Terminal pods release their chips (idempotent vs. the
                # following DELETE event).
                self.cache.delete_pod(new)
                self.queue.move_all_to_active("pod-finished")
            else:
                self.cache.update_pod(old, new)
        elif self._ours(new) and new.status.phase == "Pending":
            self.queue.add(new)

    def _on_pod_delete(self, pod: Pod) -> None:
        if pod.spec.node_name:
            self.cache.delete_pod(pod)
            self.queue.move_all_to_active("pod-deleted")
        else:
            self.queue.remove(pod)
        self.handle.nominator.clear(pod.metadata.uid)
        with self._fail_mu:
            self.failure_reasons.pop(pod.metadata.key, None)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.factory.informer("Node")
        self.factory.informer("Pod")
        self.factory.start()
        self.factory.wait_for_cache_sync()
        if self.elector is not None:
            self.elector.start()
        self._thread = threading.Thread(target=self._run, name="sched-cycle", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        # Join the cycle thread FIRST — both so no new waiting pod can be
        # parked after the reject pass below (shutdown would block for its
        # full permit timeout), and so the leadership lease is released only
        # after this replica's in-flight cycle has finished binding.
        # Releasing first would let a standby acquire the lease and start
        # binding while our last cycle still binds: two leaders.
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.elector is not None:
            self.elector.stop()
        self.handle.iterate_waiting_pods(lambda wp: wp.reject("scheduler shutting down"))
        self._binder.shutdown(wait=True)
        self._cycle_pool.shutdown(wait=True)
        self.factory.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.elector is not None and not self.elector.is_leader():
                self._stop.wait(0.05)
                continue
            pod = self.queue.pop(timeout=0.5)
            if pod is None:
                continue
            if self.elector is not None and not self.elector.is_leader():
                # Leadership lapsed while blocked in pop (the pop window
                # straddles a demotion — found by the chaos failover
                # test): the new leader owns this pod now. Requeue it
                # locally with backoff so a re-elected replica still has
                # it; never run a cycle without the lease.
                self.queue.add_unschedulable(pod)
                continue
            try:
                self.schedule_pod(pod)
            except Exception:  # noqa: BLE001 — the cycle must survive anything
                log.exception("scheduling cycle failed for %s", pod.metadata.key)
                self.queue.add_unschedulable(pod)

    # -- one cycle ---------------------------------------------------------
    def schedule_pod(self, pod: Pod) -> None:
        # Revalidate against the live informer: the queued object may be
        # stale (deleted or already bound while queued).
        live = self.factory.informer("Pod").get(pod.metadata.name, pod.metadata.namespace)
        if live is None or live.spec.node_name:
            self.queue.done(pod)
            return
        pod = live

        if self._faults is not None:
            self._faults.fire("sched.cycle")
        if self._tracer is not None:
            # Queue wait ends where the cycle begins; t0 is the FIRST
            # enqueue (backoff round-trips count toward the wait — the
            # number an SLO investigation needs).
            now = self._clock.monotonic()
            t0 = self.queue.enqueued_at(pod.metadata.uid)
            self._tracer.record("sched_queue", t0 if t0 is not None
                                else now, now, lane="sched",
                                rid=pod.metadata.name)
        state = CycleState()
        state.write("cycle_start", self._clock.monotonic())
        try:
            self._run_cycle(state, pod)
        finally:
            dt = self._clock.monotonic() - state.read("cycle_start")
            self._m_cycle.observe(dt)
            if self._tracer is not None:
                self._tracer.record(
                    "sched_cycle", state.read("cycle_start"),
                    state.read("cycle_start") + dt, lane="sched",
                    rid=pod.metadata.name)

    def _run_cycle(self, state: CycleState, pod: Pod) -> None:
        for pl in self.profile.pre_filter:
            st = pl.pre_filter(state, pod)
            if st.code == UNSCHEDULABLE:
                self._record_failure(pod, f"{pl.name}: {st.message}")
                self._m_attempts.inc(result="unschedulable")
                self.queue.add_unschedulable(pod)
                return
            if not st.ok:
                self._m_attempts.inc(result="error")
                self.queue.add_unschedulable(pod)
                return

        snapshot = self.cache.snapshot()
        feasible, reasons = self._find_feasible(state, pod, snapshot)

        if not feasible:
            msg = "; ".join(f"{n}: {r}" for n, r in sorted(reasons.items())) or "no nodes"
            self._record_failure(pod, f"0/{len(snapshot)} nodes available: {msg}")
            # PostFilter (preemption): a plugin may free capacity so the
            # requeued pod succeeds next cycle — the victims' delete events
            # move it from backoff to active, and the priority queue pops
            # the (higher-priority) preemptor before anything that could
            # steal the freed chips.
            for pl in self.profile.post_filter:
                st = pl.post_filter(state, pod, reasons)
                if st.ok:
                    self._record_failure(
                        pod, f"{pl.name}: preempted victims; waiting for "
                             f"capacity release")
                    self._m_attempts.inc(result="preempted")
                    self.queue.add_unschedulable(pod)
                    return
                if st.message:
                    self._record_failure(
                        pod, f"0/{len(snapshot)} nodes available: {msg}; "
                             f"{pl.name}: {st.message}")
            self._m_attempts.inc(result="unschedulable")
            self.queue.add_unschedulable(pod)
            return

        best = self._select_node(state, pod, feasible)

        # Reserve: debit the cache first so concurrent cycles see the chips
        # taken, then run Reserve plugins (scheduler-local state only). Any
        # failure OR exception past this point must credit the chips back —
        # a leaked assume would permanently shrink the node.
        self.cache.assume(pod, best)
        try:
            for pl in self.profile.reserve:
                st = pl.reserve(state, pod, best)
                if not st.ok:
                    self._record_failure(pod, f"{pl.name}: {st.message}")
                    self._abort_after_assume(state, pod, best)
                    return

            # Permit: may park the pod (gang admission).
            wait_plugins: List[str] = []
            wait_timeout = self.config.permit_timeout_s
            for pl in self.profile.permit:
                st, timeout = pl.permit(state, pod, best)
                if st.code == WAIT:
                    wait_plugins.append(pl.name)
                    wait_timeout = min(wait_timeout, timeout) if timeout > 0 else wait_timeout
                elif not st.ok:
                    self._record_failure(pod, f"{pl.name}: {st.message}")
                    self._abort_after_assume(state, pod, best)
                    return

            # submit can itself raise (executor shut down mid-cycle) — the
            # enclosing except must credit the chips back then too.
            if wait_plugins:
                wp = WaitingPod(pod, best, wait_plugins)
                self.handle.add_waiting_pod(wp)
                self._binder.submit(self._wait_then_bind, state, wp, wait_timeout)
            else:
                self._binder.submit(self._bind, state, pod, best)
        except Exception as e:  # noqa: BLE001 — plugin raised instead of returning Status
            self.handle.remove_waiting_pod(pod.metadata.uid)
            self._record_failure(pod, f"plugin exception: {e}")
            self._abort_after_assume(state, pod, best)
            return

    # -- feasible-node search (parallel + sampled) -------------------------
    def _find_feasible(
        self, state: CycleState, pod: Pod, snapshot: Dict[str, NodeInfo]
    ) -> "tuple[List[NodeInfo], Dict[str, str]]":
        """Run the Filter chain over the snapshot — kube-scheduler's
        findNodesThatFitPod shape: a bounded worker pool over nodes
        (--parallelism=16) and early stop once ``num_to_find`` feasible
        nodes exist (percentageOfNodesToScore). The scan starts at a
        rotating offset so sampling doesn't always favor the same
        alphabetical prefix of the fleet. The r3 cycle was O(nodes) serial
        with no cap (VERDICT.md weak #3)."""
        infos = list(snapshot.values())
        num_to_find = self._num_feasible_to_find(len(infos))
        start = getattr(self, "_scan_offset", 0) % max(len(infos), 1)
        infos = infos[start:] + infos[:start]
        self._scan_offset = (start + 1) % max(len(infos), 1)
        # The pod's nominated node (preemption) is always scanned FIRST:
        # sampling may otherwise early-stop before reaching it, and binding
        # anywhere else wastes the eviction while the nomination keeps the
        # freed chips fenced (kube-scheduler evaluates the nominated node
        # ahead of the list for the same reason).
        nominated = self.handle.nominator.node_for(pod.metadata.uid)
        if nominated is not None:
            infos.sort(key=lambda i: i.name != nominated)

        feasible: List[NodeInfo] = []
        reasons: Dict[str, str] = {}

        def check(info: NodeInfo):
            for pl in self.profile.filter:
                st = pl.filter(state, pod, info)
                if not st.ok:
                    return info, f"{pl.name}: {st.message}"
            return info, None

        if len(infos) < self.config.parallelize_threshold:
            for info in infos:
                if len(feasible) >= num_to_find:
                    break
                info, verdict = check(info)
                (feasible.append(info) if verdict is None
                 else reasons.__setitem__(info.name, verdict))
            return feasible, reasons

        # Parallel: one future per worker SLICE (not per node — 256 futures
        # of submit/set_result overhead cost more than the filters they
        # run), waves so the early-stop check runs between them.
        wave = max(1, self.config.parallelism) * 8
        for i in range(0, len(infos), wave):
            if len(feasible) >= num_to_find:
                break
            for info, verdict in self._parallel_map(infos[i:i + wave], check):
                if verdict is None:
                    if len(feasible) < num_to_find:
                        feasible.append(info)
                else:
                    reasons[info.name] = verdict
        return feasible, reasons

    def _parallel_map(self, items: List, fn) -> List:
        """Map ``fn`` over ``items`` on the cycle pool, one future per
        worker slice; results in input order."""
        workers = max(1, self.config.parallelism)
        per = max(1, (len(items) + workers - 1) // workers)
        slices = [items[j:j + per] for j in range(0, len(items), per)]
        return [
            r
            for chunk in self._cycle_pool.map(
                lambda sl: [fn(x) for x in sl], slices)
            for r in chunk
        ]

    def _num_feasible_to_find(self, n_nodes: int) -> int:
        """kube-scheduler's numFeasibleNodesToFind: all nodes below the
        floor; above it, an adaptive percentage (50 - nodes/125, min 5) or
        the configured literal percentage."""
        floor = self.config.min_feasible_to_find
        if n_nodes <= floor:
            return n_nodes
        pct = self.config.percentage_of_nodes_to_score
        if pct <= 0:
            pct = max(5, int(50 - n_nodes / 125))
        return max(floor, n_nodes * pct // 100)

    def _select_node(self, state: CycleState, pod: Pod, feasible: List[NodeInfo]) -> str:
        # A preemption nomination wins outright when still feasible: the
        # victims were evicted on THIS node for THIS pod, so landing anywhere
        # else wastes the eviction (kube-scheduler checks the nominated node
        # before the full list for the same reason).
        nominated = self.handle.nominator.node_for(pod.metadata.uid)
        if nominated is not None and any(i.name == nominated for i in feasible):
            return nominated
        if len(feasible) == 1 or not self.profile.score:
            return sorted(info.name for info in feasible)[0]
        totals: Dict[str, float] = {info.name: 0.0 for info in feasible}
        parallel = len(feasible) >= self.config.parallelize_threshold
        for pl in self.profile.score:
            if parallel:
                vals = self._parallel_map(
                    feasible, lambda info: pl.score(state, pod, info.name))
                scores = {
                    info.name: (val if st.ok else 0.0)
                    for info, (val, st) in zip(feasible, vals)
                }
            else:
                scores = {}
                for info in feasible:
                    val, st = pl.score(state, pod, info.name)
                    scores[info.name] = val if st.ok else 0.0
            pl.normalize_scores(state, pod, scores)
            for name, val in scores.items():
                totals[name] += pl.weight * val
        # Deterministic tie-break by name (upstream randomizes; determinism
        # makes hermetic tests exact).
        return max(sorted(totals), key=lambda n: totals[n])

    # -- binding (async) ---------------------------------------------------
    def _wait_then_bind(self, state: CycleState, wp: WaitingPod, timeout: float) -> None:
        st = wp.wait(timeout)
        self.handle.remove_waiting_pod(wp.uid)
        if not st.ok:
            self._record_failure(wp.pod, st.message)
            self._abort_after_assume(state, wp.pod, wp.node_name)
            return
        self._bind(state, wp.pod, wp.node_name)

    def _bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        t_bind = self._clock.monotonic()
        try:
            self.descriptor.bind_pod(pod.metadata.name, pod.metadata.namespace, node_name)
        except Exception as e:  # noqa: BLE001
            self._record_failure(pod, f"bind failed: {e}")
            self._abort_after_assume(state, pod, node_name)
            return
        self.cache.finish_binding(pod)
        self.handle.nominator.clear(pod.metadata.uid)
        self.queue.done(pod)
        self._m_attempts.inc(result="scheduled")
        if self._tracer is not None:
            self._tracer.record("sched_bind", t_bind,
                                self._clock.monotonic(), lane="sched",
                                rid=pod.metadata.name, node=node_name)
        start = state.read("cycle_start")
        if start is not None:
            dt = self._clock.monotonic() - start
            self._m_e2e.observe(dt)
            self._m_e2e_class[pod_class(pod)].observe(dt)
        with self._fail_mu:
            self.failure_reasons.pop(pod.metadata.key, None)
        for pl in self.profile.post_bind:
            try:
                pl.post_bind(state, pod, node_name)
            except Exception:  # noqa: BLE001
                log.exception("post_bind %s failed for %s", pl.name, pod.metadata.key)

    # -- failure path ------------------------------------------------------
    def _abort_after_assume(self, state: CycleState, pod: Pod, node_name: str) -> None:
        # Every terminal failure after node selection lands here (reserve/
        # permit rejection, plugin exception, permit timeout, bind failure),
        # so the attempts counter can't under-report a retry storm.
        self._m_attempts.inc(result="error")
        for pl in self.profile.reserve:
            try:
                pl.unreserve(state, pod, node_name)
            except Exception:  # noqa: BLE001
                log.exception("unreserve %s failed", pl.name)
        self.cache.forget(pod)
        self.queue.add_unschedulable(pod)

    def _record_failure(self, pod: Pod, reason: str) -> None:
        with self._fail_mu:
            self.failure_reasons[pod.metadata.key] = reason
        log.info("cannot schedule %s: %s", pod.metadata.key, reason)
