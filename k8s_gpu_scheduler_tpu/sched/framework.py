"""Scheduling-framework plugin interfaces — the layer the reference inherits.

The reference compiles its plugin INTO upstream kube-scheduler
(cmd/scheduler/main.go:20-22 ``app.WithPlugin(gpuPlugin.Name, gpuPlugin.New)``)
and implements only ScorePlugin/ScoreExtensions/PostBindPlugin
(gpu_plugins.go:43-44,779,816,843). We own the whole framework, so the full
extension-point set exists here: PreFilter → Filter → Score/NormalizeScore →
Reserve → Permit → PostBind, with kube-scheduler's semantics:

- Filter runs per (pod, node) and returns Success/Unschedulable.
- Score returns 0..MAX_NODE_SCORE per node; NormalizeScore may rescale the
  whole map afterwards (parity: gpu_plugins.go:816-841 min-max rescale).
- Reserve mutates only scheduler-local state (cache assume); Unreserve must
  roll it back. Side effects on cluster state belong in PostBind — this is
  the design fix for the reference writing ConfigMaps during Score
  (gpu_plugins.go:653-666,760-772; SURVEY.md hard part b).
- Permit may return WAIT, parking the pod as a WaitingPod; another cycle (a
  gang peer) or a timeout resolves it. This is the extension point the gang
  plugin uses — the capability the reference lacks entirely (SURVEY.md §2).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0

SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
WAIT = "Wait"
ERROR = "Error"


@dataclass
class Status:
    code: str = SUCCESS
    message: str = ""

    @property
    def ok(self) -> bool:
        return self.code == SUCCESS

    @staticmethod
    def success() -> "Status":
        return Status(SUCCESS)

    @staticmethod
    def unschedulable(msg: str) -> "Status":
        return Status(UNSCHEDULABLE, msg)

    @staticmethod
    def wait(msg: str = "") -> "Status":
        return Status(WAIT, msg)

    @staticmethod
    def error(msg: str) -> "Status":
        return Status(ERROR, msg)


class CycleState:
    """Per-scheduling-cycle scratch space shared across a pod's plugins —
    kube-scheduler's framework.CycleState. The TPU plugin stashes its Reserve
    decision here for PostBind to write (instead of the reference's
    write-during-Score side channel)."""

    def __init__(self) -> None:
        self._data: Dict[str, Any] = {}
        # Filter/Score fan out over nodes on a thread pool
        # (scheduler._parallel_each); plugins write per-node keys
        # concurrently, and clone() must never iterate a mutating dict.
        self._mu = threading.Lock()

    def write(self, key: str, value: Any) -> None:
        with self._mu:
            self._data[key] = value

    def read(self, key: str, default: Any = None) -> Any:
        with self._mu:
            return self._data.get(key, default)

    def clone(self) -> "CycleState":
        """Shallow copy for speculative re-runs (preemption dry-run Filter):
        the copy sees everything written so far (gang.group, tpu.request)
        but its own writes never leak back into the real cycle."""
        out = CycleState()
        with self._mu:
            out._data = dict(self._data)
        return out


class Plugin:
    name = "Plugin"


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod) -> Status:
        raise NotImplementedError


class FilterPlugin(Plugin):
    def filter(self, state: CycleState, pod, node_info) -> Status:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod,
                    filtered_reasons: Dict[str, str]) -> Status:
        """Runs only when Filter left NO feasible node. ``filtered_reasons``
        maps node name → why it was rejected. Returning Success means the
        plugin changed the cluster (e.g. preempted victims) such that a
        retry may succeed — kube-scheduler's PostFilter/DefaultPreemption
        contract (inherited whole by the reference via
        cmd/scheduler/main.go:20-22)."""
        raise NotImplementedError


class ScorePlugin(Plugin):
    # weight multiplies this plugin's normalized scores in the final sum
    # (deploy/scheduler.yaml:8-24 gives the reference's plugin weight 10100).
    weight: float = 1.0

    def score(self, state: CycleState, pod, node_name: str) -> Tuple[float, Status]:
        raise NotImplementedError

    def normalize_scores(self, state: CycleState, pod, scores: Dict[str, float]) -> Status:
        """Optional in-place rescale of the full node→score map (parity:
        NormalizeScore gpu_plugins.go:816-841)."""
        return Status.success()


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod, node_name: str) -> Status:
        raise NotImplementedError

    def unreserve(self, state: CycleState, pod, node_name: str) -> None:
        """Roll back reserve; must be idempotent (kube-scheduler contract)."""


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod, node_name: str) -> Tuple[Status, float]:
        """Return (status, timeout_s). WAIT parks the pod up to timeout_s."""
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod, node_name: str) -> None:
        raise NotImplementedError


@dataclass
class Profile:
    """Which plugins run at each extension point (a scheduler profile —
    KubeSchedulerConfiguration's plugins block, deploy/scheduler.yaml:14-24)."""

    pre_filter: List[PreFilterPlugin] = field(default_factory=list)
    filter: List[FilterPlugin] = field(default_factory=list)
    post_filter: List[PostFilterPlugin] = field(default_factory=list)
    score: List[ScorePlugin] = field(default_factory=list)
    reserve: List[ReservePlugin] = field(default_factory=list)
    permit: List[PermitPlugin] = field(default_factory=list)
    post_bind: List[PostBindPlugin] = field(default_factory=list)


class WaitingPod:
    """A pod parked by a Permit WAIT — kube-scheduler's framework.WaitingPod.

    Gang peers call ``allow(plugin_name)``; when every pending plugin has
    allowed, the binder thread proceeds. ``reject`` fails the pod's cycle
    (triggering unreserve + requeue)."""

    def __init__(self, pod, node_name: str, pending_plugins: List[str]) -> None:
        self.pod = pod
        self.node_name = node_name
        self._mu = threading.Lock()
        self._pending = set(pending_plugins)
        self._event = threading.Event()
        self._rejected: Optional[str] = None

    @property
    def uid(self) -> str:
        return self.pod.metadata.uid

    def allow(self, plugin_name: str) -> None:
        with self._mu:
            self._pending.discard(plugin_name)
            if not self._pending:
                self._event.set()

    def reject(self, reason: str) -> None:
        with self._mu:
            if self._rejected is None:
                self._rejected = reason
            self._event.set()

    def wait(self, timeout: float) -> Status:
        """Block until allowed by all plugins, rejected, or timed out."""
        fired = self._event.wait(timeout)
        with self._mu:
            if self._rejected is not None:
                return Status.unschedulable(self._rejected)
            if fired and not self._pending:
                return Status.success()
            return Status.unschedulable("permit wait timed out")


class Nominator:
    """In-memory nominated-pod table — kube-scheduler's PodNominator.

    After preemption frees capacity on a node, the preemptor is *nominated*
    to it. Until the preemptor binds (or is deleted), other pods' Filter
    treats the nominated chips as taken when the nominee has equal or higher
    priority — so the freed capacity cannot be sniped by a pod the eviction
    was not for (the equal-priority race VERDICT.md r3 weak #5 flags).
    kube-scheduler persists the nomination in pod.status.nominatedNodeName;
    ours is scheduler-local like the rest of the assume state — a failover
    leader re-preempts at worst."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # pod uid -> (pod object at nomination time, node name)
        self._nominated: Dict[str, Tuple[Any, str]] = {}

    def nominate(self, pod, node_name: str) -> None:
        with self._mu:
            self._nominated[pod.metadata.uid] = (pod, node_name)

    def clear(self, pod_uid: str) -> None:
        with self._mu:
            self._nominated.pop(pod_uid, None)

    def node_for(self, pod_uid: str) -> Optional[str]:
        with self._mu:
            entry = self._nominated.get(pod_uid)
            return entry[1] if entry else None

    def pods_on(self, node_name: str) -> List[Any]:
        """Pods currently nominated to this node."""
        with self._mu:
            return [p for p, n in self._nominated.values() if n == node_name]

    def has_nominations(self) -> bool:
        """True when ANY nomination exists — lets the Filter hot path skip
        the per-node pods_on scan in the overwhelmingly common no-recent-
        preemption case (a bare len read is atomic under the GIL). An
        explicit method, not __bool__: truthiness on a Nominator must keep
        meaning 'exists' for `if handle.nominator:` callers."""
        # graftcheck: ignore[lock-guard] — deliberate lock-free read: GIL-atomic, staleness acceptable (docstring above)
        return bool(self._nominated)


class Handle:
    """What plugins get to see — kube-scheduler's framework.Handle. Carries
    the informer factory, resource Descriptor, cluster cache, config, the
    waiting-pod table (for gang admission), and the nominator (preemption)."""

    def __init__(self, factory, descriptor, cache, config) -> None:
        self.factory = factory
        self.descriptor = descriptor
        self.cache = cache
        self.config = config
        self.nominator = Nominator()
        self._waiting_mu = threading.Lock()
        self._waiting: Dict[str, WaitingPod] = {}

    # -- waiting pods (Permit) --------------------------------------------
    def add_waiting_pod(self, wp: WaitingPod) -> None:
        with self._waiting_mu:
            self._waiting[wp.uid] = wp

    def remove_waiting_pod(self, uid: str) -> None:
        with self._waiting_mu:
            self._waiting.pop(uid, None)

    def get_waiting_pod(self, uid: str) -> Optional[WaitingPod]:
        with self._waiting_mu:
            return self._waiting.get(uid)

    def iterate_waiting_pods(self, fn: Callable[[WaitingPod], None]) -> None:
        with self._waiting_mu:
            pods = list(self._waiting.values())
        for wp in pods:
            fn(wp)
