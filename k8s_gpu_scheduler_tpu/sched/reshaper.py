"""Async slice repartitioning — the MIG-reconfigure analogue, de-blocked.

The reference repartitions an idle A30 from inside Score: it labels the node
``nvidia.com/mig.config``, kills the profiler pod, then POLLS Redis every
2 s until the UUID set changes — blocking the scheduling thread for the
whole hardware reconfiguration (gpu_plugins.go:357-452; SURVEY.md hard part
e says this must become an async state machine). This is that state machine:

    idle ──request()──▶ applying ──agent republishes──▶ idle (new config)
                          │
                          └────────timeout────────▶ idle (rolled back)

- ``request(node, config)`` just annotates the node (``tpu.sched/slice.config``
  = target, ``tpu.sched/slice.reshape-state`` = applying) and returns; a
  worker thread owns all waiting.
- Confirmation = the node agent publishing a FRESH inventory (its heartbeat
  advancing past the request) — the analogue of the profiler republishing
  post-MIG UUIDs. With no registry wired (unit tests, smoke rigs) requests
  confirm immediately.
- While a node is ``applying``, the TPU plugin filters it out — scheduling
  of other pods proceeds; the displaced pod retries via normal backoff and
  lands on the repartitioned node.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from ..api.objects import ANN_RESHAPE_STATE, ANN_SLICE_CONFIG, Node
from ..obs import SYSTEM_CLOCK
from ..registry.inventory import HEARTBEAT_SUFFIX, node_key

log = logging.getLogger(__name__)

STATE_APPLYING = "applying"


@dataclass
class _Pending:
    node_name: str
    target: str
    previous: str
    # TWO request timestamps, deliberately: the timeout/auto-confirm math
    # is a DURATION and rides the monotonic clock (the old single
    # time.time() field meant an NTP step forward instantly timed out and
    # rolled back a healthy reshape, and a step backward stalled the
    # timeout — the wall-clock-for-duration bug the obs.Clock sweep
    # found); the agent-heartbeat comparison crosses processes and stays
    # on the wall clock the agent publishes.
    requested_mono: float
    requested_wall: float


class SliceReshaper:
    def __init__(
        self,
        descriptor,
        registry=None,
        poll_interval_s: float = 0.25,
        timeout_s: float = 60.0,
        auto_confirm_delay_s: float = 0.0,
        simulate_without_registry: bool = True,
        clock=None,
    ):
        self.descriptor = descriptor
        self.registry = registry
        self._clock = clock or SYSTEM_CLOCK
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        # No-registry mode: confirmation is SIMULATED (there is no agent to
        # republish). Each request is loudly logged as such and confirms
        # only after this delay — so a demo shows the applying→idle window
        # instead of pretending hardware repartitioned instantly
        # (VERDICT.md weak #7). Tests keep 0.0 for instant confirm.
        self.auto_confirm_delay_s = auto_confirm_delay_s
        # With neither a registry NOR simulation opted into (in-cluster
        # against real hardware without an agent feed), request() REFUSES:
        # flipping applying→idle on a timer with no observer would tell the
        # scheduler a repartition happened that nothing confirmed. Demo and
        # test rigs pass True (the default keeps hermetic rigs working);
        # cmd/scheduler.py passes False for --in-cluster.
        self.simulate_without_registry = simulate_without_registry
        self._mu = threading.Lock()
        self._pending: Dict[str, _Pending] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._adopt_orphans()

    def _adopt_orphans(self) -> None:
        """A node still annotated 'applying' from a dead reshaper instance
        (crash/restart mid-reshape) would otherwise be filtered out of
        scheduling forever — adopt it so the normal confirm/timeout path
        clears the state. Rollback target = its current config (the previous
        value died with the old instance)."""
        try:
            nodes = self.descriptor.list_nodes()
        except Exception:  # noqa: BLE001 — API unavailable at construction
            return
        for node in nodes:
            if node.metadata.annotations.get(ANN_RESHAPE_STATE) == STATE_APPLYING:
                cfg = node.metadata.annotations.get(ANN_SLICE_CONFIG, "")
                with self._mu:
                    self._pending[node.metadata.name] = _Pending(
                        node.metadata.name, cfg, cfg,
                        self._clock.monotonic(), self._clock.wall()
                    )
                log.warning("adopted orphaned reshape on %s (config %r)",
                            node.metadata.name, cfg)
        self._ensure_worker()

    # -- API ---------------------------------------------------------------
    def request(self, node_name: str, target_config: str) -> bool:
        """Begin repartitioning ``node_name`` to ``target_config``.
        Non-blocking; returns False if a reshape is already in flight (the
        reference serializes with a global mutex, gpu_plugins.go:480-496)."""
        if self._stop.is_set():
            return False  # shut down — never annotate a state nobody clears
        if self.registry is None and not self.simulate_without_registry:
            log.warning(
                "refusing reshape of %s: no registry to confirm the new "
                "partitioning and simulation not enabled", node_name)
            return False
        with self._mu:
            if node_name in self._pending:
                return False
            try:
                node: Node = self.descriptor.get_node(node_name)
            except Exception:  # noqa: BLE001 — node gone
                return False
            if node.metadata.annotations.get(ANN_RESHAPE_STATE) == STATE_APPLYING:
                return False
            previous = node.metadata.annotations.get(ANN_SLICE_CONFIG, "")
            if previous == target_config:
                return False
            self._annotate(node_name, {
                ANN_SLICE_CONFIG: target_config,
                ANN_RESHAPE_STATE: STATE_APPLYING,
            })
            self._pending[node_name] = _Pending(
                node_name, target_config, previous,
                self._clock.monotonic(), self._clock.wall()
            )
        log.info("reshape %s: %r -> %r", node_name, previous, target_config)
        self._ensure_worker()
        return True

    def in_flight(self, node_name: str) -> bool:
        with self._mu:
            return node_name in self._pending

    @staticmethod
    def is_applying(node: Node) -> bool:
        return node.metadata.annotations.get(ANN_RESHAPE_STATE) == STATE_APPLYING

    # -- worker ------------------------------------------------------------
    #
    # The drained-exit decision and the spawn decision both happen under
    # _mu: the worker sets _thread=None BEFORE returning, so a request()
    # racing the exit either sees the entry picked up by the live worker or
    # spawns a fresh one — an accepted request can never be stranded.
    def _ensure_worker(self) -> None:
        with self._mu:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="slice-reshaper"
                )
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # _thread is lock-guarded state (the worker nulls it on drained
        # exit, _ensure_worker respawns under _mu) — snapshot it under the
        # same lock; join() on the snapshot is then race-free either way.
        with self._mu:
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._mu:
                pending = list(self._pending.values())
                if not pending:
                    self._thread = None
                    return
            for p in pending:
                try:
                    self._advance(p)
                except Exception:  # noqa: BLE001 — one node must not stall all
                    log.exception("reshape of %s failed", p.node_name)
                    self._finish(p, rollback=True)
            self._stop.wait(self.poll_interval_s)

    def _advance(self, p: _Pending) -> None:
        if self._confirmed(p):
            self._finish(p, rollback=False)
        elif self._clock.monotonic() - p.requested_mono > self.timeout_s:
            log.warning("reshape of %s timed out; rolling back to %r",
                        p.node_name, p.previous)
            self._finish(p, rollback=True)

    def _confirmed(self, p: _Pending) -> bool:
        """Agent republished since the request → the host observed the new
        partitioning (UUID-change parity, gpu_plugins.go:436-452)."""
        if self.registry is None:
            if self._clock.monotonic() - p.requested_mono \
                    < self.auto_confirm_delay_s:
                return False
            log.warning(
                "reshape of %s to %r confirmed WITHOUT a registry — "
                "simulated confirmation, no agent observed the new "
                "partitioning", p.node_name, p.target)
            return True
        try:
            raw = self.registry.get(node_key(p.node_name) + HEARTBEAT_SUFFIX)
        except Exception:  # noqa: BLE001 — registry down: keep waiting
            return False
        if raw is None:
            return False
        try:
            # Cross-process comparison: the agent publishes WALL time, so
            # this one stays on the wall clock (monotonic clocks share no
            # epoch across processes).
            return float(raw) >= p.requested_wall
        except ValueError:
            return False

    def _finish(self, p: _Pending, rollback: bool) -> None:
        # Drop the entry FIRST: if the annotate below fails (node deleted,
        # API down) we must not retry it forever and wedge the worker on one
        # node — a vanished node's annotations vanished with it anyway.
        with self._mu:
            self._pending.pop(p.node_name, None)
        ann = {ANN_RESHAPE_STATE: ""}
        if rollback:
            ann[ANN_SLICE_CONFIG] = p.previous
        try:
            self._annotate(p.node_name, ann)
        except Exception:  # noqa: BLE001
            log.exception("could not clear reshape state on %s", p.node_name)

    def _annotate(self, node_name: str, ann: Dict[str, str]) -> None:
        def fn(n: Node) -> None:
            for k, v in ann.items():
                if v:
                    n.metadata.annotations[k] = v
                else:
                    n.metadata.annotations.pop(k, None)

        self.descriptor.server.mutate("Node", node_name, "default", fn)
