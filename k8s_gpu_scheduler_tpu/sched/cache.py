"""Cluster cache with TPU chip accounting — the scheduler's world model.

Upstream kube-scheduler keeps a cache of NodeInfos plus "assumed" pods
(reserved but not yet observed bound through the watch); the reference
inherits that wholesale (SURVEY.md §3.1 — "queues, cache, Filter/Score cycle
... inherited, not implemented"). We implement it: per-node chip accounting
(allocatable − Σ requests of bound+assumed pods) is the predicate VERDICT.md
weak-item 7 flagged as missing — a TPU Filter cannot exist without it.

Chips are the ``google.com/tpu`` extended resource (objects.py:26); slice
shape/generation ride on the GKE node labels.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.objects import Node, ObjectMeta, Pod, TPU_RESOURCE
from ..api.topology import SliceTopology, TPUGen


@dataclass
class NodeInfo:
    """Point-in-time view of one node. Snapshots hand these out by value —
    plugins may read freely; mutation happens only inside the Cache."""

    node: Node
    pods: List[Pod] = field(default_factory=list)
    requested_tpu: int = 0
    # Bumped by the Cache on every mutation of this node — lets snapshot()
    # reuse unchanged per-node copies across cycles (kube-scheduler's
    # nodeInfo.Generation / cache.UpdateSnapshot design).
    generation: int = 0
    # ((accelerator, topology), parsed) memo — see slice_topology().
    _topo_cache: Optional[tuple] = field(default=None, repr=False, compare=False)

    @property
    def name(self) -> str:
        return self.node.metadata.name

    @property
    def allocatable_tpu(self) -> int:
        return int(self.node.status.allocatable.get(TPU_RESOURCE, 0))

    @property
    def free_tpu(self) -> int:
        return self.allocatable_tpu - self.requested_tpu

    def slice_topology(self) -> Optional[SliceTopology]:
        # Parsed once per (node object, label pair): Filter + Score call
        # this for every (pod × node) and the labels almost never change —
        # re-parsing the topology string was ~10% of cycle time at 256
        # nodes. Keyed on the label values, so a relabel invalidates.
        acc, topo = self.node.tpu_accelerator(), self.node.tpu_topology()
        if not acc or not topo:
            return None
        cached = self._topo_cache
        if cached is not None and cached[0] == (acc, topo):
            return cached[1]
        try:
            parsed = SliceTopology.parse(TPUGen(acc), topo)
        except ValueError:
            parsed = None
        self._topo_cache = ((acc, topo), parsed)
        return parsed

    def shallow_copy(self) -> "NodeInfo":
        c = NodeInfo(node=self.node, pods=list(self.pods),
                     requested_tpu=self.requested_tpu,
                     generation=self.generation)
        # Carry the topology memo: labels rarely change and the memo is
        # keyed on their values, so a stale carry self-invalidates.
        c._topo_cache = self._topo_cache
        return c


class Cache:
    """Thread-safe node/pod cache with assume semantics.

    Lifecycle of a pod through the cache (kube-scheduler's state machine):
      assume(pod, node)      — Reserve succeeded; chips debited immediately so
                               the next cycle's snapshot sees them taken.
      finish_binding(pod)    — bind API call succeeded; the assumed entry now
                               waits for the watch to confirm.
      forget(pod)            — Reserve/Permit/bind failed; chips credited back.
      add/update/delete_pod  — watch events; a confirmed add replaces the
                               assumed entry.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._nodes: Dict[str, NodeInfo] = {}
        # uid -> (pod, node_name) reserved in-flight
        self._assumed: Dict[str, tuple] = {}
        # Monotonic mutation counter + per-node snapshot copies keyed by the
        # generation they were taken at: snapshot() re-copies only nodes
        # that changed since the last cycle (O(churn), not O(fleet)).
        self._gen = 0
        self._snap: Dict[str, NodeInfo] = {}

    def _touch_locked(self, info: NodeInfo) -> None:
        self._gen += 1
        info.generation = self._gen

    # -- node events -------------------------------------------------------
    def add_node(self, node: Node) -> None:
        with self._mu:
            info = self._nodes.get(node.metadata.name)
            if info is None:
                info = NodeInfo(node=node)
                self._nodes[node.metadata.name] = info
            else:
                info.node = node
            self._touch_locked(info)

    def update_node(self, _old: Optional[Node], new: Node) -> None:
        self.add_node(new)

    def delete_node(self, node: Node) -> None:
        with self._mu:
            self._nodes.pop(node.metadata.name, None)

    # -- pod events (from the watch) --------------------------------------
    #
    # All of these are IDEMPOTENT: adding a pod already accounted is a
    # no-op object refresh, removing one already gone is a no-op. The watch
    # can deliver redundant events (terminal update followed by DELETE, a
    # replayed ADD) and accounting must never double-debit or double-credit.
    def add_pod(self, pod: Pod) -> None:
        if not pod.spec.node_name:
            return
        with self._mu:
            uid = pod.metadata.uid
            assumed = self._assumed.pop(uid, None)
            if assumed is not None:
                a_pod, a_node = assumed
                if a_node != pod.spec.node_name:
                    # bound somewhere else than assumed — move the debit
                    self._remove_locked(a_node, a_pod)
                else:
                    # already debited by assume; just swap the pod object in
                    self._refresh_locked(a_node, pod)
                    return
            self._add_locked(pod.spec.node_name, pod)

    def update_pod(self, old: Optional[Pod], new: Pod) -> None:
        if old is not None and old.spec.node_name and old.spec.node_name != new.spec.node_name:
            self.delete_pod(old)
        if not (old is not None and old.spec.node_name == new.spec.node_name):
            self.add_pod(new)
            return
        with self._mu:
            self._refresh_locked(new.spec.node_name, new)

    def delete_pod(self, pod: Pod) -> None:
        if not pod.spec.node_name:
            return
        with self._mu:
            self._remove_locked(pod.spec.node_name, pod)

    # -- assume / forget ---------------------------------------------------
    def assume(self, pod: Pod, node_name: str) -> None:
        with self._mu:
            prev = self._assumed.get(pod.metadata.uid)
            if prev is not None:
                if prev[1] == node_name:
                    return  # already assumed here — idempotent
                self._remove_locked(prev[1], prev[0])
            self._assumed[pod.metadata.uid] = (pod, node_name)
            self._add_locked(node_name, pod)

    def finish_binding(self, pod: Pod) -> None:
        # No-op beyond bookkeeping: the assumed entry is reconciled when the
        # watch delivers the bound pod (add_pod above).
        pass

    def forget(self, pod: Pod) -> None:
        with self._mu:
            assumed = self._assumed.pop(pod.metadata.uid, None)
            if assumed is None:
                return
            a_pod, a_node = assumed
            self._remove_locked(a_node, a_pod)

    def is_assumed(self, pod: Pod) -> bool:
        with self._mu:
            return pod.metadata.uid in self._assumed

    # -- snapshot ----------------------------------------------------------
    def snapshot(self) -> Dict[str, NodeInfo]:
        """Copy-on-read view for one scheduling cycle (kube-scheduler's
        Snapshot().NodeInfos(), used by the reference at gpu_plugins.go:798).

        Incremental: per-node copies are reused until that node's
        generation changes (kube's cache.UpdateSnapshot). A cycle holding
        last cycle's dict keeps reading its own consistent copies — the
        cache only ever REPLACES entries here, never mutates them."""
        with self._mu:
            snap = self._snap
            for name, info in self._nodes.items():
                prev = snap.get(name)
                if prev is None or prev.generation != info.generation:
                    snap[name] = info.shallow_copy()
            if len(snap) != len(self._nodes):
                for name in [n for n in snap if n not in self._nodes]:
                    del snap[name]
            return dict(snap)

    def node_names(self) -> List[str]:
        with self._mu:
            return list(self._nodes)

    # -- internals (call with lock held) ----------------------------------
    def _node_info_locked(self, node_name: str) -> NodeInfo:
        info = self._nodes.get(node_name)
        if info is None:
            # Node not (yet) known — placeholder so accounting survives
            # pod-before-node watch ordering; add_node fills in the object.
            info = NodeInfo(node=Node(metadata=ObjectMeta(name=node_name)))
            self._nodes[node_name] = info
        return info

    def _add_locked(self, node_name: str, pod: Pod) -> None:
        info = self._node_info_locked(node_name)
        for i, p in enumerate(info.pods):
            if p.metadata.uid == pod.metadata.uid:
                info.pods[i] = pod  # already accounted — refresh only
                self._touch_locked(info)
                return
        info.pods.append(pod)
        info.requested_tpu += pod.spec.tpu_chips()
        self._touch_locked(info)

    def _remove_locked(self, node_name: str, pod: Pod) -> None:
        info = self._nodes.get(node_name)
        if info is None:
            return
        for i, p in enumerate(info.pods):
            if p.metadata.uid == pod.metadata.uid:
                del info.pods[i]
                info.requested_tpu -= p.spec.tpu_chips()
                self._touch_locked(info)
                return
        # not present — already credited; no-op

    def _refresh_locked(self, node_name: str, pod: Pod) -> None:
        """Swap the stored object WITHOUT touching accounting; ignores pods
        the cache no longer tracks (e.g. an update trailing a terminal
        credit)."""
        info = self._nodes.get(node_name)
        if info is None:
            return
        for i, p in enumerate(info.pods):
            if p.metadata.uid == pod.metadata.uid:
                info.pods[i] = pod
                self._touch_locked(info)
                return
