"""obs — request-lifecycle tracing and the engine flight recorder.

The reference scheduler's whole pitch is SLO-aware placement driven by
live telemetry (DCGM → Prometheus → Score), yet a latency number alone
cannot answer *why* a request was slow: queue wait, gang-bind latency, a
page-shortage admission stall, a prefill chunk blocking decode, a
speculative rewind storm, or a drain/restore gap. This package is the
measurement substrate the ROADMAP's next tentpoles (disaggregated
prefill/decode, cache-aware fleet routing) attribute latency with:

- :mod:`~.trace` — the span API: an injectable :class:`Clock` (so chaos
  and trace tests run on virtual time), :class:`Tracer` with a
  thread-safe bounded drop-oldest buffer (the hot path never blocks and
  never grows), ``span()`` context managers and explicit
  ``record()``/``event()`` for phases whose endpoints live on different
  host paths (queue wait: submit → admission).
- :mod:`~.flight` — the engine flight recorder: a fixed-size ring of
  per-step records (step kind, wall ms, active slots, tokens emitted,
  accept rate, pool watermark, admissions/evictions/retires, fault
  injections) that rides into ``ServingSnapshot`` so a post-preemption
  engine can explain its pre-preemption behavior.
- :mod:`~.export` — Chrome-trace/Perfetto JSON export (one lane per
  engine slot, one per control-plane component) plus the fold of
  drained phase durations into the ``tpu_serve_phase_duration_seconds``
  Prometheus histogram.

Tracing is off-by-default-cheap: production constructs engines and
schedulers with ``tracer=None`` (one ``is None`` check per phase), and
``bench.py --leg obs_overhead`` CI-asserts the tracing-ON steady-state
decode leg within 2% of tracing-off. Span calls are HOST-side by
contract — inside jit-traced code they would be host syncs, which
graftcheck's ``trace-in-jit`` pass (analysis/tracelint.py) makes a lint
error.
"""
from .trace import (
    Clock, Span, SystemClock, Tracer, VirtualClock, SYSTEM_CLOCK,
)
from .flight import FlightRecorder
from .export import (
    PHASES, to_perfetto, validate_perfetto, write_perfetto,
)

__all__ = [
    "Clock",
    "SystemClock",
    "VirtualClock",
    "SYSTEM_CLOCK",
    "Span",
    "Tracer",
    "FlightRecorder",
    "PHASES",
    "to_perfetto",
    "validate_perfetto",
    "write_perfetto",
]
