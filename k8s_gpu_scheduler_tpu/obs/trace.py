"""Span API — injected clocks, bounded buffers, never block the hot path.

Design constraints, in order:

1. **Cheap when off.** Everything that instruments a hot path guards
   with ``tracer is None`` (or ``tracer.enabled``); a disabled tracer's
   ``span()`` returns one shared no-op context manager — no allocation,
   no clock read.
2. **Never block, never grow.** The buffer is a fixed-capacity ring
   with drop-oldest semantics: an append under load evicts the oldest
   span and counts it in ``dropped`` instead of stalling the step loop
   or leaking memory. The lock is held for one deque append.
3. **Monotonic time only.** Spans are measured on ``Clock.monotonic()``
   — wall clocks jump (NTP, suspend) and a duration measured on one is
   a latent bug (the sweep this PR did found exactly that in the
   reshaper's timeout path). ``Clock.wall()`` exists for *timestamps
   that leave the process* (registry heartbeats, snapshot downtime
   accounting), never for durations.
4. **Injectable time.** Production uses :data:`SYSTEM_CLOCK`; chaos and
   trace tests inject :class:`VirtualClock` and advance it by hand, so
   timing-dependent assertions are exact instead of sleep-and-hope.

Spans are flat records (name, t0, t1, lane, rid, thread id, attrs) —
nesting is positional: two spans on the same lane whose intervals nest
render nested in Perfetto, which is all the structure the timeline
views need. ``rid`` is the cross-plane correlation key: the scheduler
tags spans with the pod name, the serving engine with the request's
trace id, and a caller that uses one string for both gets a single
correlated timeline from scheduler enqueue to token stream.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional


class Clock:
    """Injectable time source. ``monotonic()`` is for durations and
    ordering; ``wall()`` is for timestamps that cross process/host
    boundaries. Subclasses override both."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def wall(self) -> float:
        raise NotImplementedError


class SystemClock(Clock):
    """The real clocks: ``time.monotonic`` / ``time.time``."""

    def monotonic(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()


class VirtualClock(Clock):
    """Hand-advanced time for tests: ``advance(dt)`` moves both clocks
    forward together (a virtual wall clock can additionally ``jump`` —
    the NTP-step scenario duration code must be immune to)."""

    def __init__(self, mono: float = 1000.0, wall: float = 1.7e9) -> None:
        self._mono = float(mono)
        self._wall = float(wall)
        self._mu = threading.Lock()

    def monotonic(self) -> float:
        with self._mu:
            return self._mono

    def wall(self) -> float:
        with self._mu:
            return self._wall

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError("monotonic time cannot go backward")
        with self._mu:
            self._mono += dt
            self._wall += dt

    def jump_wall(self, dt: float) -> None:
        """Step ONLY the wall clock (either direction) — the clock-jump
        scenario that distinguishes duration code on the right clock
        from duration code that merely worked so far."""
        with self._mu:
            self._wall += dt


SYSTEM_CLOCK = SystemClock()


@dataclass(frozen=True)
class Span:
    """One closed interval on one lane. ``t0``/``t1`` are
    ``Clock.monotonic()`` readings from the owning tracer's clock."""

    name: str                        # phase: queue|admit|prefill|...
    #                                  (tiered engines add demote|promote)
    t0: float
    t1: float
    lane: str = "host"               # Perfetto row: slot3, sched, ...
    rid: Optional[str] = None        # cross-plane correlation key
    tid: int = 0                     # host thread ident
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.t1 - self.t0


class _NullSpan:
    """Shared no-op context manager for disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe bounded span collector.

    ``record()`` / ``event()`` append; ``span()`` is the context-manager
    form for synchronous blocks. The buffer drops OLDEST on overflow
    (``dropped`` counts evictions) — a tracer left on forever costs a
    fixed amount of memory and the most recent window of spans, which is
    the window an incident investigation wants anyway.
    """

    def __init__(self, capacity: int = 16384,
                 clock: Optional[Clock] = None,
                 enabled: bool = True) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.clock = clock or SYSTEM_CLOCK
        self.capacity = capacity
        self.enabled = bool(enabled)
        self._mu = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._dropped = 0

    # -- write side --------------------------------------------------------
    def record(self, name: str, t0: float, t1: float, lane: str = "host",
               rid: Optional[str] = None, **attrs) -> None:
        """Append a closed span with explicit endpoints (for phases whose
        start and end live on different code paths — queue wait is
        recorded at admission with t0 = the submit-time clock reading)."""
        if not self.enabled:
            return
        span = Span(name, t0, t1, lane, rid, threading.get_ident(), attrs)
        with self._mu:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(span)

    def event(self, name: str, lane: str = "host",
              rid: Optional[str] = None, **attrs) -> None:
        """Zero-duration marker (rewinds, page-shortage stalls, fault
        injections)."""
        now = self.clock.monotonic()
        self.record(name, now, now, lane, rid, **attrs)

    @contextlib.contextmanager
    def _span_cm(self, name: str, lane: str, rid: Optional[str],
                 attrs: Dict[str, object]) -> Iterator[Dict[str, object]]:
        t0 = self.clock.monotonic()
        try:
            # The yielded dict lets the body attach result attrs
            # (tokens emitted, accept rate) before the span closes.
            yield attrs
        finally:
            self.record(name, t0, self.clock.monotonic(), lane, rid,
                        **attrs)

    def span(self, name: str, lane: str = "host",
             rid: Optional[str] = None, **attrs):
        """``with tracer.span("decode_chunk", lane="engine") as a: ...`` —
        times the block on the tracer's monotonic clock. Disabled tracers
        return a shared no-op (no clock read, no allocation)."""
        if not self.enabled:
            return _NULL_SPAN
        return self._span_cm(name, lane, rid, dict(attrs))

    # -- read side ---------------------------------------------------------
    @property
    def dropped(self) -> int:
        with self._mu:
            return self._dropped

    def __len__(self) -> int:
        with self._mu:
            return len(self._buf)

    def spans(self, rid: Optional[str] = None,
              name: Optional[str] = None) -> List[Span]:
        """Snapshot of the buffer (oldest first), optionally filtered by
        correlation id and/or phase name."""
        with self._mu:
            out = list(self._buf)
        if rid is not None:
            out = [s for s in out if s.rid == rid]
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._mu:
            self._buf.clear()
            self._dropped = 0
