"""Exporters — Chrome-trace/Perfetto JSON and the Prometheus phase fold.

``to_perfetto`` renders a span list as the Chrome Trace Event format
(the JSON flavor Perfetto's UI and ``chrome://tracing`` both load): one
process group for the serving engine with a thread row per slot lane,
one process group for the control plane with a row per component lane,
complete "X" events with microsecond timestamps rebased to the earliest
span, span attrs (and the rid correlation key) in ``args``. The format
is append-only JSON — no SDK, no protobuf dependency — which keeps the
exporter usable from the bench and from a post-mortem REPL alike.

``validate_perfetto`` is the structural schema check CI runs on the
bench-produced file: a trace that silently drops required keys loads as
an empty timeline in the UI, which is exactly the kind of bitrot a
loader-side check catches the day it happens.

The Prometheus side lives in ``metrics/exporter.py`` (the
``tpu_serve_phase_duration_seconds{phase=...}`` histogram fed from
``ContinuousBatcher.pool_metrics()``'s atomic phase drain); this module
only owns the span-shaped exports.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Sequence, Tuple

from .trace import Span

# The request-lifecycle phase taxonomy (README "Observability" documents
# each): every engine span name is one of these; the scheduler plane adds
# its own sched_* names on control-plane lanes. "handoff" is the one
# router-lane member — the disaggregated prefill→decode migration sits
# between an engine's prefill phases and its peer's decode phases on the
# same rid track.
PHASES = ("queue", "admit", "prefill", "prefill_chunk", "decode_chunk",
          "verify", "rewind", "reap", "drain", "restore", "handoff")

_ENGINE_PID = 1
_CONTROL_PID = 2


def _lane_ids(lanes: Iterable[str]) -> Dict[str, Tuple[int, int]]:
    """lane name -> (pid, tid): engine lanes (``engine``, ``slot*``)
    group under one process so slot rows sit together; everything else
    (sched, queue, registry, ...) is a control-plane row."""
    ids: Dict[str, Tuple[int, int]] = {}
    next_tid = {_ENGINE_PID: 1, _CONTROL_PID: 1}
    for lane in sorted(set(lanes)):
        pid = _ENGINE_PID if (lane == "engine" or lane.startswith("slot")) \
            else _CONTROL_PID
        ids[lane] = (pid, next_tid[pid])
        next_tid[pid] += 1
    return ids


def to_perfetto(spans: Sequence[Span]) -> Dict[str, object]:
    """Chrome Trace Event JSON document for ``spans`` (any order).
    Timestamps rebase to the earliest t0 so the trace starts at 0 µs
    regardless of the monotonic clock's epoch."""
    spans = list(spans)
    base = min((s.t0 for s in spans), default=0.0)
    ids = _lane_ids(s.lane for s in spans)
    events: List[Dict[str, object]] = [
        {"name": "process_name", "ph": "M", "pid": _ENGINE_PID, "tid": 0,
         "args": {"name": "serving-engine"}},
        {"name": "process_name", "ph": "M", "pid": _CONTROL_PID, "tid": 0,
         "args": {"name": "control-plane"}},
    ]
    for lane, (pid, tid) in sorted(ids.items()):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": lane}})
    for s in sorted(spans, key=lambda s: (s.t0, s.t1)):
        pid, tid = ids[s.lane]
        args: Dict[str, object] = dict(s.attrs)
        if s.rid is not None:
            args["rid"] = s.rid
        events.append({
            "name": s.name,
            "cat": "phase",
            "ph": "X",
            "ts": round((s.t0 - base) * 1e6, 3),
            "dur": round(max(0.0, s.t1 - s.t0) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_perfetto(doc: object) -> List[str]:
    """Structural schema check; returns the list of problems (empty =
    loads cleanly). Checked: top-level shape, per-event required keys
    and types, non-negative rebased timestamps/durations, and that
    every complete event's (pid, tid) has a thread_name row — a lane
    without one renders as an anonymous track, which usually means the
    exporter and the recorder disagree about lanes."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be an object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents must be a non-empty list"]
    named_lanes = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M", "i", "I"):
            problems.append(f"event {i}: unsupported ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"event {i}: missing name")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                problems.append(f"event {i}: {key} must be an int")
        if ph == "M":
            if ev.get("name") == "thread_name":
                named_lanes.add((ev.get("pid"), ev.get("tid")))
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: ts must be a number >= 0")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: dur must be a number >= 0")
    for i, ev in enumerate(events):
        if isinstance(ev, dict) and ev.get("ph") == "X" \
                and (ev.get("pid"), ev.get("tid")) not in named_lanes:
            problems.append(
                f"event {i}: lane (pid={ev.get('pid')}, "
                f"tid={ev.get('tid')}) has no thread_name metadata")
    return problems


def write_perfetto(spans: Sequence[Span], path: str) -> Dict[str, object]:
    """Export + write; returns the document (callers usually also
    ``validate_perfetto`` it — the bench does, CI asserts it)."""
    doc = to_perfetto(spans)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return doc
