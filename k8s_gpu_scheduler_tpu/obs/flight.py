"""Engine flight recorder — a fixed ring of per-step records.

A span buffer answers "what did request R wait on"; the flight recorder
answers "what was the ENGINE doing" — one compact record per batcher
step (kind, host wall ms, active slots, tokens emitted, accept rate,
pool watermark, admissions/evictions/retires, fault injections), kept in
a fixed-size drop-oldest ring. It is always on (one dict append per
step — orders of magnitude under the dispatch it records) and, unlike
the tracer, its contents SURVIVE preemption: ``ContinuousBatcher.
drain()`` folds the ring into the ``ServingSnapshot``, so a restored
engine can explain its pre-preemption behavior — the black-box that
makes "why did the p99 spike right before the spot reclaim" answerable
after the pod is gone.

Records are plain JSON-safe dicts (the snapshot's meta doc carries them
verbatim); ``seq`` is a monotonically increasing step counter that keeps
numbering continuous across drain/restore, and ``t_mono`` is the
recording engine's monotonic clock — meaningful for intra-ring deltas,
not across process boundaries (the restore record marks the seam).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from .trace import Clock, SYSTEM_CLOCK

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Thread-safe fixed ring of per-step records (drop-oldest)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: Optional[Clock] = None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.clock = clock or SYSTEM_CLOCK
        self._mu = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._seq = 0
        self._dropped = 0

    def record(self, kind: str, **fields) -> Dict[str, object]:
        """Append one step record; returns it (callers may keep a
        reference for tests). ``fields`` must be JSON-safe — they ride
        the snapshot's meta document unchanged."""
        with self._mu:
            rec = {"seq": self._seq, "kind": kind,
                   "t_mono": self.clock.monotonic(), **fields}
            self._seq += 1
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(rec)
            return rec

    @property
    def dropped(self) -> int:
        with self._mu:
            return self._dropped

    def __len__(self) -> int:
        with self._mu:
            return len(self._buf)

    def records(self, kind: Optional[str] = None) -> List[Dict[str, object]]:
        """Snapshot (oldest first), optionally filtered by step kind."""
        with self._mu:
            out = list(self._buf)
        if kind is not None:
            out = [r for r in out if r["kind"] == kind]
        return out

    # -- snapshot codec ----------------------------------------------------
    def to_payload(self) -> List[Dict[str, object]]:
        """JSON-safe dump for ``ServingSnapshot`` (oldest first)."""
        with self._mu:
            return [dict(r) for r in self._buf]

    def seed(self, payload: List[Dict[str, object]]) -> None:
        """Refill from a snapshot payload (restore path): the restored
        ring keeps the drained engine's records — trimmed to this ring's
        capacity, newest kept — and continues ``seq`` past them so the
        combined timeline stays ordered."""
        with self._mu:
            self._buf.clear()
            for rec in payload[-self.capacity:]:
                self._buf.append(dict(rec))
            if self._buf:
                self._seq = max(self._seq,
                                int(self._buf[-1]["seq"]) + 1)
