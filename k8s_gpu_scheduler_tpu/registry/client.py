"""RESP client for the kvstored registry — parity with the reference's
go-redis wrapper (pkg/redis/client/client.go:12-67: ``Client`` interface with
Set/Get/GetRange/GetKeys/FlushRedis and ``New(addr, password, db)``).

Pure-stdlib socket client: no redis-py dependency, works against kvstored or
a real Redis. Thread safety: one lock per client serializes request/response
pairs (the reference creates a fresh go-redis client per call instead —
gpu_plugins.go:534; pooling here avoids that per-call dial).

Failure handling (the robustness PR): every transport failure retries
under a bounded ``RetryPolicy`` (utils/retry.py — attempt cap,
exponential backoff with jitter, wall-clock deadline), with the
idempotency distinction preserved: a CONNECT failure is always safe to
retry (nothing was sent), a command that died MID-FLIGHT re-sends only
if it is in ``_IDEMPOTENT``. Backoff sleeps happen with the client lock
RELEASED — sleeping under the lock would stall every other thread's
call for the whole backoff ladder (graftcheck retry-lint's
``blocking-io-under-lock`` rule). ``on_retry`` is the metrics hook the
scheduler entrypoint maps onto
``tpu_sched_rpc_retries_total{client="registry"}``, and
``fault_injector`` (testing/faults.py) exposes the two failure points —
``registry.connect`` and ``registry.roundtrip`` — to the chaos
harness.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Callable, List, Optional

from ..utils.retry import RetryPolicy


class RegistryError(Exception):
    pass


class AuthError(RegistryError):
    pass


class ConnectionLost(RegistryError):
    """Transport-level failure (as opposed to a server -ERR reply)."""


# Commands safe to transparently re-send after a reconnect. DEL is absent on
# purpose: re-sending it after a dropped reply would erase the key a second
# time and report 0, lying to the caller about whether the key existed.
_IDEMPOTENT = {"GET", "MGET", "SET", "GETRANGE", "KEYS", "EXISTS", "DBSIZE", "PING", "INFO", "FLUSHDB"}


class Client:
    """``New(addr, password, db)`` parity (client.go:54-67)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 32767,
        password: Optional[str] = None,
        db: int = 0,
        timeout_s: float = 5.0,
        retry: Optional[RetryPolicy] = None,
        on_retry: Optional[Callable[[], None]] = None,
        fault_injector=None,
    ) -> None:
        self.host = host
        self.port = port
        self._password = password
        self._db = db
        self._timeout = timeout_s
        # Default bound: 4 tries, ~20/40/80 ms jittered backoff, and the
        # whole call (sleeps included) never past 2 s — a dead registry
        # costs a scheduler cycle a bounded, predictable delay, not a hang.
        self._retry = retry or RetryPolicy(attempts=4, base_s=0.02,
                                           max_s=0.25, deadline_s=2.0)
        self.on_retry = on_retry
        self._faults = fault_injector
        self._mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._buf = b""

    # -- connection --------------------------------------------------------
    def _connect_locked(self) -> None:
        s = socket.create_connection((self.host, self.port), timeout=self._timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._buf = b""
        if self._password:
            try:
                reply = self._roundtrip_locked(["AUTH", self._password])
            except AuthError:
                raise
            except RegistryError as e:
                raise AuthError(f"AUTH failed: {e}") from e
            if reply != "OK":
                raise AuthError(f"AUTH failed: {reply}")
        if self._db:
            reply = self._roundtrip_locked(["SELECT", str(self._db)])
            if reply != "OK":
                raise RegistryError(f"SELECT failed: {reply}")

    def _close_locked(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        finally:
            self._sock = None

    def close(self) -> None:
        with self._mu:
            self._close_locked()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- protocol ----------------------------------------------------------
    def _send_locked(self, argv: List[str]) -> None:
        out = [f"*{len(argv)}\r\n".encode()]
        for a in argv:
            data = a.encode() if isinstance(a, str) else a
            out.append(f"${len(data)}\r\n".encode() + data + b"\r\n")
        assert self._sock is not None
        self._sock.sendall(b"".join(out))

    def _read_line_locked(self) -> bytes:
        assert self._sock is not None
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionLost("connection closed by server")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact_locked(self, n: int) -> bytes:
        assert self._sock is not None
        while len(self._buf) < n:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionLost("connection closed by server")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply_locked(self):
        line = self._read_line_locked()
        kind, rest = line[:1], line[1:].decode()
        if kind == b"+":
            return rest
        if kind == b"-":
            if rest.startswith("NOAUTH"):
                raise AuthError(rest)
            raise RegistryError(rest)
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self._read_exact_locked(n + 2)[:-2]
            return data.decode()
        if kind == b"*":
            return [self._read_reply_locked() for _ in range(int(rest))]
        raise RegistryError(f"bad reply line: {line!r}")

    def _roundtrip_locked(self, argv: List[str]):
        self._send_locked(argv)
        return self._read_reply_locked()

    def _call(self, *argv: str):
        """One command under the bounded-retry policy. Two failure
        phases with different retry rights: a CONNECT-phase failure
        (dial, AUTH/SELECT transport) sent nothing, so ANY command
        retries it; a mid-flight failure (the server may have executed
        the command and the reply died) re-sends only idempotent
        commands — DEL stays absent from ``_IDEMPOTENT`` on purpose: a
        blind re-send after a dropped reply would erase the key a second
        time and report 0, lying to the caller about whether the key
        existed. A server -ERR reply never lands here (the server DID
        answer); AUTH failures abort immediately — retrying a bad
        password is a lockout, not a recovery."""
        policy = self._retry
        deadline = policy.deadline_from(time.monotonic())
        attempt = 0
        while True:
            sent = False
            try:
                with self._mu:
                    try:
                        if self._sock is None:
                            if self._faults is not None:
                                self._faults.fire("registry.connect",
                                                  drop_exc=ConnectionLost)
                            self._connect_locked()
                        sent = True
                        if self._faults is not None:
                            self._faults.fire("registry.roundtrip",
                                              drop_exc=ConnectionLost)
                        return self._roundtrip_locked(list(argv))
                    except (OSError, ConnectionLost):
                        # Transport died (server restarted, idle timeout,
                        # injected drop): the socket is poisoned either
                        # way — drop it so the next attempt redials.
                        self._close_locked()
                        raise
            except AuthError:
                raise
            except (OSError, ConnectionLost) as transport_err:
                if sent and argv[0].upper() not in _IDEMPOTENT:
                    raise ConnectionLost(
                        f"{argv[0]} failed mid-flight (not retried)"
                    ) from transport_err
                attempt += 1
                delay = policy.backoff_s(attempt)
                if policy.give_up(attempt, time.monotonic(), deadline,
                                  delay):
                    raise ConnectionLost(
                        f"{argv[0]} failed after {attempt} attempt(s): "
                        f"{transport_err}") from transport_err
                if self.on_retry is not None:
                    self.on_retry()
                # Backoff with the lock RELEASED: other threads' calls
                # proceed (and may themselves reconnect) while this one
                # waits out its jittered delay.
                time.sleep(delay)

    # -- API parity with client.go:26-67 ----------------------------------
    def set(self, key: str, value: str) -> None:
        reply = self._call("SET", key, value)
        if reply != "OK":
            raise RegistryError(f"SET: {reply}")

    def get(self, key: str) -> Optional[str]:
        return self._call("GET", key)

    def mget(self, *keys: str) -> List[Optional[str]]:
        """Values for ``keys`` in order, None per missing key — one round
        trip for a whole fleet's inventories (Redis MGET semantics)."""
        if not keys:
            return []
        return self._call("MGET", *keys)

    def get_range(self, key: str, start: int, end: int) -> str:
        return self._call("GETRANGE", key, str(start), str(end)) or ""

    def get_keys(self, pattern: str = "*") -> List[str]:
        return list(self._call("KEYS", pattern))

    def delete(self, *keys: str) -> int:
        return int(self._call("DEL", *keys))

    def exists(self, key: str) -> bool:
        return bool(self._call("EXISTS", key))

    def dbsize(self) -> int:
        return int(self._call("DBSIZE"))

    def flush(self) -> None:
        """FlushRedis parity (client.go:48-52)."""
        self._call("FLUSHDB")

    def ping(self) -> bool:
        return self._call("PING") == "PONG"
