"""Typed node-inventory schema stored in the registry.

The reference stores an untyped JSON list of UUID strings per node
(``nodeName → ["GPU-…", "MIG-…"]``, written by the profiler client at
pkg/profiler/cmd/client/client.go:70-79, read back by the scheduler at
gpu_plugins.go:536-542). The TPU analogue is richer — a node publishes its
chip inventory, slice shape/generation, and live utilization — so the schema
is typed here once and shared by the agent (writer) and scheduler (reader),
per SURVEY.md §7 step 2 ("typed inventory schema
node → {chips, slice shape, topology coords}").
"""
from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

# Key layout in the registry (db 0).
NODE_KEY_PREFIX = "node/"          # node/<name>   -> NodeInventory JSON
HEARTBEAT_SUFFIX = "/heartbeat"    # node/<name>/heartbeat -> unix ts
OBSERVED_KEY_PREFIX = "observed/"  # observed/<workload>/<column> -> Observation
LATENCY_KEY_PREFIX = "latency/"    # latency/<workload>/<column> -> p99 ms
REPLICA_KEY_PREFIX = "replica/"    # replica/<fleet>/<id> -> ReplicaSummary


def node_key(node_name: str) -> str:
    return NODE_KEY_PREFIX + node_name


def replica_key(fleet: str, replica: str) -> str:
    """Serving-replica state summary published for the cache-aware
    router (fleet/summary.py) — the serving-tier analogue of the
    reference's per-node GPU-UUID keys: each replica writes its radix
    digest + pool watermarks here, the router lists them with the same
    chunked-MGET pattern ``list_inventories`` uses."""
    return f"{REPLICA_KEY_PREFIX}{fleet}/{replica}"


def latency_key(workload: str, column: str) -> str:
    """Collector-folded MEASURED p99 per (workload, partition size) — what
    Score/rightsize consult so placement answers to observed latency, not
    only predicted QPS (VERDICT r4 #3). Columns use the workload publisher's
    chips-based convention ({chips}P_{GEN}) — both ends of this key are
    owned by this codebase, so the convention is self-consistent."""
    return f"{LATENCY_KEY_PREFIX}{workload}/{column}"


def observed_key(workload: str, column: str, co_located: bool = False) -> str:
    """Solo and co-located samples get DISTINCT keys: they feed different
    matrices (configurations vs interference), and sharing one key would
    let whichever replica wrote last clobber the other stream."""
    suffix = "/co" if co_located else ""
    return f"{OBSERVED_KEY_PREFIX}{workload}/{column}{suffix}"


@dataclass
class Observation:
    """One measured workload throughput sample, published by the workload
    itself (models print tok/s; models/llama.py pushes it here when the
    registry env is set). The recommender's Collector folds these back into
    the train matrices — closing the loop BASELINE's north star describes
    ("right-sizes pod requests against observed XLA-step utilization"),
    which round 2 left open (VERDICT.md weak #5): the matrices were static
    seed data forever.

    ``neighbors`` names the workloads co-located on the same partition when
    the sample was taken (the scheduler injects them as TPU_NEIGHBORS at
    PostBind). A sample WITH neighbors is an interference measurement — the
    collector folds its throughput DELTA vs the solo configurations cell
    into the interference matrix; a sample without neighbors is the solo
    throughput itself."""

    workload: str      # train-matrix row label, e.g. llama3_8b_serve
    column: str        # train-matrix column, e.g. 4P_V5E
    qps: float         # observed throughput (requests/s or steps/s)
    at: float = 0.0    # unix ts of the sample
    neighbors: List[str] = field(default_factory=list)
    # Measured per-request p99 latency (serving engines report it from
    # ContinuousBatcher.pop_request_metrics); 0 = not measured. The
    # collector folds it into latency/<workload>/<column> so the scheduler
    # right-sizes against observed latency.
    p99_ms: float = 0.0

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @staticmethod
    def from_json(raw: str) -> "Observation":
        d = json.loads(raw)
        return Observation(
            workload=d.get("workload", ""), column=d.get("column", ""),
            qps=float(d.get("qps", 0.0)), at=float(d.get("at", 0.0)),
            neighbors=[str(n) for n in d.get("neighbors", [])],
            p99_ms=float(d.get("p99_ms", 0.0)),
        )


@dataclass
class ChipInfo:
    """One TPU chip as the agent sees it (device id within the host)."""

    device_id: int
    # Torus coordinates of the chip within its slice, e.g. [0, 1] / [0, 1, 0].
    coords: List[int] = field(default_factory=list)
    # Live utilization 0..1 (MXU duty cycle), HBM bytes.
    duty_cycle: float = 0.0
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0


@dataclass
class NodeInventory:
    node_name: str
    # GKE label values: accelerator type + slice topology.
    accelerator: str = ""
    topology: str = ""
    chips: List[ChipInfo] = field(default_factory=list)
    # Worker index of this host within a multi-host slice (the value the
    # scheduler injects as TPU_WORKER_ID's base).
    worker_id: int = 0
    # Mean MXU duty cycle over the chips, 0..1 — the Score input replacing
    # the reference's DCGM_FI_PROF_GR_ENGINE_ACTIVE (prom_metrics.go:64).
    utilization: float = 0.0
    published_at: float = 0.0

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @staticmethod
    def from_json(raw: str) -> "NodeInventory":
        d = json.loads(raw)
        chips = [ChipInfo(**c) for c in d.pop("chips", [])]
        return NodeInventory(chips=chips, **d)


def publish_inventory(client, inv: NodeInventory) -> None:
    """Agent-side write (parity: profiler client Set(nodeName, jsonUuids),
    cmd/client/client.go:70-79 — but typed)."""
    client.set(node_key(inv.node_name), inv.to_json())


def read_inventory(client, node_name: str) -> Optional[NodeInventory]:
    """Scheduler-side read (parity: redis Get(nodeName) + JSON decode,
    gpu_plugins.go:536-542)."""
    raw = client.get(node_key(node_name))
    if raw is None:
        return None
    try:
        return NodeInventory.from_json(raw)
    except (ValueError, TypeError, KeyError):
        return None


def list_inventories(client) -> Dict[str, NodeInventory]:
    keys = [k for k in client.get_keys(NODE_KEY_PREFIX + "*")
            if not k.endswith(HEARTBEAT_SUFFIX)]
    if not keys:
        return {}
    # One MGET round trip per 512 keys (N+1 GETs before — at 256 nodes
    # that was 257 network round trips per listing). Chunked: kvstored's
    # RESP reader caps a command at 1024 array elements, so an unchunked
    # fleet-wide MGET would hard-drop the connection at >=1023 nodes.
    # Registries without mget (test fakes, plain KV stores) keep the
    # per-key path.
    mget = getattr(client, "mget", None)
    if callable(mget):
        values: List[Optional[str]] = []
        for i in range(0, len(keys), 512):
            values.extend(mget(*keys[i:i + 512]))
    else:
        values = [client.get(k) for k in keys]
    out: Dict[str, NodeInventory] = {}
    for raw in values:
        if raw is None:
            continue
        try:
            inv = NodeInventory.from_json(raw)
        except (ValueError, TypeError, KeyError):
            continue
        out[inv.node_name] = inv
    return out
