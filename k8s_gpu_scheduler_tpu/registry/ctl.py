"""registryctl — operator CLI for the kvstore registry.

Parity with the reference's redisCtl (pkg/redis/client/cmd/redisCtl.go:24-80:
flags ``-l`` list keys+values, ``-f`` flush, ``-c`` clientset/discovery).
Endpoint comes from flags or TPU_SCHED_REGISTRY_* env (config.py) instead of
the reference's in-cluster pod discovery.

Usage:
    python -m k8s_gpu_scheduler_tpu.registry.ctl -l
    python -m k8s_gpu_scheduler_tpu.registry.ctl -f
    python -m k8s_gpu_scheduler_tpu.registry.ctl --get node/v5e-0
"""
from __future__ import annotations

import argparse
import sys

from ..config import SchedulerConfig
from .client import Client


def main(argv=None) -> int:
    cfg = SchedulerConfig.from_env().registry
    ap = argparse.ArgumentParser(prog="registryctl", description=__doc__)
    ap.add_argument("--host", default=cfg.host)
    ap.add_argument("--port", type=int, default=cfg.port)
    ap.add_argument("--password", default=cfg.password)
    ap.add_argument("--db", type=int, default=cfg.db)
    ap.add_argument("-l", "--list", action="store_true", help="list all keys and values")
    ap.add_argument("-f", "--flush", action="store_true", help="flush the db")
    ap.add_argument("--get", metavar="KEY", help="print one key's value")
    ap.add_argument("--set", nargs=2, metavar=("KEY", "VALUE"), help="set a key")
    args = ap.parse_args(argv)

    with Client(args.host, args.port, password=args.password, db=args.db) as c:
        if args.flush:
            c.flush()
            print("OK")
        if args.set:
            c.set(args.set[0], args.set[1])
            print("OK")
        if args.get is not None:
            val = c.get(args.get)
            if val is None:
                print("(nil)", file=sys.stderr)
                return 1
            print(val)
        if args.list:
            for key in sorted(c.get_keys("*")):
                print(f"{key}\t{c.get(key)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
