"""KV registry layer (SURVEY.md L2) — Python client + typed inventory schema
for the native C++ kvstored server (native/kvstore)."""
from .client import AuthError, Client, RegistryError
from .inventory import (
    ChipInfo,
    NodeInventory,
    list_inventories,
    node_key,
    publish_inventory,
    read_inventory,
)

__all__ = [
    "AuthError",
    "Client",
    "RegistryError",
    "ChipInfo",
    "NodeInventory",
    "list_inventories",
    "node_key",
    "publish_inventory",
    "read_inventory",
]
