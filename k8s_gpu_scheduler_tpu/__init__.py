"""k8s_gpu_scheduler_tpu — a TPU-native Kubernetes-style scheduling framework.

A ground-up rebuild of the capabilities of dimgatz98/k8s-gpu-scheduler
(reference mounted at /root/reference) for GKE TPU node pools:

- ``api``:       typed object model (Pod, Node, ConfigMap, PodGroup) plus TPU
                 slice topology math (ICI torus coordinates).
- ``cluster``:   hermetic in-memory API server with watch streams, and
                 client-go-style shared informers / listers / indexers.
- ``sched``:     the scheduling framework itself (queue, cache, cycle,
                 Filter/Score/Reserve/Permit/PostBind plugin points) plus the
                 TPU plugin — the analogue of the reference's out-of-tree GPU
                 plugin (reference: pkg/plugins/gpu_plugin/gpu_plugins.go).
- ``registry``:  chip-inventory KV registry (C++ RESP server under native/,
                 socket client here) — parity with pkg/redis/client.
- ``metrics``:   Prometheus instant-query layer for the TPU device-plugin
                 exporter — parity with pkg/prom.
- ``recommender``: throughput/interference imputation service (gRPC) with a
                 JAX-native iterative imputer — parity with pkg/recommender.
- ``agent``:     per-node inventory/utilization publisher fed by the C++
                 prober under native/ — parity with pkg/profiler.
- ``models``/``ops``/``parallel``: the JAX workload layer the scheduler
                 places (Llama/BERT/ResNet; pallas kernels; mesh shardings).
"""

__version__ = "0.1.0"
