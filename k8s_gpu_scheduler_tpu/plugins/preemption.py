"""Preemption PostFilter — the last inherited kube-scheduler capability.

The reference compiles its plugin into upstream kube-scheduler v1.21
(/root/reference/cmd/scheduler/main.go:20-22, go.mod:55-66) and with it
inherits the DefaultPreemption PostFilter: an unschedulable high-priority
pod may evict lower-priority pods to make room. Round 2 had priority
*ordering* (sched/queue.py pops by the ``tpu.sched/priority`` annotation)
but no preemption — a full cluster starved a high-priority pod forever
(VERDICT.md missing #1).

Victim selection (DefaultPreemption's shape, simplified to the one extended
resource this scheduler manages):

- only pods with strictly LOWER priority are candidates;
- gang members are never victims (killing one collapses the whole gang —
  the gang plugin's quorum logic owns that lifecycle, plugins/gang.py);
- pods without a controller owner are never victims (a bare pod is gone
  forever; StatefulSet/Job/Deployment pods come back — the same guard
  VERDICT.md weak #6 asked of gang eviction);
- candidate nodes must match the pod's nodeSelector and be Ready — if a
  node failed Filter for a *non-capacity* reason, evicting pods there
  cannot help;
- per node, victims are taken lowest-priority-first until the pod fits;
  the chosen node minimizes (victim count, summed victim priority).

On success the victims are deleted through the API server and the pod is
requeued: their DELETE events release chips in the cache and flip the
queue, and the priority queue pops the preemptor before lower-priority
work can steal the freed capacity.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..api.objects import Pod
from ..sched.cache import NodeInfo
from ..sched.framework import CycleState, PostFilterPlugin, Status
from ..sched.queue import pod_priority

log = logging.getLogger(__name__)


class PreemptionPlugin(PostFilterPlugin):
    name = "Preemption"

    def __init__(self, handle) -> None:
        self.handle = handle

    # -- PostFilter --------------------------------------------------------
    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_reasons: Dict[str, str]) -> Status:
        prio = pod_priority(pod)
        if prio <= 0:
            return Status.unschedulable(
                "priority 0 pods never preempt (set tpu.sched/priority)")
        need = pod.spec.tpu_chips()
        if need <= 0:
            return Status.unschedulable("pod requests no TPU chips")

        best: Optional[Tuple[Tuple[int, int], str, List[Pod]]] = None
        for info in self.handle.cache.snapshot().values():
            victims = self._victims_for(pod, prio, need, info)
            if victims is None:
                continue
            cost = (len(victims), sum(pod_priority(v) for v in victims))
            if best is None or cost < best[0]:
                best = (cost, info.name, victims)

        if best is None:
            return Status.unschedulable(
                "no node frees enough chips by evicting lower-priority pods")
        _, node_name, victims = best
        for v in victims:
            try:
                self.handle.descriptor.delete_pod(
                    v.metadata.name, v.metadata.namespace)
                log.info("preempted %s (prio %d) on %s for %s (prio %d)",
                         v.metadata.key, pod_priority(v), node_name,
                         pod.metadata.key, prio)
            except Exception as e:  # noqa: BLE001 — victim may be gone already
                log.warning("preemption delete %s failed: %s",
                            v.metadata.key, e)
        state.write("preemption/node", node_name)
        return Status.success()

    # -- victim selection --------------------------------------------------
    def _victims_for(self, pod: Pod, prio: int, need: int,
                     info: NodeInfo) -> Optional[List[Pod]]:
        """Minimal victim list on this node, or None if preemption there
        cannot make the pod schedulable."""
        node = info.node
        for k, v in pod.spec.node_selector.items():
            if node.metadata.labels.get(k) != v:
                return None
        if "Ready" not in node.status.conditions:
            return None
        free = info.free_tpu
        if free >= need:
            # Capacity was never the problem on this node — Filter rejected
            # it for a reason eviction cannot fix.
            return None
        candidates = sorted(
            (p for p in info.pods
             if pod_priority(p) < prio
             and not p.pod_group()
             and p.metadata.owner_references),
            key=pod_priority,
        )
        victims: List[Pod] = []
        for v in candidates:
            victims.append(v)
            free += v.spec.tpu_chips()
            if free >= need:
                return victims
        return None
