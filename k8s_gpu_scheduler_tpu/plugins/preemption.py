"""Preemption PostFilter — the last inherited kube-scheduler capability.

The reference compiles its plugin into upstream kube-scheduler v1.21
(/root/reference/cmd/scheduler/main.go:20-22, go.mod:55-66) and with it
inherits the DefaultPreemption PostFilter: an unschedulable high-priority
pod may evict lower-priority pods to make room. Round 2 had priority
*ordering* (sched/queue.py pops by the ``tpu.sched/priority`` annotation)
but no preemption — a full cluster starved a high-priority pod forever
(VERDICT.md missing #1).

Victim selection (DefaultPreemption's shape, extended for TPU topology):

- only pods with strictly LOWER priority are candidates;
- gang members are never victims (killing one collapses the whole gang —
  the gang plugin's quorum logic owns that lifecycle, plugins/gang.py);
- pods without a controller owner are never victims (a bare pod is gone
  forever; StatefulSet/Job/Deployment pods come back);
- **topology-aware**: the freed chips must form a *partition* the
  preemptor fits (the sub-slice carving from plugins/tpu.py). Freeing 4
  chips spread over two 2x2 partitions of a v5p board does not make a
  4-chip pod schedulable — victims are chosen per-partition so eviction
  only happens where it produces a usable hole;
- **dry-run Filter**: before any eviction, the full Filter chain is re-run
  against a hypothetical NodeInfo with the victims removed (kube's
  DefaultPreemption runs RunFilterPlugins on the victims-less snapshot the
  same way). This generalizes the r3 advisor finding: a node rejected for
  a non-capacity reason (NotReady, selector mismatch, reshape 'applying',
  gang slice-group conflict) can never produce destructive deletes that
  don't help;
- per node, victims are taken lowest-priority-first; the chosen node
  minimizes (victim count, summed victim priority).

On success the victims are deleted through the API server, the preemptor is
**nominated** to the node (framework.Nominator — kube's
pod.status.nominatedNodeName), and the pod is requeued: the victims' DELETE
events release chips in the cache and flip the queue, other pods' Filter
counts the nominated chips as taken for equal-or-lower-priority rivals, and
the preemptor's next cycle lands on its nominated node.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..api.objects import Pod
from ..sched.cache import NodeInfo
from ..sched.framework import CycleState, PostFilterPlugin, Status
from ..sched.queue import pod_priority

log = logging.getLogger(__name__)


class PreemptionPlugin(PostFilterPlugin):
    name = "Preemption"

    def __init__(self, handle, filter_plugins: Optional[list] = None,
                 tpu=None) -> None:
        """``filter_plugins``: the profile's Filter chain, re-run against the
        victims-removed NodeInfo (dry run). ``tpu``: the TPUPlugin, borrowed
        for partition carving so victim selection is topology-aware. Both
        optional — without them selection degrades to the node-level
        capacity greedy."""
        self.handle = handle
        self.filter_plugins = filter_plugins or []
        self.tpu = tpu

    # -- PostFilter ----------------------------------------------------------
    def post_filter(self, state: CycleState, pod: Pod,
                    filtered_reasons: Dict[str, str]) -> Status:
        prio = pod_priority(pod)
        if prio <= 0:
            return Status.unschedulable(
                "priority 0 pods never preempt (set tpu.sched/priority)")
        need = pod.spec.tpu_chips()
        if need <= 0:
            return Status.unschedulable("pod requests no TPU chips")

        best: Optional[Tuple[Tuple[int, int], str, List[Pod]]] = None
        for info in self.handle.cache.snapshot().values():
            victims = self._victims_for(state, pod, prio, need, info)
            if victims is None:
                continue
            cost = (len(victims), sum(pod_priority(v) for v in victims))
            if best is None or cost < best[0]:
                best = (cost, info.name, victims)

        if best is None:
            return Status.unschedulable(
                "no node frees enough chips by evicting lower-priority pods")
        _, node_name, victims = best
        for v in victims:
            try:
                self.handle.descriptor.delete_pod(
                    v.metadata.name, v.metadata.namespace)
                log.info("preempted %s (prio %d) on %s for %s (prio %d)",
                         v.metadata.key, pod_priority(v), node_name,
                         pod.metadata.key, prio)
            except Exception as e:  # noqa: BLE001 — victim may be gone already
                log.warning("preemption delete %s failed: %s",
                            v.metadata.key, e)
        # Reserve the hole: Filter subtracts nominated chips for rivals of
        # equal/lower priority, and the preemptor's own next cycle prefers
        # this node (scheduler._select_node).
        self.handle.nominator.nominate(pod, node_name)
        state.write("preemption/node", node_name)
        return Status.success()

    # -- victim selection ------------------------------------------------------
    def _victims_for(self, state: CycleState, pod: Pod, prio: int, need: int,
                     info: NodeInfo) -> Optional[List[Pod]]:
        """Minimal victim list on this node, or None if preemption there
        cannot make the pod schedulable."""
        if info.allocatable_tpu < need:
            # Eviction can never create capacity the node doesn't have.
            return None
        # Effective free capacity mirrors Filter's view: chips held by
        # equal-or-higher-priority nominations are NOT free (evicting
        # residents can still help around them), so a node whose raw
        # free_tpu looks sufficient may genuinely need victims. Without
        # the subtraction such a node is skipped as "capacity was never
        # the problem" and the preemptor starves behind a stuck rival
        # nomination.
        nominated = (self.tpu._nominated_chips(pod, info)
                     if self.tpu is not None else 0)
        free = info.free_tpu - nominated
        if free >= need:
            # Capacity was never the problem on this node — Filter rejected
            # it for a reason eviction cannot fix (selector, NotReady,
            # reshape in flight, gang conflict).
            return None
        candidates = sorted(
            (p for p in info.pods
             if pod_priority(p) < prio
             and not p.pod_group()
             and p.metadata.owner_references),
            key=pod_priority,
        )
        victims = self._partition_victims(info, need, candidates, free,
                                          nominated)
        if victims is None:
            return None
        if not self._dry_run_filter(state, pod, info, victims):
            return None
        return victims

    def _partition_victims(self, info: NodeInfo, need: int,
                           candidates: List[Pod], node_free: int,
                           nominated: int = 0) -> Optional[List[Pod]]:
        """Pick victims so the freed chips form a usable hole.

        With the TPU plugin available the node's board is carved into its
        current partitions and victims are taken within the single partition
        that frees >= ``need`` chips at minimal cost. ``nominated`` chips
        (reserved for equal/higher-priority nominees) aren't
        partition-attributed, so each candidate partition plans for the
        nominee too: it consumes raw free space in the OTHER partitions
        first; the unabsorbed remainder must either fit in this partition
        beyond ``need`` (evicting further residents here) or be made by
        evicting lower-priority residents elsewhere on the board. Debiting
        every partition by the full nominated count instead would make
        eviction look futile exactly when a sibling partition can host the
        nominee — the starvation case the nomination adjustment exists
        for. The dry-run Filter is the final arbiter either way. Without
        the TPU plugin (or topology labels), falls back to node-level
        greedy over ``node_free`` (nomination-adjusted free chips)."""
        parts = self._partitions_of(info)
        if not parts:
            return self._greedy_victims(node_free, need, candidates)

        evictable = {p.metadata.uid for p in candidates}
        # Attribute every chip-consuming resident to a partition — the ONE
        # attribution rule shared with Score (tpu.residents_by_partition),
        # ConfigMap fetches memoized inside.
        by_part = self.tpu.residents_by_partition(info, parts)
        raw_free = {
            p.key: len(p.chip_ids) - sum(
                r.spec.tpu_chips() for r in by_part[p.key])
            for p in parts
        }

        def evict_within(part, amount) -> Optional[List[Pod]]:
            """Cheapest victims inside ``part`` so its free chips reach
            ``amount``; None if its occupants can't free that much."""
            free = raw_free[part.key]
            out: List[Pod] = []
            for r in sorted(by_part[part.key], key=pod_priority):
                if free >= amount:
                    break
                if r.metadata.uid not in evictable:
                    continue
                out.append(r)
                free += r.spec.tpu_chips()
            return out if free >= amount else None

        def cost(victims: List[Pod]) -> Tuple[int, int]:
            return (len(victims), sum(pod_priority(v) for v in victims))

        best_cost: Optional[Tuple[int, int]] = None
        best_victims: Optional[List[Pod]] = None
        for part in parts:
            if len(part.chip_ids) < need:
                continue  # this hole can never fit the preemptor
            # The nominee needs its chips in ONE partition too — planning
            # it as divisible (summed scattered free chips) would evict
            # workloads for a placement that can never happen. With
            # multiple nominees this single-partition requirement is
            # conservative: it declines some feasible preemptions, never
            # the reverse. Options per candidate partition:
            options: List[List[Pod]] = []
            if nominated <= 0:
                v = evict_within(part, need)
                if v is not None:
                    options.append(v)
            else:
                # (a) nominee shares this partition with the preemptor;
                if len(part.chip_ids) >= need + nominated:
                    v = evict_within(part, need + nominated)
                    if v is not None:
                        options.append(v)
                # (b) nominee lands whole in another partition q (evicting
                #     there too if q's occupants allow it).
                base = evict_within(part, need)
                if base is not None:
                    for q in parts:
                        if q.key == part.key or len(q.chip_ids) < nominated:
                            continue
                        vq = evict_within(q, nominated)
                        if vq is not None:
                            options.append(base + vq)
            for victims in options:
                c = cost(victims)
                if best_cost is None or c < best_cost:
                    best_cost, best_victims = c, victims
        return best_victims

    def _partitions_of(self, info: NodeInfo):
        if self.tpu is None:
            return []
        topo = info.slice_topology()
        if topo is None:
            return []
        try:
            inv = self.tpu._inventory(info.name)
            return self.tpu._partitions(info, topo, inv)
        except Exception:  # noqa: BLE001 — degrade to node-level greedy
            return []

    @staticmethod
    def _greedy_victims(free: int, need: int,
                        candidates: List[Pod]) -> Optional[List[Pod]]:
        if free >= need:
            return None  # capacity was never the problem here
        victims: List[Pod] = []
        for v in candidates:
            victims.append(v)
            free += v.spec.tpu_chips()
            if free >= need:
                return victims
        return None

    # -- dry run ---------------------------------------------------------------
    def _dry_run_filter(self, state: CycleState, pod: Pod, info: NodeInfo,
                        victims: List[Pod]) -> bool:
        """Re-run the Filter chain against this node with the victims gone —
        kube's DefaultPreemption contract. Catches every non-capacity
        rejection (NotReady, selector, reshape 'applying', gang slice-group)
        without parsing reason strings. No chain wired → legacy checks."""
        if not self.filter_plugins:
            node = info.node
            for k, v in pod.spec.node_selector.items():
                if node.metadata.labels.get(k) != v:
                    return False
            return "Ready" in node.status.conditions
        gone = {v.metadata.uid for v in victims}
        hypo = info.shallow_copy()
        hypo.pods = [p for p in hypo.pods if p.metadata.uid not in gone]
        hypo.requested_tpu -= sum(v.spec.tpu_chips() for v in victims)
        shadow = state.clone()
        for pl in self.filter_plugins:
            try:
                if not pl.filter(shadow, pod, hypo).ok:
                    return False
            except Exception:  # noqa: BLE001 — a crashing filter is a veto
                return False
        return True
