"""Gang scheduling — all-or-nothing admission with ICI-aware placement.

The reference has NO gang scheduling: every pod is scored and bound
independently (SURVEY.md §2 — grep for coscheduling/PodGroup/gang yields
nothing), which cannot place a multi-host JAX job (a v5p-16 pretrain is 4
pods that must land together on 4 ICI-connected hosts or not at all). This
plugin is the flagship new TPU capability (SURVEY.md §7.7, BASELINE config 4):

- Pods opt in with the ``tpu.sched/pod-group`` label naming a ``PodGroup``
  object (min_member, topology, schedule_timeout_s).
- **Permit** parks each gang pod as a WaitingPod; when waiting+bound members
  reach ``min_member``, every parked peer is allowed and the gang binds as a
  unit. A timeout (or any member's failure) rejects every parked peer, whose
  cycles then unreserve — chips never leak to a half-placed gang.
- **Filter/Score** steer members onto hosts of ONE slice (same slice-group
  label) with minimal added ICI torus diameter, using the worker-index label
  and the slice shape from ``host_coordinates`` (api/topology.py) — the
  locality the reference could not express with UUID strings.
- **Multislice** (GKE-standard, VERDICT r4 missing #3): when NO single
  slice group can host ``min_member`` hosts, the gang is allowed to span
  groups — data parallelism's gradient all-reduce rides DCN between slices
  while model parallelism stays on each slice's ICI
  (parallel/mesh.py multislice_mesh: outer dp axis = slice index). Score
  still packs members into as few groups as possible (every extra group is
  an extra DCN edge), and PostBind additionally injects TPU_SLICE_ID /
  TPU_NUM_SLICES / TPU_SLICE_HOSTNAMES so the workload can build the
  slice-major mesh. The spanning decision is re-evaluated while the gang is
  still confined to one group, and sticky once it actually spans.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..api.objects import (
    LABEL_NODEPOOL,
    LABEL_SLICE_GROUP,
    LABEL_WORKER_INDEX,
    Pod,
    PodGroup,
)
from ..api.topology import SliceTopology, ici_hop_distance
from ..sched.cache import NodeInfo
from ..sched.framework import (
    CycleState,
    FilterPlugin,
    MAX_NODE_SCORE,
    PermitPlugin,
    PostBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)
from .tpu import ENV_WORKER_HOSTNAMES, ENV_WORKER_ID

log = logging.getLogger(__name__)


def slice_group_of(info: NodeInfo) -> str:
    labels = info.node.metadata.labels
    return labels.get(LABEL_SLICE_GROUP) or labels.get(LABEL_NODEPOOL) or ""


def worker_index_of(info: NodeInfo) -> int:
    try:
        return int(info.node.metadata.labels.get(LABEL_WORKER_INDEX, "0"))
    except ValueError:
        return 0


class GangPlugin(
    PreFilterPlugin, FilterPlugin, ScorePlugin, ReservePlugin, PermitPlugin, PostBindPlugin
):
    name = "Gang"
    weight = 1.0

    def __init__(self, handle) -> None:
        self.handle = handle
        self._mu = threading.Lock()
        # group key -> {pod uid -> node name}, reserved-but-not-yet-confirmed
        # AND bound members (pruned when the pod or group is deleted).
        self._assignments: Dict[str, Dict[str, str]] = {}
        # Gangs allowed to span slice groups (no single group fits them) —
        # see pre_filter. Pruned with the assignments.
        self._multislice: set = set()
        # Prune bookkeeping when gang members disappear, so a re-created
        # gang under the same name starts from a clean count.
        self.handle.factory.informer("Pod").add_event_handler(
            on_delete=self._on_pod_delete
        )

    def _on_pod_delete(self, pod: Pod) -> None:
        name = pod.pod_group()
        if not name:
            return
        key = f"{pod.metadata.namespace}/{name}"
        with self._mu:
            members = self._assignments.get(key, {})
            members.pop(pod.metadata.uid, None)
            if not members:
                self._assignments.pop(key, None)
                self._multislice.discard(key)

    # -- group lookup ------------------------------------------------------
    def _group_of(self, pod: Pod) -> Optional[PodGroup]:
        name = pod.pod_group()
        if not name:
            return None
        try:
            return self.handle.descriptor.server.get(
                "PodGroup", name, pod.metadata.namespace
            )
        except Exception:  # noqa: BLE001 — NotFound
            return None

    # -- PreFilter ---------------------------------------------------------
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        name = pod.pod_group()
        if not name:
            return Status.success()
        group = self._group_of(pod)
        if group is None:
            return Status.unschedulable(f"pod group {name!r} not found")
        state.write("gang.group", group)
        # Early total-capacity check so a gang that can never fit doesn't
        # assume chips pod by pod and thrash the cluster.
        chips = pod.spec.tpu_chips()
        if chips > 0:
            # ONE snapshot pass serves the capacity check AND the
            # multislice decision (at 1024 nodes, repeated O(nodes) scans
            # per gang-member cycle were the mixed-load p99 tail).
            snap = self._cycle_infos(state)
            free_hosts = sum(
                1 for info in snap.values() if info.free_tpu >= chips)
            key = self._key(group)
            with self._mu:
                already = len(self._assignments.get(key, {}))
            if free_hosts + already < group.min_member:
                return Status.unschedulable(
                    f"gang {name}: {free_hosts} candidate hosts + {already} "
                    f"reserved < min_member {group.min_member}"
                )
            self._update_multislice(group, chips, snap)
        return Status.success()

    def _cycle_infos(self, state: CycleState) -> Dict[str, NodeInfo]:
        """The node snapshot, taken ONCE per scheduling cycle (CycleState
        memo): snapshot() walks every node under the cache lock, and the
        gang plugin needs it from PreFilter, Filter, and per-node Score."""
        infos = state.read("gang.cycle_infos")
        if infos is None:
            infos = self.handle.cache.snapshot()
            state.write("gang.cycle_infos", infos)
        return infos

    def _update_multislice(self, group: PodGroup, chips: int,
                           snap: Dict[str, NodeInfo]) -> None:
        """Decide (or re-decide) whether this gang may span slice groups:
        spanning turns on when NO single group can host min_member members,
        and heals back to single-slice only while the gang is still
        confined to at most one group — once members actually sit in two
        groups, flipping the flag would strand the rest at Filter."""
        key = self._key(group)
        with self._mu:
            assigned_nodes = set(
                self._assignments.get(key, {}).values())
            flagged = key in self._multislice
        spanning = len(self._slice_groups_of_nodes(assigned_nodes, snap)) > 1
        if flagged and spanning:
            return
        feasible = self._single_slice_feasible(group, chips, assigned_nodes,
                                               snap)
        with self._mu:
            if feasible:
                self._multislice.discard(key)
            else:
                self._multislice.add(key)

    def _single_slice_feasible(self, group: PodGroup, chips: int,
                               assigned_nodes: set,
                               snap: Dict[str, NodeInfo]) -> bool:
        """Can ANY one slice group provide min_member hosts (counting the
        gang's own reserved hosts as available in their group)?"""
        per_group: Dict[str, int] = {}
        for info in snap.values():
            g = slice_group_of(info)
            if info.name in assigned_nodes or info.free_tpu >= chips:
                per_group[g] = per_group.get(g, 0) + 1
        return any(n >= group.min_member for n in per_group.values())

    def _is_multislice(self, group: PodGroup) -> bool:
        with self._mu:
            return self._key(group) in self._multislice

    @staticmethod
    def _key(group: PodGroup) -> str:
        return group.metadata.key

    # -- Filter ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Pod, info: NodeInfo) -> Status:
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return Status.success()
        with self._mu:
            assigned = dict(self._assignments.get(self._key(group), {}))
        # One gang member per host — a multi-host JAX job runs exactly one
        # worker process per TPU VM.
        if info.name in assigned.values():
            return Status.unschedulable("host already holds a gang peer")
        if group.topology:
            topo = info.slice_topology()
            if topo is None:
                return Status.unschedulable("node missing TPU topology labels")
            try:
                want = SliceTopology.parse(topo.gen, group.topology)
            except ValueError as e:
                # PodGroup.topology is user data — a malformed value must be
                # a terminal verdict, not a retry-storm exception.
                return Status.unschedulable(f"bad gang topology: {e}")
            if topo.dims != want.dims:
                return Status.unschedulable(
                    f"slice shape {topo.dims} != gang topology {want.dims}"
                )
        # All members ride one slice's ICI: once any member is reserved, the
        # rest must share its slice group — unless the gang is in
        # multislice mode (no single group fits it; dp spans groups over
        # DCN, Score still packs).
        if assigned and not self._is_multislice(group):
            peer_groups = state.read("gang.peer_slice_groups")
            if peer_groups is None:
                peer_groups = self._slice_groups_of_nodes(
                    set(assigned.values()), self._cycle_infos(state))
                state.write("gang.peer_slice_groups", peer_groups)
            mine = slice_group_of(info)
            if peer_groups and mine not in peer_groups:
                return Status.unschedulable(
                    f"slice group {mine!r} differs from gang's {sorted(peer_groups)}"
                )
        return Status.success()

    def _slice_groups_of_nodes(self, node_names,
                               snap: Dict[str, NodeInfo]) -> set:
        """Slice groups of the named nodes — O(members) dict lookups, not a
        fleet scan (the snapshot is name-keyed; snap is REQUIRED so no
        caller can silently regress to one cache-lock snapshot per call,
        the 1024-node p99 tail)."""
        groups = set()
        for name in node_names:
            info = snap.get(name)
            if info is not None:
                g = slice_group_of(info)
                if g:
                    groups.add(g)
        return groups

    # -- Score -------------------------------------------------------------
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[float, Status]:
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return 0.0, Status.success()
        info: Optional[NodeInfo] = state.read(f"tpu.nodeinfo/{node_name}")
        if info is None:
            return 0.0, Status.success()
        topo = info.slice_topology()
        if topo is None:
            return 0.0, Status.success()
        with self._mu:
            assigned = dict(self._assignments.get(self._key(group), {}))
        if not assigned:
            # First member: prefer low worker indices so gangs pack from the
            # slice origin and leave contiguous room for the next gang —
            # but ONLY in a slice group that can actually host min_member
            # members (a first member landing in a too-small group strands
            # the gang there until the Permit timeout collapses it, then
            # the retry can pick the same group forever).
            base = float(
                MAX_NODE_SCORE - min(worker_index_of(info), MAX_NODE_SCORE))
            if not self._group_fits(state, pod, group, slice_group_of(info)):
                base /= 4.0
            return base, Status.success()
        # Later members: minimize added ICI hops to the reserved peers.
        # Distances are measured on the HOST grid (host_grid units), not chip
        # dims — wraparound shortcuts exist at host granularity too. In
        # multislice mode only IN-GROUP peers have meaningful ICI distance;
        # a node opening a NEW slice group scores at half scale (every
        # extra group is an extra DCN edge — pack first, span only when
        # packing is impossible).
        snap = self._cycle_infos(state)
        if self._is_multislice(group):
            mine_group = slice_group_of(info)
            in_group = {
                uid: node for uid, node in assigned.items()
                if (slice_group_of(snap[node]) if node in snap else "")
                == mine_group
            }
            if not in_group:
                base = float(
                    MAX_NODE_SCORE - min(worker_index_of(info), MAX_NODE_SCORE))
                return base / 2.0, Status.success()
            assigned = in_group      # in-group peers: full-scale ICI scoring
        try:
            coords, grid = self._host_coords(topo)
        except ValueError:
            return 0.0, Status.success()
        peers = self._peer_indices(assigned, snap)
        mine = worker_index_of(info)
        if mine >= len(coords) or any(p >= len(coords) for p in peers):
            return 0.0, Status.success()
        wrap = topo.has_wraparound
        added = sum(
            ici_hop_distance(coords[mine], coords[p], grid, wrap=wrap)
            for p in peers
        )
        worst = sum(grid) * max(len(peers), 1)
        score = max(0.0, MAX_NODE_SCORE * (1.0 - added / max(worst, 1)))
        return score, Status.success()

    def _group_fits(self, state: CycleState, pod: Pod, group: PodGroup,
                    slice_group: str) -> bool:
        """Can ``slice_group`` host min_member members? Candidate counts
        are computed once per cycle (CycleState memo) — Score runs per
        node."""
        sizes = state.read("gang.group_candidates")
        if sizes is None:
            chips = pod.spec.tpu_chips()
            sizes = {}
            for info in self._cycle_infos(state).values():
                if info.free_tpu >= chips:
                    g = slice_group_of(info)
                    sizes[g] = sizes.get(g, 0) + 1
            state.write("gang.group_candidates", sizes)
        return sizes.get(slice_group, 0) >= group.min_member

    @staticmethod
    def _host_coords(topo: SliceTopology):
        from ..api.topology import host_coordinates, host_grid

        return host_coordinates(topo.dims, topo.gen), host_grid(topo.dims, topo.gen)

    def _peer_indices(self, assigned: Dict[str, str],
                      snap: Dict[str, NodeInfo]) -> List[int]:
        """Worker indices of the reserved peers — O(members) lookups (this
        runs once per SCORED NODE; a fleet scan here was part of the
        1024-node mixed-load p99 tail)."""
        out = []
        for node in assigned.values():
            info = snap.get(node)
            if info is not None:
                out.append(worker_index_of(info))
        return out

    # -- Reserve -----------------------------------------------------------
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return Status.success()
        with self._mu:
            members = self._assignments.setdefault(self._key(group), {})
            members[pod.metadata.uid] = node_name
        return Status.success()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return
        key = self._key(group)
        with self._mu:
            members = self._assignments.get(key, {})
            members.pop(pod.metadata.uid, None)
            if not members:
                self._assignments.pop(key, None)
        # All-or-nothing: one member's failure collapses the whole gang —
        # reject every parked peer so their cycles unreserve too.
        self._reject_gang(key, f"gang peer {pod.metadata.name} failed")

    def _reject_gang(self, group_key: str, reason: str) -> None:
        def maybe_reject(wp) -> None:
            g = wp.pod.pod_group()
            if g and f"{wp.pod.metadata.namespace}/{g}" == group_key:
                wp.reject(reason)

        self.handle.iterate_waiting_pods(maybe_reject)
        # Post-quorum failure window: peers that were already ALLOWED and
        # bound are no longer waiting, but a gang with a missing worker
        # deadlocks jax.distributed init. Evict members that are bound yet
        # still Pending (never started) so the owner recreates them and the
        # gang reschedules as a unit; Running members mean the gang
        # previously succeeded and must not be touched.
        ns, name = group_key.split("/", 1)
        try:
            pods = self.handle.factory.informer("Pod").list()
            group = self.handle.descriptor.server.get("PodGroup", name, ns)
        except Exception:  # noqa: BLE001 — informer not started / group gone
            return
        bound = [
            p for p in pods
            if p.metadata.namespace == ns and p.pod_group() == name
            and p.spec.node_name and p.status.phase not in ("Succeeded", "Failed")
        ]
        if len(bound) >= group.min_member:
            # The gang is still viable (a straggler beyond min_member
            # failed) — leave the quorum alone.
            return
        for p in pods:
            if (
                p.metadata.namespace == ns
                and p.pod_group() == name
                and p.spec.node_name
                and p.status.phase == "Pending"
            ):
                if not p.metadata.owner_references:
                    # A bare pod has no controller to recreate it — deleting
                    # it would be permanent, worse than the deadlock we're
                    # clearing. Leave it; the operator owns its lifecycle.
                    log.warning(
                        "gang %s collapsed (%s): NOT evicting bare member %s "
                        "(no ownerReferences)", group_key, reason,
                        p.metadata.key,
                    )
                    continue
                log.warning(
                    "gang %s collapsed (%s): evicting bound member %s",
                    group_key, reason, p.metadata.key,
                )
                try:
                    self.handle.descriptor.delete_pod(
                        p.metadata.name, p.metadata.namespace
                    )
                except Exception:  # noqa: BLE001 — already gone
                    pass

    # -- Permit ------------------------------------------------------------
    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Status, float]:
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return Status.success(), 0.0
        key = self._key(group)
        # Members already through Reserve (this pod included).
        with self._mu:
            reserved = len(self._assignments.get(key, {}))
        if reserved >= group.min_member:
            # Quorum: release every parked peer, proceed ourselves.
            def allow(wp) -> None:
                g = wp.pod.pod_group()
                if g and f"{wp.pod.metadata.namespace}/{g}" == key:
                    wp.allow(self.name)

            self.handle.iterate_waiting_pods(allow)
            log.info("gang %s reached quorum (%d/%d) — admitting",
                     key, reserved, group.min_member)
            return Status.success(), 0.0
        return Status.wait(
            f"gang {key}: {reserved}/{group.min_member} members reserved"
        ), group.schedule_timeout_s

    # -- PostBind ----------------------------------------------------------
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        """Write the distributed-runtime env: this worker's id and every
        member's ADDRESS — what jax.distributed.initialize needs
        (coordinator = worker 0). Overrides the single-host values the TPU
        plugin wrote (profile order puts Gang after TPU).

        Addresses are pod-reachable, not node names: a pod doesn't listen on
        its node's address without hostNetwork, so a gang injected with node
        names places fine and then hangs at rendezvous (VERDICT.md r3
        missing #1). Per member: stable pod DNS
        ``<hostname>.<subdomain>.<ns>.svc`` (StatefulSet pods always carry
        hostname+subdomain — deploy/workloads/llama-gang.yaml's headless
        Service provides the records), else the node name (correct only for
        hostNetwork pods, which is what plain-pod gangs must use — there is
        no stable pod address before the pod starts). Deliberately NO pod-IP
        fallback: IPs are assigned after binding, so early members' PostBind
        would see no IPs and late members' would — each member would inject
        a DIFFERENT list (different coordinator!) and the rendezvous hangs.
        Both remaining inputs (pod spec fields, node assignment) are fixed
        before any PostBind runs, so every member derives the identical
        list. The reference never faces this class of bug: its injected
        env, CUDA_VISIBLE_DEVICES, is node-local (gpu_plugins.go:910-920)."""
        group: Optional[PodGroup] = state.read("gang.group")
        if group is None:
            return
        with self._mu:
            assigned = dict(self._assignments.get(self._key(group), {}))
        if not assigned:
            return
        # Deterministic worker ids: sort members SLICE-GROUP-major, then by
        # their host's worker-index label (falling back to node name), so
        # every member derives the same order independently AND a
        # multislice gang's ids are contiguous per slice — the slice-major
        # device order multislice_mesh (parallel/mesh.py) expects, putting
        # the outer dp axis across slices. Single-slice gangs sort exactly
        # as before (one group).
        infos = self.handle.cache.snapshot()        # already name-keyed

        def member_key(kv):
            node = kv[1]
            info = infos.get(node)
            return (slice_group_of(info) if info is not None else "",
                    worker_index_of(info) if info is not None else 0, node)

        members = sorted(assigned.items(), key=member_key)
        ns, gname = pod.metadata.namespace, group.metadata.name
        try:
            peers = self.handle.factory.informer("Pod").list()
        except Exception:  # noqa: BLE001 — informer not started (unit tests)
            peers = []
        by_uid = {p.metadata.uid: p
                  for p in peers
                  if p.metadata.namespace == ns and p.pod_group() == gname}
        by_uid[pod.metadata.uid] = pod
        addresses = [
            self._member_address(by_uid.get(uid), node)
            for uid, node in members
        ]
        my_id = next(
            (i for i, (uid, _) in enumerate(members)
             if uid == pod.metadata.uid), 0)
        data = {
            ENV_WORKER_ID: str(my_id),
            ENV_WORKER_HOSTNAMES: ",".join(addresses),
            "TPU_WORKER_COUNT": str(len(addresses)),
        }
        # Multislice gang: also inject the slice coordinates so the
        # workload can build the slice-major mesh (outer dp over DCN) —
        # pure functions of node labels + assignments, so every member
        # derives the same values.
        node_group = {
            node: (slice_group_of(infos[node]) if node in infos else "")
            for _, node in members
        }
        member_groups = sorted(set(node_group.values()))
        if len(member_groups) > 1:
            my_group = node_group.get(node_name, "")
            slice_hosts = [
                addr for (_, node), addr in zip(members, addresses)
                if node_group[node] == my_group
            ]
            data["TPU_SLICE_ID"] = str(member_groups.index(my_group))
            data["TPU_NUM_SLICES"] = str(len(member_groups))
            data["TPU_SLICE_HOSTNAMES"] = ",".join(slice_hosts)
        self.handle.descriptor.append_to_pod_configmaps(pod, data)

    @staticmethod
    def _member_address(peer: Optional[Pod], node_name: str) -> str:
        """One gang member's reachable address (see post_bind docstring).
        Must be a pure function of pod SPEC fields + node assignment so all
        members derive the same list — never of late-bound status like
        pod IP."""
        if peer is not None and peer.spec.subdomain:
            host = peer.spec.hostname or peer.metadata.name
            return f"{host}.{peer.spec.subdomain}.{peer.metadata.namespace}.svc"
        return node_name
