"""The TPU scheduler plugin — Filter/Score/Reserve/PostBind.

TPU-native rebuild of the reference's single GPU plugin
(/root/reference/pkg/plugins/gpu_plugin/gpu_plugins.go:455-930). Behavior
parity, re-architected:

- The assignable unit is a *sub-slice partition* of a host's board (the MIG
  instance analogue, SLICE_CONFIGS in api/topology.py), identified by a
  partition key instead of a GPU UUID string.
- Score is SIDE-EFFECT-FREE. The reference writes ConfigMaps while scoring
  (gpu_plugins.go:653-666,760-772) so the last-scored node's writes win even
  for nodes that lose — SURVEY.md §3.2 flags this as a correctness hazard.
  Here every decision is stashed in CycleState during Score, adopted by
  Reserve for the winning node only, and written to the cluster in PostBind.
- The SLO-slack/interference formula is exact parity (gpu_plugins.go:616-622,
  727-733): slack = SLO - (predicted_qps - interference), violated SLOs
  accumulate 1/(1+(|slack/SLO|+1)^2), satisfied ones 1/(1+|slack/SLO|), and
  the partition score is 100*((1-k)*pos_avg + k*neg_avg) with
  k = neg_count/(neg_count+pos_count).
- The no-registry fallback scores 100*(1-utilization) from the metrics layer
  (parity :508-527 — except the reference then returns 0 regardless, a bug
  we do not reproduce).
- Right-sizing parity (:638-666): for shareable hosts the plugin picks the
  cheapest partitioning whose predicted QPS still meets the pod's SLO and
  records it for PostBind (the MPS_<node> ConfigMap key analogue) — but the
  write happens post-bind, not mid-score.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Sequence, Tuple

from ..api.objects import ANN_RESHAPE_STATE, Pod
from ..api.topology import SliceTopology, TPUGen, chip_count, parse_topology
from ..registry.inventory import NodeInventory, node_key
from ..sched.cache import NodeInfo
from ..sched.framework import (
    CycleState,
    FilterPlugin,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    PostBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)

log = logging.getLogger(__name__)

# ConfigMap/env keys injected at PostBind — the CUDA_VISIBLE_DEVICES /
# CUDA_MPS_* analogues (gpu_plugins.go:910-920) in GKE-TPU vocabulary.
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"
ENV_WORKER_ID = "TPU_WORKER_ID"
ENV_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TOPOLOGY = "TPU_TOPOLOGY"
ENV_ACCELERATOR = "TPU_ACCELERATOR_TYPE"
ENV_HBM_LIMIT = "TPU_HBM_LIMIT_BYTES"
ENV_DUTY_PCT = "TPU_DUTY_CYCLE_PERCENTAGE"
ENV_NEIGHBORS = "TPU_NEIGHBORS"
ENV_SLO = "SLO"
# Latency SLO (p99 ms). The QPS SLO scores against the recommender's
# PREDICTIONS; this one scores against MEASURED latency — serving engines
# publish per-request p99 (models/llama.py --serve), the collector folds
# it into latency/<workload>/<column> keys, and Score/rightsize read them
# here. Closes VERDICT r4 #3: an SLO you never measure cannot be verified.
ENV_SLO_P99 = "SLO_P99_MS"

_GEN_SHORT = {TPUGen.V5E: "V5E", TPUGen.V6E: "V6E", TPUGen.V5P: "V5P", TPUGen.V4: "V4"}


def gen_short(gen: TPUGen) -> str:
    return _GEN_SHORT[gen]


class PredictionClient(Protocol):
    """What the plugin needs from the recommender (C8 parity —
    go_client/pkg/client_call.go:11-37). Implementations: the gRPC client in
    recommender/client.py; tests inject an in-memory fake."""

    def impute_configurations(self, index: str) -> Dict[str, float]: ...

    def impute_interference(self, index: str) -> Dict[str, float]: ...


class InventorySource(Protocol):
    """Registry read seam (redis Get(nodeName) analogue, gpu_plugins.go:536)."""

    def get(self, key: str) -> Optional[str]: ...


@dataclass
class Partition:
    """One assignable sub-slice of a host board (the MIG-instance analogue).
    chip_ids is a tuple: Partition objects are shared read-only from the
    carve cache across cycles, so an in-place edit would poison every later
    Score call."""

    key: str                    # e.g. "part-0/2x2"
    topology: str               # sub-slice shape, e.g. "2x2"
    chip_ids: Tuple[int, ...]   # device ids owned by this partition


@dataclass
class Decision:
    """What Score decided for one node; Reserve adopts the winner's, PostBind
    writes it. Replaces the reference's mid-score ConfigMap side channel."""

    node_name: str
    partition: Optional[Partition] = None
    # Right-sized partitioning chosen for the pod (MPS_<node> analogue),
    # e.g. "2x2" meaning: this pod is happy with a quarter board.
    rightsized_config: str = ""
    worker_id: int = 0
    hostnames: List[str] = field(default_factory=list)
    accelerator: str = ""
    hbm_limit_bytes: int = 0
    duty_pct: int = 100


def pod_slo(pod: Pod) -> float:
    """Parse the pod's SLO env (QPS target) — parity with the tolerant parse
    at gpu_plugins.go:460-469 (unset/garbage → 0)."""
    raw = pod.get_env(ENV_SLO)
    if not raw:
        return 0.0
    try:
        return float(raw)
    except ValueError:
        return 0.0


def pod_latency_slo(pod: Pod) -> float:
    """The pod's p99 latency SLO in ms (SLO_P99_MS env; unset/garbage → 0),
    same tolerant parse as the QPS SLO."""
    raw = pod.get_env(ENV_SLO_P99)
    if not raw:
        return 0.0
    try:
        return float(raw)
    except ValueError:
        return 0.0


def slo_slack_terms(slo: float, predicted: float, interference: float) -> Tuple[float, bool]:
    """One pod's contribution to the partition score (gpu_plugins.go:616-622).

    Returns (term, violated): violated pods feed negative_sum with a
    quadratically-penalized term; satisfied pods feed positive_sum.
    """
    slack = slo - (predicted - interference)
    rel = abs(slack / slo)
    if slo > predicted - interference:
        return 1.0 / (1.0 + (rel + 1.0) ** 2), True
    return 1.0 / (1.0 + rel), False


def combine_terms(pos_sum: float, pos_n: int, neg_sum: float, neg_n: int) -> float:
    """Blend satisfied/violated contributions (gpu_plugins.go:676-688)."""
    if pos_n and neg_n:
        k = neg_n / (neg_n + pos_n)
        return 100.0 * ((1 - k) * pos_sum / pos_n + k * neg_sum / neg_n)
    if neg_n:
        return 100.0 * neg_sum / neg_n
    if pos_n:
        return 100.0 * pos_sum / pos_n
    return 0.0


def match_interference(interference: Dict[str, float], pod_name: str) -> float:
    """First row of the interference reply whose key is a substring of the
    (normalized) pod name — parity with the '-'→'_' substring match at
    gpu_plugins.go:595-612."""
    normalized = pod_name.replace("-", "_")
    for key, val in interference.items():
        if key in normalized:
            return val
    return 0.0


class TPUPlugin(
    PreFilterPlugin, FilterPlugin, ScorePlugin, ReservePlugin, PostBindPlugin
):
    """The plugin. Construction mirrors New(_, handle) (gpu_plugins.go:928):
    everything it touches arrives via the Handle plus two injected clients."""

    name = "TPU"

    def __init__(
        self,
        handle,
        registry: Optional[InventorySource] = None,
        prom=None,
        recommender: Optional[PredictionClient] = None,
        reshaper=None,
        metrics=None,
    ) -> None:
        self.handle = handle
        self.registry = registry
        self.prom = prom
        self.recommender = recommender
        self.reshaper = reshaper
        # Degraded-scoring accounting (metrics: a metrics.exporter
        # Registry or None): when a recommender RPC exhausts its bounded
        # retries (recommender/client.py RetryPolicy), the cycle SCORES
        # WITHOUT that signal — skip, log once per outage, count — never
        # fails the pod. A scheduler that dies with its advisor inverts
        # the dependency hierarchy: predictions improve placement, their
        # absence must only degrade it.
        self._m_degraded = (metrics.counter(
            "tpu_sched_score_degraded_total",
            "Score decisions that skipped a failing signal source")
            if metrics is not None else None)
        self._recommender_down = False
        self.weight = handle.config.tpu_score_weight
        # Register the ConfigMap informer NOW (before factory.start()) so
        # Score's assignment readbacks hit the lister cache instead of one
        # API-server GET per resident pod per scored node — the reference
        # reads through its configMapLister for the same reason
        # (gpu_plugins.go:60-67,893). Writes still go through the
        # Descriptor (listers are read-only).
        try:
            self._cm_lister = handle.factory.informer("ConfigMap")
        except Exception:  # noqa: BLE001 — factory absent in bare unit tests
            self._cm_lister = None
        # node -> (raw registry value, parsed inventory); see _inventory.
        self._inv_parse_cache: Dict[str, Tuple[str, Optional[NodeInventory]]] = {}
        # (dims, gen, config-annotation) -> carved Partition tuple (read-only).
        self._carve_cache: Dict[Tuple, Tuple[Partition, ...]] = {}
        # pod uid -> (node, partition key) recorded at Reserve; bridges the
        # Reserve -> ConfigMap-visible-in-lister window (see reserve()).
        self._assigned_memo: Dict[str, Tuple[str, str]] = {}
        self._assign_mu = threading.Lock()

    # -- PreFilter ---------------------------------------------------------
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        chips = pod.spec.tpu_chips()
        if chips < 0:
            return Status.unschedulable("negative TPU request")
        state.write("tpu.request", chips)
        state.write("tpu.slo", pod_slo(pod))
        state.write("tpu.slo_p99", pod_latency_slo(pod))
        return Status.success()

    # -- Filter ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Pod, info: NodeInfo) -> Status:
        # node_selector must match (the reference encodes GPU model in the
        # node NAME and substring-matches it, gpu_plugins.go:478-499; we use
        # labels, the GKE-native mechanism).
        for k, v in pod.spec.node_selector.items():
            if info.node.metadata.labels.get(k) != v:
                return Status.unschedulable(f"node selector {k}={v} not matched")
        if "Ready" not in info.node.status.conditions:
            return Status.unschedulable("node not Ready")
        if info.node.metadata.annotations.get(ANN_RESHAPE_STATE) == "applying":
            # Chips are in flux mid-repartition — the reference instead
            # BLOCKS the scheduling thread through the whole MIG reconfig
            # (gpu_plugins.go:436-452); we skip the node and keep scheduling.
            return Status.unschedulable("slice repartition in progress")
        chips = self._requested_chips(state, pod)
        if chips == 0:
            # CPU-only pod (busybox smoke, BASELINE config 1) — any Ready
            # node that matches the selector will do.
            state.write(f"tpu.nodeinfo/{info.name}", info)
            return Status.success()
        if info.allocatable_tpu == 0:
            return Status.unschedulable("node has no TPUs")
        free = info.free_tpu - self._nominated_chips(pod, info)
        if free < chips:
            return Status.unschedulable(
                f"insufficient TPU chips: want {chips}, free {free}"
            )
        topo = info.slice_topology()
        if topo is None:
            return Status.unschedulable("node missing TPU accelerator/topology labels")
        if chips > topo.chips:
            return Status.unschedulable(
                f"request {chips} exceeds slice size {topo.chips}"
            )
        state.write(f"tpu.nodeinfo/{info.name}", info)
        return Status.success()

    @staticmethod
    def _requested_chips(state: CycleState, pod: Pod) -> int:
        """The pod's chip request, from PreFilter's per-cycle cache when
        present — Filter/Score run per NODE, and re-summing container
        resources each time was ~8% of the 1024-node cycle."""
        chips = state.read("tpu.request")
        return pod.spec.tpu_chips() if chips is None else chips

    def _nominated_chips(self, pod: Pod, info: NodeInfo) -> int:
        """Chips reserved on this node for pods nominated by preemption —
        kube-scheduler's addNominatedPods: when filtering pod P, nominated
        pods with priority >= P's count as already placed (their capacity
        was freed FOR them), so P cannot snipe it; lower-priority nominees
        yield to P exactly as they would on a real node."""
        from ..sched.queue import pod_priority

        nominator = getattr(self.handle, "nominator", None)
        if nominator is None or not nominator.has_nominations():
            return 0
        my_prio = pod_priority(pod)
        my_uid = pod.metadata.uid
        placed = {p.metadata.uid for p in info.pods}
        return sum(
            np.spec.tpu_chips()
            for np in nominator.pods_on(info.name)
            if np.metadata.uid != my_uid
            and np.metadata.uid not in placed
            and pod_priority(np) >= my_prio
        )

    # -- Score -------------------------------------------------------------
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[float, Status]:
        try:
            decision, raw = self._decide(state, pod, node_name)
        except Exception as e:  # noqa: BLE001 — a scoring dependency down ≠ cycle abort
            log.warning("score(%s) degraded: %s", node_name, e)
            return 0.0, Status.success()
        state.write(f"tpu.decision/{node_name}", decision)
        return raw, Status.success()

    def normalize_scores(self, state: CycleState, pod: Pod, scores: Dict[str, float]) -> Status:
        """Min-max rescale to [MIN,MAX] — parity NormalizeScore
        (gpu_plugins.go:816-841)."""
        if not scores:
            return Status.success()
        lo, hi = min(scores.values()), max(scores.values())
        if hi == lo:
            for k in scores:
                scores[k] = float(MAX_NODE_SCORE)
            return Status.success()
        span = MAX_NODE_SCORE - MIN_NODE_SCORE
        for k, v in scores.items():
            scores[k] = MIN_NODE_SCORE + span * (v - lo) / (hi - lo)
        return Status.success()

    # -- Reserve -----------------------------------------------------------
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        decision = state.read(f"tpu.decision/{node_name}")
        if decision is None:
            # Score was skipped (single feasible node) — decide now.
            try:
                decision, _ = self._decide(state, pod, node_name)
            except Exception as e:  # noqa: BLE001
                log.warning("reserve-time decide(%s) degraded: %s", node_name, e)
                decision = Decision(node_name=node_name)
        reshape = self._maybe_reshape(state, pod, node_name, decision)
        if reshape is not None:
            return reshape
        state.write("tpu.reserved", decision)
        if decision.partition is not None:
            # Scheduler-local assignment memo: the authoritative record is
            # the ConfigMap written at PostBind, but between Reserve and
            # the lister observing that write there's a window where a
            # concurrent cycle reading only ConfigMaps would not see this
            # pod's partition and could double-place onto it.
            # residents_by_partition consults this memo first.
            with self._assign_mu:
                self._assigned_memo[pod.metadata.uid] = (
                    node_name, decision.partition.key)
                while len(self._assigned_memo) > 4096:
                    self._assigned_memo.pop(next(iter(self._assigned_memo)))
        return Status.success()

    def _maybe_reshape(
        self, state: CycleState, pod: Pod, node_name: str, decision: Decision
    ) -> Optional[Status]:
        """Empty winning node whose partitioning can't serve this pod's SLO:
        kick off the ASYNC repartition and requeue the pod (reconfigure
        parity, gpu_plugins.go:357-452 — triggered on an empty A30 — minus
        its scheduling-thread block). The pod retries via backoff and lands
        once the agent confirms the new layout."""
        if self.reshaper is None or not decision.rightsized_config:
            return None
        info: Optional[NodeInfo] = state.read(f"tpu.nodeinfo/{node_name}")
        if info is None or any(p.spec.tpu_chips() > 0 for p in info.pods):
            return None  # only idle hosts repartition (reference parity)
        current = decision.partition.topology if decision.partition else ""
        if decision.rightsized_config == current:
            return None
        if self.reshaper.request(node_name, decision.rightsized_config):
            return Status.unschedulable(
                f"repartitioning {node_name} to {decision.rightsized_config}"
            )
        return None

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        state.write("tpu.reserved", None)
        with self._assign_mu:
            self._assigned_memo.pop(pod.metadata.uid, None)

    # -- PostBind ----------------------------------------------------------
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        """Inject the device assignment through the pod's EnvFrom ConfigMaps —
        the mechanism of gpu_plugins.go:843-920 (kubelet resolves EnvFrom at
        container start, after this write)."""
        decision: Optional[Decision] = state.read("tpu.reserved")
        if decision is None or decision.node_name != node_name:
            decision = Decision(node_name=node_name)
        data: Dict[str, str] = {}
        if decision.partition is not None:
            part = decision.partition
            data[ENV_VISIBLE_CHIPS] = ",".join(str(i) for i in part.chip_ids)
            data[ENV_TOPOLOGY] = part.topology
            # {nodeName: selectedUUID} parity (gpu_plugins.go:760-772) so
            # GetSLOs-style reverse lookups can attribute pods to partitions.
            data[node_name] = part.key
            # Co-located workloads on this partition, injected so the
            # workload can tag its throughput observations — the collector
            # folds tagged samples into the interference matrix (the r3
            # loop only ever fed configurations). Besides the (static) env
            # for the pod being bound, the LIVE per-pod registry keys of
            # every affected resident are refreshed: an already-running
            # tenant must stop tagging its samples as solo the moment a
            # neighbor arrives, or its degraded throughput poisons the
            # solo baseline. (Departures are not tracked — a stale tag
            # folds a ~zero delta into interference, the damped direction.)
            residents = self._partition_residents_confirmed(
                pod, node_name, part)
            neighbors = sorted({self._workload_of(p) for p in residents})
            if neighbors:
                data[ENV_NEIGHBORS] = ",".join(neighbors)
            if self.registry is not None:
                my_workload = self._workload_of(pod)
                try:
                    set_fn = getattr(self.registry, "set", None)
                    if set_fn is not None:
                        set_fn(f"neighbors/{pod.metadata.name}",
                               ",".join(neighbors))
                        for r in residents:
                            others = sorted(
                                {self._workload_of(q) for q in residents
                                 if q.metadata.uid != r.metadata.uid}
                                | {my_workload})
                            set_fn(f"neighbors/{r.metadata.name}",
                                   ",".join(others))
                except Exception:  # noqa: BLE001 — observability never blocks binds
                    log.debug("neighbor registry update failed", exc_info=True)
        if decision.accelerator:
            data[ENV_ACCELERATOR] = decision.accelerator
        if decision.rightsized_config:
            # MPS_<node> analogue (gpu_plugins.go:653-666).
            data[f"RIGHTSIZE_{node_name}"] = decision.rightsized_config
        if decision.duty_pct < 100:
            # CUDA_MPS_PINNED_DEVICE_MEM_LIMIT / ACTIVE_THREAD_PERCENTAGE
            # analogues (gpu_plugins.go:896-904). Keyed on duty_pct, not the
            # HBM value: the HBM debit can legitimately reach 0 on a
            # fully-occupied partition, and a shared-host pod must still get
            # its caps then — 0 free is a cap, not an exemption.
            data[ENV_HBM_LIMIT] = str(decision.hbm_limit_bytes)
            data[ENV_DUTY_PCT] = str(decision.duty_pct)
        data[ENV_WORKER_ID] = str(decision.worker_id)
        if decision.hostnames:
            data[ENV_WORKER_HOSTNAMES] = ",".join(decision.hostnames)
        written = self.handle.descriptor.append_to_pod_configmaps(pod, data)
        if not written:
            log.info("pod %s has no EnvFrom ConfigMap; assignment not injected",
                     pod.metadata.key)

    def _partition_residents_confirmed(
        self, pod: Pod, node_name: str, part: Partition
    ) -> List[Pod]:
        """Chip-consuming pods whose CONFIRMED assignment is this partition
        (excluding the pod being bound). Deliberately NOT
        residents_by_partition: its partitions[0] fallback is conservative
        for capacity accounting but would FABRICATE co-residency for pods
        whose assignment couldn't be read back — interference rows keyed on
        a neighbor that never shared chips."""
        info = self.handle.cache.snapshot().get(node_name)
        if info is None:
            return []
        with self._assign_mu:
            memo = dict(self._assigned_memo)
        out = []
        cm_cache: Dict[Tuple[str, str], object] = {}
        for p in info.pods:
            if p.spec.tpu_chips() == 0 or p.metadata.uid == pod.metadata.uid:
                continue
            held = memo.get(p.metadata.uid)
            if held is not None and held[0] == node_name:
                key = held[1]
            else:
                key = self._assigned_partition(p, node_name, cm_cache)
            if key == part.key:
                out.append(p)
        return out

    @staticmethod
    def _workload_of(pod: Pod) -> str:
        """Interference-matrix identity of a pod: its WORKLOAD_NAME env
        (the label the train matrices key on), else the pod name normalized
        to the matrix convention (dashes→underscores, trailing replica
        ordinal stripped) so learned columns merge with seed columns and
        match_interference's substring rule can hit them."""
        name = pod.get_env("WORKLOAD_NAME")
        if name:
            return name
        base = pod.metadata.name.replace("-", "_")
        head, _, tail = base.rpartition("_")
        return head if head and tail.isdigit() else base

    # -- decision core -----------------------------------------------------
    def _decide(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Decision, float]:
        """Compute (decision, raw_score) for one node. Pure read-only."""
        info: Optional[NodeInfo] = state.read(f"tpu.nodeinfo/{node_name}")
        if info is None:
            for name, i in self.handle.cache.snapshot().items():
                if name == node_name:
                    info = i
                    break
        if info is None:
            return Decision(node_name=node_name), 0.0

        chips_wanted = self._requested_chips(state, pod)
        topo = info.slice_topology()
        if chips_wanted == 0 or topo is None:
            # CPU pod or unlabeled node: score by inverse utilization only.
            return Decision(node_name=node_name), self._utilization_score(node_name)

        inv = self._inventory(node_name)
        partitions = self._partitions(info, topo, inv)
        slo = state.read("tpu.slo") or pod_slo(pod)
        slo_p99 = state.read("tpu.slo_p99")
        if slo_p99 is None:
            slo_p99 = pod_latency_slo(pod)
        workload = self._workload_of(pod)

        if inv is None and self.registry is not None:
            # Registry reachable but node unpublished — conservative parity
            # with the no-registry DCGM fallback (gpu_plugins.go:508-527).
            decision = Decision(node_name=node_name, accelerator=topo.gen.value)
            decision.partition = self._pick_free_partition(info, partitions, chips_wanted)
            return decision, self._utilization_score(node_name, inv)

        decision = Decision(node_name=node_name, accelerator=topo.gen.value)
        if slo <= 0 or self.recommender is None:
            # No QPS SLO or no predictor: inverse-utilization score,
            # emptiest fitting partition (per-chip duty/HBM break
            # pod-count ties). A latency SLO still right-sizes — measured
            # p99 needs only the registry, not the recommender.
            decision.partition = self._pick_free_partition(
                info, partitions, chips_wanted, inv)
            if slo_p99 > 0:
                decision.rightsized_config = self._rightsize(
                    topo, slo, chips_wanted, workload, slo_p99)
            self._fill_sharing_limits(decision, topo, partitions, inv)
            return decision, self._utilization_score(node_name, inv=inv)

        # One registry GET per latency size per _decide call — _slo_score's
        # partition loop and _rightsize's config loop read the same
        # latency/<workload>/<size> keys.
        lat_cache: Dict[int, Optional[float]] = {}
        score, best = self._slo_score(info, topo, partitions, pod, slo,
                                      chips_wanted, inv, slo_p99, lat_cache)
        decision.partition = best or self._pick_free_partition(
            info, partitions, chips_wanted, inv)
        decision.rightsized_config = self._rightsize(
            topo, slo, chips_wanted, workload, slo_p99, lat_cache)
        self._fill_sharing_limits(decision, topo, partitions, inv)
        return decision, score

    def _impute(self, kind: str, index: str) -> Dict[str, float]:
        """Recommender prediction with graceful degradation: a client
        whose bounded retries are spent (deadline expired, attempts
        exhausted — recommender/client.py) raises, and the answer here
        is the EMPTY prediction — every downstream consumer already
        treats a missing column as "no signal", so the cycle completes
        with utilization/latency-only scoring instead of dying. Logged
        once per outage transition (not per call — Score makes 2 calls
        per resident pod per node) and counted per skipped signal so the
        degradation is visible on /metrics while it lasts."""
        assert self.recommender is not None
        fn = (self.recommender.impute_configurations if kind == "conf"
              else self.recommender.impute_interference)
        try:
            reply = fn(index)
        except Exception as e:  # noqa: BLE001 — degrade, never fail the cycle
            if not self._recommender_down:
                log.warning(
                    "recommender degraded (%s: %s); scoring without its "
                    "signal", type(e).__name__, e)
                self._recommender_down = True
            if self._m_degraded is not None:
                self._m_degraded.inc(client="recommender")
            return {}
        if self._recommender_down:
            log.info("recommender recovered; full scoring resumed")
            self._recommender_down = False
        return reply

    def _slo_score(
        self,
        info: NodeInfo,
        topo: SliceTopology,
        partitions: Sequence[Partition],
        pod: Pod,
        slo: float,
        chips_wanted: int,
        inv: Optional[NodeInventory] = None,
        slo_p99: float = 0.0,
        lat_cache: Optional[Dict[int, Optional[float]]] = None,
    ) -> Tuple[float, Optional[Partition]]:
        """The hot loop (gpu_plugins.go:561-756): for every partition, blend
        SLO slack of already-placed pods and of the incoming pod; argmax.
        Per-chip duty cycle breaks SLO-score ties so the emptier sub-slice
        wins — the per-UUID DCGM richness (gpu_plugins.go:162-236) the
        reference feeds its loop and r3 published but ignored. With a
        latency SLO, the incoming pod also contributes a MEASURED-latency
        term per partition size (same slack shape, latency units), so a
        node carved into sub-slices this workload has been observed to
        violate its p99 on loses to a node with bigger partitions."""
        assert self.recommender is not None
        gen = gen_short(topo.gen)
        lat_workload = self._workload_of(pod)
        if lat_cache is None:
            lat_cache = {}
        parts_count = max(len(partitions), 1)
        conf_index = f"{parts_count}P_{gen}"
        placed = self._placed_slos(info, partitions)

        best_score, best_part = float(MIN_NODE_SCORE), None
        best_duty = float("inf")
        incoming_conf = self._impute("conf", pod.metadata.name)
        incoming_intf = self._impute("intf", f"{pod.metadata.name}_{gen}")
        # Hoist per-resident-pod predictions out of the partition loop —
        # conf_index and gen are loop-invariant, so with the real gRPC
        # recommender this is 2 roundtrips per resident pod instead of
        # 2 × partition_count (the reference pays the full quadratic cost,
        # gpu_plugins.go:577-590).
        pred_cache: Dict[str, Tuple[Optional[float], Dict[str, float]]] = {}
        for names in placed.values():
            for other_name in names:
                if other_name not in pred_cache:
                    pred_cache[other_name] = (
                        self._impute("conf", other_name).get(conf_index),
                        self._impute("intf", f"{other_name}_{gen}"),
                    )
        for part in partitions:
            if len(part.chip_ids) < chips_wanted:
                continue
            pos_sum, neg_sum, pos_n, neg_n = 0.0, 0.0, 0, 0
            co_located = placed.get(part.key, {})
            for other_name, other_slo in co_located.items():
                if other_slo <= 0:
                    continue
                conf, intf_row = pred_cache[other_name]
                if conf is None:
                    continue
                intf = sum(
                    match_interference(intf_row, third)
                    for third in co_located
                    if third != other_name
                )
                intf += match_interference(intf_row, pod.metadata.name)
                term, violated = slo_slack_terms(other_slo, conf, intf)
                if violated:
                    neg_sum += term
                    neg_n += 1
                else:
                    pos_sum += term
                    pos_n += 1

            conf = incoming_conf.get(conf_index)
            if conf is not None:
                intf = sum(
                    match_interference(incoming_intf, third) for third in co_located
                )
                term, violated = slo_slack_terms(slo, conf, intf)
                if violated:
                    neg_sum += term
                    neg_n += 1
                else:
                    pos_sum += term
                    pos_n += 1

            if slo_p99 > 0:
                chips_p = len(part.chip_ids)
                if chips_p not in lat_cache:
                    # One registry GET per partition SIZE per score call —
                    # a carved board repeats the same size across its
                    # partitions, and this sits in the hot loop.
                    lat_cache[chips_p] = self._measured_p99(
                        lat_workload, chips_p, gen)
                measured = lat_cache[chips_p]
                if measured is not None:
                    # Same slack shape as slo_slack_terms, latency units
                    # (violation = measured ABOVE the target).
                    rel = abs(measured - slo_p99) / slo_p99
                    if measured > slo_p99:
                        neg_sum += 1.0 / (1.0 + (rel + 1.0) ** 2)
                        neg_n += 1
                    else:
                        pos_sum += 1.0 / (1.0 + rel)
                        pos_n += 1

            part_score = combine_terms(pos_sum, pos_n, neg_sum, neg_n)
            duty, _, _ = self._partition_load(part, inv)
            if part_score > best_score or (
                part_score == best_score and duty < best_duty
            ):
                best_score, best_part, best_duty = part_score, part, duty
        return best_score, best_part

    def _rightsize(self, topo: SliceTopology, slo: float, chips_wanted: int,
                   workload: str = "", slo_p99: float = 0.0,
                   lat_cache: Optional[Dict[int, Optional[float]]] = None,
                   ) -> str:
        """Cheapest partitioning that still meets the SLO — V100/MPS
        right-sizing parity (gpu_plugins.go:638-666), smallest sub-slice
        preferred (the reference prefers the *lowest predicted QPS* that
        still clears the SLO). Sub-slices smaller than the pod's own chip
        request are never candidates — repartitioning a node so the
        triggering pod can't fit would strand it.

        Latency overlay (``slo_p99`` > 0): a candidate whose MEASURED p99
        for this workload at that sub-slice size violates the latency SLO
        is excluded — so a serving pod observed missing its p99 on a small
        partition gets a bigger one on its next placement, even when the
        recommender's QPS prediction says the small one suffices. Without
        a QPS SLO the latency overlay alone right-sizes, but only when a
        violation was actually observed (no measured violation → no
        reshape churn)."""
        if self.recommender is None and slo_p99 <= 0:
            return ""
        from ..api.topology import SLICE_CONFIGS

        gen = gen_short(topo.gen)
        if lat_cache is None:
            lat_cache = {}
        candidates: List[Tuple[str, int, int]] = []   # (cfg, parts, chips)
        max_violating = 0
        for cfg, parts in SLICE_CONFIGS[topo.gen]:
            chips_c = chip_count(parse_topology(cfg))
            if chips_c < chips_wanted:
                continue
            if slo_p99 > 0:
                if chips_c not in lat_cache:
                    lat_cache[chips_c] = self._measured_p99(
                        workload, chips_c, gen)
                measured = lat_cache[chips_c]
                if measured is not None and measured > slo_p99:
                    max_violating = max(max_violating, chips_c)
                    continue
            candidates.append((cfg, parts, chips_c))
        # Latency is monotone in partition size for a fixed workload: any
        # config AT OR BELOW a measured-violating size is out too, even if
        # never measured itself — otherwise a violation at 4 chips could
        # "rightsize" the pod down to an unmeasured 1-chip slice, the
        # opposite of escaping the violation.
        eligible = [c for c in candidates if c[2] > max_violating]
        if slo <= 0 or self.recommender is None:
            # Latency-only mode: smallest non-violating sub-slice, and only
            # when a measured violation exists to escape from.
            if not max_violating or not eligible:
                return ""
            return min(eligible, key=lambda e: e[2])[0]
        best_cfg, best_pred = "", -1.0
        for cfg, parts, _ in eligible:
            preds = self._impute("conf", cfg)
            pred = preds.get(f"{parts}P_{gen}")
            if pred is None:
                continue
            if pred > slo and (best_pred < 0 or pred < best_pred):
                best_cfg, best_pred = cfg, pred
        return best_cfg

    # -- measured latency (SLO_P99_MS loop) --------------------------------
    def _measured_p99(self, workload: str, chips: int,
                      gen: str) -> Optional[float]:
        """Collector-folded p99 for (workload, {chips}P_{gen}) from the
        registry (recommender/collector.py _fold_latencies writes it);
        None = never measured / registry absent."""
        if self.registry is None or not workload:
            return None
        from ..registry.inventory import latency_key

        try:
            raw = self.registry.get(latency_key(workload, f"{chips}P_{gen}"))
        except Exception:  # noqa: BLE001 — registry down = no latency signal
            return None
        if not raw:
            return None
        try:
            return float(raw)
        except ValueError:
            return None


    # -- partition / inventory helpers ------------------------------------
    def _inventory(self, node_name: str) -> Optional[NodeInventory]:
        """Registry read with a parse cache keyed on the RAW value: Score
        reads every feasible node's inventory every cycle, but the agent
        republishes each node at most every heartbeat — re-decoding an
        unchanged JSON blob per (pod × node) was the top cycle cost at 256
        nodes. The raw string is the cache key, so a republished value is
        picked up immediately; dict ops are GIL-atomic, so concurrent Score
        threads at worst parse the same blob twice."""
        if self.registry is None:
            return None
        try:
            raw = self.registry.get(node_key(node_name))
        except Exception:  # noqa: BLE001 — registry down = degrade, don't abort
            return None
        if raw is None:
            return None
        cached = self._inv_parse_cache.get(node_name)
        if cached is not None and cached[0] == raw:
            return cached[1]
        try:
            inv = NodeInventory.from_json(raw)
        except (ValueError, TypeError, KeyError):
            inv = None
        self._inv_parse_cache[node_name] = (raw, inv)
        return inv

    def _partitions(
        self, info: NodeInfo, topo: SliceTopology, inv: Optional[NodeInventory]
    ) -> Tuple[Partition, ...]:
        """Carve the host board into assignable partitions according to the
        node's current slice config annotation (the nvidia.com/mig.config
        analogue) — default one whole-board partition. Board size comes from
        host_board (a multi-host v5e host owns a 2x2 4-chip board, NOT the
        full 2x4 — topology.py:100-118), so partition chip ids always exist
        on this host."""
        from ..api.objects import ANN_SLICE_CONFIG
        from ..api.topology import format_topology, host_board

        cfg = info.node.metadata.annotations.get(ANN_SLICE_CONFIG, "")
        # The carve is a pure function of (board, config annotation) and
        # Partition objects are read-only after construction — memoized so
        # Score at fleet scale doesn't rebuild identical lists per node per
        # cycle (it was a top allocation site in the 256-node profile).
        memo_key = (topo.dims, topo.gen, cfg)
        cached = self._carve_cache.get(memo_key)
        if cached is not None:
            return cached
        board = host_board(topo.dims, topo.gen)
        total = chip_count(board)
        if cfg:
            try:
                per = chip_count(parse_topology(cfg))
            except ValueError:
                per = total
            shown = cfg
        else:
            shown = format_topology(board)
            per = total
        per = max(1, min(per, total))
        count = total // per
        parts = tuple(
            Partition(
                key=f"part-{i}/{shown}",
                topology=shown,
                chip_ids=tuple(range(i * per, (i + 1) * per)),
            )
            for i in range(count)
        )
        if len(self._carve_cache) > 1024:
            self._carve_cache.clear()
        self._carve_cache[memo_key] = parts
        return parts

    def residents_by_partition(
        self, info: NodeInfo, partitions: Sequence[Partition]
    ) -> Dict[str, List[Pod]]:
        """partition key → chip-consuming residents, attributed by ConfigMap
        readback ({nodeName: partition} written at PostBind); pods with no
        assignment yet go to the first partition so its capacity still
        counts (conservative). The ONE attribution rule — Score
        (_placed_slos) and preemption victim selection both call this, so
        they can never diverge. ConfigMap fetches are memoized per call:
        gang members share one map, and each fetch is an API-server
        round-trip (cluster/resources.py get_configmap)."""
        fallback = partitions[0].key if partitions else ""
        out: Dict[str, List[Pod]] = {p.key: [] for p in partitions}
        cm_cache: Dict[Tuple[str, str], object] = {}
        # Per-resident .get()s under the lock, NOT a dict copy: the memo
        # holds up to 4096 entries and this runs once per Score call — the
        # copy dominated the 256-node cycle profile.
        with self._assign_mu:
            held_by_uid = [
                (p, self._assigned_memo.get(p.metadata.uid))
                for p in info.pods if p.spec.tpu_chips() > 0
            ]
        for p, held in held_by_uid:
            if held is not None and held[0] == info.name and held[1] in out:
                key = held[1]
            else:
                key = self._assigned_partition(p, info.name, cm_cache)
            if key is None or key not in out:
                key = fallback
            out.setdefault(key, []).append(p)
        return out

    def _placed_slos(
        self, info: NodeInfo, partitions: Sequence[Partition]
    ) -> Dict[str, Dict[str, float]]:
        """partition key → {pod name → SLO} for pods already on the node —
        GetSLOs parity (gpu_plugins.go:87-160)."""
        out: Dict[str, Dict[str, float]] = {}
        for key, residents in self.residents_by_partition(info, partitions).items():
            for p in residents:
                out.setdefault(key, {})[p.metadata.name] = pod_slo(p)
        return out

    def _assigned_partition(
        self,
        pod: Pod,
        node_name: str,
        cm_cache: Optional[Dict] = None,
    ) -> Optional[str]:
        for c in pod.spec.containers:
            for ref in c.env_from:
                cache_key = (ref.name, pod.metadata.namespace)
                if cm_cache is not None and cache_key in cm_cache:
                    cm = cm_cache[cache_key]
                else:
                    cm = self._read_configmap(ref.name, pod.metadata.namespace)
                    if cm_cache is not None:
                        cm_cache[cache_key] = cm
                if cm is not None and node_name in cm.data:
                    return cm.data[node_name]
        return None

    def _read_configmap(self, name: str, namespace: str):
        """Lister-first ConfigMap read (see __init__); API GET fallback when
        the informer isn't running (unit tests, bare construction)."""
        if self._cm_lister is not None and self._cm_lister.has_synced():
            return self._cm_lister.get(name, namespace)
        try:
            return self.handle.descriptor.get_configmap(name, namespace)
        except Exception:  # noqa: BLE001 — NotFound or API hiccup
            return None

    def _pick_free_partition(
        self,
        info: NodeInfo,
        partitions: Sequence[Partition],
        chips_wanted: int,
        inv: Optional[NodeInventory] = None,
    ) -> Optional[Partition]:
        """Emptiest partition with enough chips: fewest pods already
        attributed, then lowest live per-chip duty cycle, then least HBM in
        use — the per-UUID metrics the reference scores with
        (GetDcgmMetricsForUUIDS, gpu_plugins.go:162-236 feeding :561-756).
        Deterministic (the reference shuffles UUIDs at :561 — determinism
        makes hermetic tests exact)."""
        if not partitions:
            return None
        placed = self._placed_slos(info, partitions)
        eligible = [p for p in partitions if len(p.chip_ids) >= chips_wanted]
        if not eligible:
            return None

        def rank(p: Partition):
            duty, hbm_used, _ = self._partition_load(p, inv)
            return (len(placed.get(p.key, {})), duty, hbm_used, p.key)

        return min(eligible, key=rank)

    @staticmethod
    def _partition_load(
        part: Partition, inv: Optional[NodeInventory]
    ) -> Tuple[float, int, int]:
        """(mean duty cycle 0..1, HBM bytes used, HBM bytes total) over the
        partition's chips, from the agent-published per-chip inventory
        (registry/inventory.py ChipInfo). No inventory → all zeros, so
        ranking degrades to pod-count order."""
        if inv is None or not inv.chips:
            return 0.0, 0, 0
        chips = [c for c in inv.chips if c.device_id in part.chip_ids]
        if not chips:
            return 0.0, 0, 0
        duty = sum(c.duty_cycle for c in chips) / len(chips)
        used = sum(c.hbm_used_bytes for c in chips)
        total = sum(c.hbm_total_bytes for c in chips)
        return duty, used, total

    def _fill_sharing_limits(
        self,
        decision: Decision,
        topo: SliceTopology,
        partitions: Sequence[Partition],
        inv: Optional[NodeInventory] = None,
    ) -> None:
        """HBM/duty caps when the host is shared — the MPS-limit analogue
        (gpu_plugins.go:896-904: 2 partitions → half memory/50%, 4 →
        quarter/25%). HBM already in use on the assigned partition (per-chip
        agent inventory) is debited from the cap, so a pod landing next to a
        resident tenant is budgeted what is actually free, not the nameplate
        capacity."""
        n = len(partitions)
        if n <= 1:
            return
        per_chip_hbm = int(topo.gen.hbm_gib * (1 << 30))
        chips = len(decision.partition.chip_ids) if decision.partition else 1
        limit = per_chip_hbm * chips
        if decision.partition is not None:
            _, hbm_used, hbm_total = self._partition_load(decision.partition, inv)
            if hbm_total > 0:
                limit = min(limit, hbm_total)
            limit = max(0, limit - hbm_used)
        decision.hbm_limit_bytes = limit
        decision.duty_pct = max(1, 100 // n)

    _UNFETCHED = object()  # sentinel: caller hasn't consulted the registry

    def _utilization_score(self, node_name: str, inv=_UNFETCHED) -> float:
        """100*(1-utilization) — the DCGM_FI_PROF_GR_ENGINE_ACTIVE fallback
        (gpu_plugins.go:508-527). Prefers the agent-published inventory
        (0..1), then the Prometheus duty-cycle series (0..100), then neutral
        0. Callers that already read the registry pass their result (possibly
        None) to avoid a second roundtrip."""
        if inv is TPUPlugin._UNFETCHED:
            inv = self._inventory(node_name)
        if inv is not None:
            return 100.0 * (1.0 - max(0.0, min(1.0, inv.utilization)))
        if self.prom is not None:
            try:
                duty_pct = self.prom.node_duty_cycle(node_name)
            except Exception:  # noqa: BLE001
                duty_pct = None
            if duty_pct is not None:
                return 100.0 - max(0.0, min(100.0, duty_pct))
        return 0.0
