"""Scheduler plugins — the out-of-tree logic the framework runs.

``tpu``  — chip-accounting Filter, SLO-slack/interference Score with the
           utilization fallback, Reserve-decided device assignment written in
           PostBind (the reference's single 930-line plugin, rebuilt
           side-effect-free: /root/reference/pkg/plugins/gpu_plugin/gpu_plugins.go).
``gang`` — Permit-based all-or-nothing admission with ICI-topology-aware
           node-set selection (no reference analogue; SURVEY.md §7.7).
``preemption`` — PostFilter evicting lower-priority pods for a starving
           high-priority pod (parity with the DefaultPreemption plugin the
           reference inherits from kube-scheduler v1.21).
"""
from .tpu import TPUPlugin
from .gang import GangPlugin
from .preemption import PreemptionPlugin

__all__ = ["TPUPlugin", "GangPlugin", "PreemptionPlugin"]
