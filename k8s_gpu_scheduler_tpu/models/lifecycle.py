"""Serve-entrypoint preemption lifecycle: SIGTERM → drain → persist →
resume.

PR 6 built the engine half (``ContinuousBatcher.drain()`` →
``ServingSnapshot`` → ``restore()``, token-identical); this module is
the ENTRYPOINT half the ROADMAP left open: GKE delivers SIGTERM ~30 s
before a spot reclaim — far more than the measured drain cost — so the
serve loop (models/llama.py) installs :class:`PreemptionGuard`, checks
it between waves, and on a request drains to the pod volume through
``utils/checkpoint.py``'s orbax machinery; the replacement pod's boot
calls :func:`resume_or_fresh` (the serving analogue of
``TrainCheckpointer.restore_or``) and every interrupted stream resumes
token-identically. The chaos harness drives the same helpers with a
``testing/faults.py`` ``Preempted`` injection instead of a real signal
— one code path, two triggers.

The same orbax step-lineage pattern also persists the fleet router's
request journal (:func:`persist_journal` / :func:`load_journal`,
fleet/journal.py): the snapshot is a replica's KV state for COOPERATIVE
recovery; the journal is the router's delivery record for recovery
after a crash that never drained.
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Callable, Optional, Tuple

from .snapshot import ServingSnapshot

log = logging.getLogger(__name__)

# Serving snapshots are singular (a drained engine has exactly one
# state), but the step id must still ADVANCE per drain: orbax's
# ``force=`` does not overwrite an existing step (StepAlreadyExists on
# the second preemption of a pod lineage), so each persist writes
# ``latest + 1`` and ``max_to_keep=1`` prunes the predecessor.
SNAPSHOT_STEP = 0


class PreemptionGuard:
    """SIGTERM-to-drain bridge for a serve loop. The handler only SETS
    an event — signal handlers run between bytecodes on the main
    thread, and draining from inside one would re-enter a step
    mid-flight; the serve loop polls ``requested`` at its wave boundary
    (seconds, versus the ~30 s GKE grace window) and runs the drain
    itself. ``request()`` is the programmatic trigger the chaos tests
    and the ``Preempted``-exception path use."""

    def __init__(self, signum: int = signal.SIGTERM) -> None:
        self._event = threading.Event()
        self._signum = signum
        self._prev = None
        self._installed = False

    def install(self) -> "PreemptionGuard":
        """Register the handler (main thread only — a CPython
        constraint on ``signal.signal``); keeps the previous handler
        for ``uninstall``."""
        self._prev = signal.signal(self._signum, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(self._signum, self._prev or signal.SIG_DFL)
            self._installed = False

    def _on_signal(self, signum, frame) -> None:
        self._event.set()

    def request(self) -> None:
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()


def _persist_pytree(tree, directory: str) -> None:
    """Write one singular pytree under ``directory`` via the orbax
    checkpointer, advancing the step past ``latest`` (orbax's ``force=``
    does not overwrite an existing step — StepAlreadyExists on a pod
    lineage's second preemption) with ``max_to_keep=1`` pruning the
    predecessor; blocks until the async save lands — the caller is
    usually about to exit. Shared by the serving snapshot and the fleet
    router's request journal (fleet/journal.py), which ride the same
    preempted-pod volume."""
    from ..utils.checkpoint import TrainCheckpointer

    with TrainCheckpointer(directory, max_to_keep=1) as ckpt:
        latest = ckpt.latest_step()
        step = SNAPSHOT_STEP if latest is None else latest + 1
        ckpt.save(step, tree, force=True)


def _load_pytree(directory: str):
    """Latest pytree under ``directory``, or None when there is none."""
    from ..utils.checkpoint import TrainCheckpointer

    with TrainCheckpointer(directory, max_to_keep=1) as ckpt:
        if ckpt.latest_step() is None:
            return None
        return ckpt.restore()


def persist_snapshot(snap: ServingSnapshot, directory: str) -> None:
    """Write a drained snapshot under ``directory`` via the orbax
    checkpointer (``to_pytree`` makes it StandardSave-compatible)."""
    _persist_pytree(snap.to_pytree(), directory)


def drain_to_checkpoint(engine, directory: str) -> ServingSnapshot:
    """The SIGTERM handler's action: drain the engine (admission stops,
    every referenced page gathers to host) and persist the snapshot.
    Returns it so the caller can log what was saved."""
    snap = engine.drain()
    persist_snapshot(snap, directory)
    log.info("drained %d in-flight request(s) to %s",
             snap.n_requests_in_flight, directory)
    return snap


def load_snapshot(directory: str) -> Optional[ServingSnapshot]:
    """Latest persisted serving snapshot under ``directory``, or None
    when there is none (first boot)."""
    tree = _load_pytree(directory)
    return None if tree is None else ServingSnapshot.from_pytree(tree)


def persist_journal(journal, directory: str) -> None:
    """Persist a fleet request journal (fleet/journal.py
    ``RequestJournal``) — same pattern, different truth: the snapshot
    carries a replica's KV state for COOPERATIVE recovery, the journal
    carries the router's delivery record for recovery after a crash
    that never drained. Keep the two in distinct directories (each is
    its own orbax step lineage)."""
    _persist_pytree(journal.to_pytree(), directory)


def load_journal(directory: str):
    """Latest persisted request journal under ``directory``, or None
    when there is none (fresh router)."""
    from ..fleet.journal import RequestJournal

    tree = _load_pytree(directory)
    return None if tree is None else RequestJournal.from_pytree(tree)


def resume_or_fresh(make_engine: Callable[[], object],
                    directory: Optional[str]) -> Tuple[object, int]:
    """``restore_or`` for serving: build a fresh engine and, when
    ``directory`` holds a snapshot, restore it — the replacement pod
    resumes every interrupted stream token-identically, with the
    preemption downtime charged to the latency records (snapshot clock
    re-basing). Returns ``(engine, resumed request count)``."""
    eng = make_engine()
    if not directory:
        return eng, 0
    snap = load_snapshot(directory)
    if snap is None:
        return eng, 0
    resumed = eng.restore(snap)
    log.info("resumed %d serving request(s) from %s", resumed, directory)
    return eng, resumed
