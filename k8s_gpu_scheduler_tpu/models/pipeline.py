"""Pipeline parallelism — GPipe-style microbatched stages over a 'pp' axis.

The sixth and final parallelism axis (DP/FSDP/TP/SP/EP live in
parallel/sharding.py rules; the reference has none of them — SURVEY.md §2
parallelism checklist). TPU-native shape:

- The layer stack [L, ...] is SHARDED over 'pp': stage s owns L/P
  contiguous layers — no weight gathering, ever (contrast FSDP, which
  all-gathers per layer).
- The schedule is one ``lax.scan`` over M + P - 1 ticks inside a
  ``shard_map``: at tick t, stage s runs microbatch t - s through its
  local layers; activations hop stage→stage via ``lax.ppermute`` (XLA
  lowers it onto the ICI ring). Bubble fraction is the usual
  (P-1)/(M+P-1) — pick microbatches >> stages.
- The backward needs NO bespoke code: ``ppermute`` is differentiable (its
  transpose is the reverse permutation), so ``jax.value_and_grad``
  through the shard_map runs the reverse schedule automatically — the
  scan's saved activations play the role of GPipe's stashed activations.
- Invalid ticks (the pipeline fill/drain bubble) compute garbage
  activations; they are masked OUT of the loss, so autodiff assigns them
  exactly zero gradient — compute wasted, correctness untouched.

Embedding and lm_head are replicated: stage 0 applies the embedding,
the last stage applies the head and accumulates token NLL; a ``psum``
makes the scalar loss replicated so out_specs=P() typechecks. loss parity
with the single-device path is asserted in tests/test_models.py.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import dense_attention
from ..ops.layers import rms_norm, rope_freqs
from .llama import LlamaConfig, attn_sublayer, mlp_sublayer, param_axes


def _block(cfg: LlamaConfig, x, blk, angles):
    """One decoder layer on [mb, T, D] — the SHARED sublayer helpers from
    llama.py (the pipeline scans over TIME ticks, not layers, but the
    per-layer math is one definition)."""
    x = attn_sublayer(
        cfg, x, blk, angles,
        lambda q, k, v: dense_attention(q, k, v, causal=True))
    x, _ = mlp_sublayer(cfg, x, blk)
    return x


def pp_loss_fn(params: Dict, batch: Dict, cfg: LlamaConfig, mesh: Mesh,
               microbatches: int) -> jax.Array:
    """Causal-LM loss computed through the pipeline. batch["tokens"] is
    [B, T] with B divisible by ``microbatches``; layers (cfg.n_layers)
    must divide by the pp axis size."""
    n_stages = mesh.shape["pp"]
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    if cfg.n_experts > 1:
        raise NotImplementedError(
            "pipeline parallelism does not compose with MoE configs yet "
            "(route expert dispatch per stage); use dense layers")
    if cfg.attn_impl != "dense":
        raise NotImplementedError(
            f"pipeline parallelism runs dense attention only (got "
            f"attn_impl={cfg.attn_impl!r}); flash/ring/ulysses per stage "
            f"is future work")
    M = microbatches
    B, T = batch["tokens"].shape
    assert B % M == 0, (B, M)
    mb = B // M
    angles = rope_freqs(cfg.head_dim, T, cfg.rope_theta)

    def stage_program(blocks, embed, lm_head, final_norm, tokens, targets):
        stage = jax.lax.axis_index("pp")
        last = n_stages - 1
        tok_mb = tokens.reshape(M, mb, T)
        tgt_mb = targets.reshape(M, mb, T)

        def run_local(x):
            def one(x, blk):
                return _block(cfg, x, blk, angles), None

            one_fn = jax.checkpoint(one) if cfg.remat else one
            x, _ = jax.lax.scan(one_fn, x, blocks)
            return x

        def tick(carry, t):
            act, loss_sum, n_sum = carry
            # Stage 0 injects microbatch t (clamped; invalid ticks masked
            # out of the loss below).
            inject = embed[tok_mb[jnp.clip(t, 0, M - 1)]].astype(cfg.dtype)
            x = jnp.where(stage == 0, inject, act)
            x = run_local(x)
            # Last stage: the activation leaving at tick t belongs to
            # microbatch t - (P-1); fold its NLL when that index is real.
            m_idx = jnp.clip(t - last, 0, M - 1)
            h = rms_norm(x, final_norm)
            logits = (h @ lm_head).astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, tgt_mb[m_idx][..., None], axis=-1)[..., 0]
            nll = (lse - tgt).sum()
            valid = (stage == last) & (t >= last) & (t - last < M)
            loss_sum = loss_sum + jnp.where(valid, nll, 0.0)
            n_sum = n_sum + jnp.where(valid, mb * T, 0)
            # Rotate activations one stage forward (ring; last→0 carries a
            # dead value that stage 0 overwrites with its next inject).
            nxt = jax.lax.ppermute(
                x, "pp", [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, loss_sum, n_sum), None

        act0 = jnp.zeros((mb, T, cfg.d_model), cfg.dtype)
        (_, loss_sum, n_sum), _ = jax.lax.scan(
            tick, (act0, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
            jnp.arange(M + n_stages - 1))
        # Only the last stage holds the sums — psum replicates the scalar.
        total = jax.lax.psum(loss_sum, "pp")
        count = jax.lax.psum(n_sum, "pp")
        return total / count.astype(jnp.float32)

    # Layer-stacked block leaves shard over pp; everything else replicates.
    blocks_spec = jax.tree.map(lambda _: P("pp"), params["blocks"])
    fn = jax.shard_map(
        stage_program,
        mesh=mesh,
        in_specs=(blocks_spec, P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(params["blocks"], params["embed"], params["lm_head"],
              params["final_norm"], batch["tokens"], batch["targets"])


def pp_param_shardings(cfg: LlamaConfig, mesh: Mesh) -> Dict:
    """NamedShardings for the pipeline layout: block leaves split their
    leading layer axis over pp, the rest replicate. Block keys come from
    param_axes — the one definition of the param tree — so a new block
    param can't silently desynchronize jit's in_shardings."""
    axes = param_axes(cfg)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()),
        {k: v for k, v in axes.items() if k != "blocks"},
        is_leaf=lambda x: isinstance(x, tuple),
    ) | {
        "blocks": jax.tree.map(
            lambda _: NamedSharding(mesh, P("pp")),
            axes["blocks"],
            is_leaf=lambda x: isinstance(x, tuple),
        )
    }


def make_pp_train_step(cfg: LlamaConfig, mesh: Mesh, optimizer,
                       microbatches: int):
    """Jitted pipeline train step: (params, opt_state, batch) →
    (params, opt_state, loss). Layer shards stay resident on their stage
    across steps (in_shardings pin them), so the optimizer update for a
    stage's layers also runs on that stage."""
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(pp_loss_fn)(
            params, batch, cfg, mesh, microbatches)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    pshard = pp_param_shardings(cfg, mesh)
    return jax.jit(
        step,
        in_shardings=(pshard, None, None),
        donate_argnums=(0, 1),
    )
