"""Pipeline parallelism — GPipe-style microbatched stages over a 'pp' axis.

The sixth and final parallelism axis (DP/FSDP/TP/SP/EP live in
parallel/sharding.py rules; the reference has none of them — SURVEY.md §2
parallelism checklist). TPU-native shape:

- The layer stack [L, ...] is SHARDED over 'pp': stage s owns L/P
  contiguous layers — no weight gathering, ever (contrast FSDP, which
  all-gathers per layer).
- The schedule is one ``lax.scan`` over M + P - 1 ticks inside a
  ``shard_map``: at tick t, stage s runs microbatch t - s through its
  local layers; activations hop stage→stage via ``lax.ppermute`` (XLA
  lowers it onto the ICI ring). Bubble fraction is the usual
  (P-1)/(M+P-1) — pick microbatches >> stages.
- The backward needs NO bespoke code: ``ppermute`` is differentiable (its
  transpose is the reverse permutation), so ``jax.value_and_grad``
  through the shard_map runs the reverse schedule automatically — the
  scan's saved activations play the role of GPipe's stashed activations.
- Invalid ticks (the pipeline fill/drain bubble) compute garbage
  activations; they are masked OUT of the loss, so autodiff assigns them
  exactly zero gradient — compute wasted, correctness untouched.

Embedding and lm_head are replicated: stage 0 applies the embedding,
the last stage applies the head and accumulates token NLL; a ``psum``
makes the scalar loss replicated so out_specs=P() typechecks. loss parity
with the single-device path is asserted in tests/test_models.py.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import dense_attention
from ..ops.layers import rms_norm, rope_freqs
from ..parallel.sharding import shard_map
from .llama import LlamaConfig, attn_sublayer, mlp_sublayer, param_axes


def _block(cfg: LlamaConfig, x, blk, angles):
    """One decoder layer on [mb, T, D] — the SHARED sublayer helpers from
    llama.py (the pipeline scans over TIME ticks, not layers, but the
    per-layer math is one definition)."""
    x = attn_sublayer(
        cfg, x, blk, angles,
        lambda q, k, v: dense_attention(q, k, v, causal=True))
    x, _ = mlp_sublayer(cfg, x, blk)
    return x


def pp_loss_fn(params: Dict, batch: Dict, cfg: LlamaConfig, mesh: Mesh,
               microbatches: int) -> jax.Array:
    """Causal-LM loss computed through the pipeline. batch["tokens"] is
    [B, T] with B divisible by ``microbatches``; layers (cfg.n_layers)
    must divide by the pp axis size."""
    n_stages = mesh.shape["pp"]
    _check_pp_config(cfg, n_stages)
    M = microbatches
    B, T = batch["tokens"].shape
    assert B % M == 0, (B, M)
    mb = B // M
    angles = rope_freqs(cfg.head_dim, T, cfg.rope_theta)

    def stage_program(blocks, embed, lm_head, final_norm, tokens, targets):
        stage = jax.lax.axis_index("pp")
        last = n_stages - 1
        tok_mb = tokens.reshape(M, mb, T)
        tgt_mb = targets.reshape(M, mb, T)

        def run_local(x):
            def one(x, blk):
                return _block(cfg, x, blk, angles), None

            one_fn = jax.checkpoint(one) if cfg.remat else one
            x, _ = jax.lax.scan(one_fn, x, blocks)
            return x

        def tick(carry, t):
            act, loss_sum, n_sum = carry
            # Stage 0 injects microbatch t (clamped; invalid ticks masked
            # out of the loss below).
            inject = embed[tok_mb[jnp.clip(t, 0, M - 1)]].astype(cfg.dtype)
            x = jnp.where(stage == 0, inject, act)
            x = run_local(x)
            # Last stage: the activation leaving at tick t belongs to
            # microbatch t - (P-1); fold its NLL when that index is real.
            m_idx = jnp.clip(t - last, 0, M - 1)
            h = rms_norm(x, final_norm)
            logits = (h @ lm_head).astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, tgt_mb[m_idx][..., None], axis=-1)[..., 0]
            nll = (lse - tgt).sum()
            valid = (stage == last) & (t >= last) & (t - last < M)
            loss_sum = loss_sum + jnp.where(valid, nll, 0.0)
            n_sum = n_sum + jnp.where(valid, mb * T, 0)
            # loss_sum/n_sum stay shape (1,), never rank-0: a SCALAR scan
            # carry becomes a rank-0 residual of the autodiff'd shard_map,
            # which 0.4.x shard_map cannot assign an out_spec (_SpecError
            # "add at least one (singleton) axis") — the singleton axis is
            # squeezed after the psum below.
            # Rotate activations one stage forward (ring; last→0 carries a
            # dead value that stage 0 overwrites with its next inject).
            nxt = jax.lax.ppermute(
                x, "pp", [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, loss_sum, n_sum), None

        act0 = jnp.zeros((mb, T, cfg.d_model), cfg.dtype)
        (_, loss_sum, n_sum), _ = jax.lax.scan(
            tick,
            (act0, jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32)),
            jnp.arange(M + n_stages - 1))
        # Only the last stage holds the sums — psum replicates the scalar.
        total = jax.lax.psum(loss_sum, "pp")
        count = jax.lax.psum(n_sum, "pp")
        return (total / count.astype(jnp.float32))[0]

    # Layer-stacked block leaves shard over pp; everything else replicates.
    blocks_spec = jax.tree.map(lambda _: P("pp"), params["blocks"])
    fn = shard_map(
        stage_program,
        mesh=mesh,
        in_specs=(blocks_spec, P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(params["blocks"], params["embed"], params["lm_head"],
              params["final_norm"], batch["tokens"], batch["targets"])


def _check_pp_config(cfg: LlamaConfig, n_stages: int) -> None:
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    if cfg.n_experts > 1:
        raise NotImplementedError(
            "pipeline parallelism does not compose with MoE configs yet "
            "(route expert dispatch per stage); use dense layers")
    if cfg.attn_impl != "dense":
        raise NotImplementedError(
            f"pipeline parallelism runs dense attention only (got "
            f"attn_impl={cfg.attn_impl!r}); flash/ring/ulysses per stage "
            f"is future work")


def pp_1f1b_loss_and_grads(params: Dict, batch: Dict, cfg: LlamaConfig,
                           mesh: Mesh, microbatches: int):
    """(loss, grads) through a synchronous 1F1B schedule — the memory-side
    successor to GPipe (pp_loss_fn):

    - **Why**: autodiff-GPipe stashes every microbatch's scan-saved
      activations until the reverse pass — O(M) live stashes per stage.
      1F1B drains each microbatch's backward as soon as it can, so stage s
      holds at most 2(P-1-s)+1 in-flight INPUT activations — O(P),
      independent of M. Same total tick count (M + 2(P-1) combined-F/B
      ticks vs GPipe's (M+P-1) forward + (M+P-1) backward); the win is
      that the O(P) stash lets you raise M at fixed HBM, and M is what
      divides the bubble down.
    - **Schedule** (synchronous formulation): at tick t, stage s runs the
      FORWARD of microbatch f = t - s and the BACKWARD of microbatch
      b = t - 2(P-1) + s, both masked to [0, M). The backward of b reaches
      stage s exactly one tick after stage s+1 emitted its cotangent
      (b + 2(P-1) - (s+1) = t - 1), so activations ppermute forward and
      cotangents ppermute backward every tick. On the last stage b == f:
      loss cotangent is produced and consumed in the same tick, so the
      last stage never stashes at all.
    - **Backward is manual VJP + recompute**: no jax.value_and_grad over
      the schedule — each backward tick re-runs the stage's forward inside
      ``jax.vjp`` from the SAVED INPUT (rematerialization, same policy as
      cfg.remat on the other paths). Invalid ticks contribute exactly
      zero: cotangents are zeroed before the VJP and VJPs are linear in
      the cotangent, so no separate masking of the parameter grads is
      needed. Invalid forwards write their garbage into a dedicated
      scratch stash slot (index W) so they can never clobber a live one.

    Loss parity with GPipe/single-device is asserted in
    tests/test_models.py."""
    n_stages = mesh.shape["pp"]
    _check_pp_config(cfg, n_stages)
    if n_stages < 2:
        raise ValueError("1F1B needs >= 2 stages; use the plain train step")
    M = microbatches
    B, T = batch["tokens"].shape
    assert B % M == 0, (B, M)
    mb = B // M
    P_ = n_stages
    W = 2 * (P_ - 1) + 1                    # max in-flight inputs (stage 0)
    angles = rope_freqs(cfg.head_dim, T, cfg.rope_theta)
    # B/T come from .shape — static Python ints, not tracers.
    total_tokens = float(B * T)  # graftcheck: ignore[tracer-cast]

    def stage_program(blocks, embed, lm_head, final_norm, tokens, targets):
        stage = jax.lax.axis_index("pp")
        last = P_ - 1
        tok_mb = tokens.reshape(M, mb, T)
        tgt_mb = targets.reshape(M, mb, T)

        def run_local(x, blk):
            def one(x, layer):
                return _block(cfg, x, layer, angles), None

            one_fn = jax.checkpoint(one) if cfg.remat else one
            x, _ = jax.lax.scan(one_fn, x, blk)
            return x

        def head_nll(y, lmh, fn, tgt):
            h = rms_norm(y, fn)
            logits = (h @ lmh).astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            hit = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
            return (lse - hit).sum()

        zero_grads = (
            jax.tree.map(jnp.zeros_like, blocks),
            jnp.zeros_like(embed),
            jnp.zeros_like(lm_head),
            jnp.zeros_like(final_norm),
        )
        act0 = jnp.zeros((mb, T, cfg.d_model), cfg.dtype)
        stash0 = jnp.zeros((W + 1, mb, T, cfg.d_model), cfg.dtype)

        def tick(carry, t):
            act_in, cot_in, stash, grads, loss_sum = carry
            gblocks, gembed, glmh, gfn = grads
            f = t - stage                          # fwd microbatch index
            b = t - 2 * (P_ - 1) + stage           # bwd microbatch index
            valid_f = (f >= 0) & (f < M)
            valid_b = (b >= 0) & (b < M)
            fc = jnp.clip(f, 0, M - 1)
            bc = jnp.clip(b, 0, M - 1)

            # ---- forward of microbatch f -------------------------------
            inject = embed[tok_mb[fc]].astype(cfg.dtype)
            x_in = jnp.where(stage == 0, inject, act_in)
            slot_f = jnp.where(valid_f, fc % W, W)   # scratch slot if invalid
            stash = jax.lax.dynamic_update_slice_in_dim(
                stash, x_in[None], slot_f, axis=0)
            y = run_local(x_in, blocks)

            # ---- last stage: loss + its cotangent (b == f here) --------
            cot_scale = jnp.where(valid_f & (stage == last),
                                  1.0 / total_tokens, 0.0)
            nll, head_vjp = jax.vjp(
                lambda yy, lmh, fn: head_nll(yy, lmh, fn, tgt_mb[fc]),
                y, lm_head, final_norm)
            dy_head, dlmh, dfn = head_vjp(cot_scale.astype(jnp.float32))
            loss_sum = loss_sum + jnp.where(
                valid_f & (stage == last), nll, 0.0)
            glmh = glmh + dlmh.astype(glmh.dtype)
            gfn = gfn + dfn.astype(gfn.dtype)

            # ---- backward of microbatch b ------------------------------
            x_saved = jnp.where(
                stage == last, x_in,
                jax.lax.dynamic_index_in_dim(stash, bc % W, axis=0,
                                             keepdims=False))
            dy = jnp.where(stage == last, dy_head.astype(cfg.dtype),
                           cot_in * valid_b.astype(cot_in.dtype))
            _, local_vjp = jax.vjp(run_local, x_saved, blocks)
            dx, dblocks = local_vjp(dy)
            gblocks = jax.tree.map(
                lambda g, d: g + d.astype(g.dtype), gblocks, dblocks)
            # Stage 0 folds dx into the embedding gradient — mask the SMALL
            # dx by the scalar and scatter straight into the accumulator
            # (scatter is linear; a zeros_like temporary would cost three
            # full-vocab passes per tick).
            emb_mask = jnp.where(valid_b & (stage == 0), 1.0, 0.0)
            gembed = gembed.at[tok_mb[bc]].add(
                (dx * emb_mask.astype(dx.dtype)).astype(gembed.dtype))

            # ---- ring movement -----------------------------------------
            act_out = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % P_) for i in range(P_)])
            cot_out = jax.lax.ppermute(
                dx, "pp", [(i, (i - 1) % P_) for i in range(P_)])
            return (act_out, cot_out, stash,
                    (gblocks, gembed, glmh, gfn), loss_sum), None

        (_, _, _, grads, loss_sum), _ = jax.lax.scan(
            tick,
            (act0, act0, stash0, zero_grads, jnp.zeros((), jnp.float32)),
            jnp.arange(M + 2 * (P_ - 1)))
        gblocks, gembed, glmh, gfn = grads
        loss = jax.lax.psum(loss_sum, "pp") / total_tokens
        # Replicated-param grads: each stage holds only its own (zero
        # elsewhere) contribution — psum sums them into the replicated
        # gradient.
        gembed = jax.lax.psum(gembed, "pp")
        glmh = jax.lax.psum(glmh, "pp")
        gfn = jax.lax.psum(gfn, "pp")
        return loss, gblocks, gembed, glmh, gfn

    blocks_spec = jax.tree.map(lambda _: P("pp"), params["blocks"])
    fn = shard_map(
        stage_program,
        mesh=mesh,
        in_specs=(blocks_spec, P(), P(), P(), P(), P()),
        out_specs=(P(), blocks_spec, P(), P(), P()),
        check_vma=False,
    )
    loss, gblocks, gembed, glmh, gfn = fn(
        params["blocks"], params["embed"], params["lm_head"],
        params["final_norm"], batch["tokens"], batch["targets"])
    grads = {"blocks": gblocks, "embed": gembed, "lm_head": glmh,
             "final_norm": gfn}
    return loss, grads


def pp_param_shardings(cfg: LlamaConfig, mesh: Mesh) -> Dict:
    """NamedShardings for the pipeline layout: block leaves split their
    leading layer axis over pp, the rest replicate. Block keys come from
    param_axes — the one definition of the param tree — so a new block
    param can't silently desynchronize jit's in_shardings."""
    axes = param_axes(cfg)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()),
        {k: v for k, v in axes.items() if k != "blocks"},
        is_leaf=lambda x: isinstance(x, tuple),
    ) | {
        "blocks": jax.tree.map(
            lambda _: NamedSharding(mesh, P("pp")),
            axes["blocks"],
            is_leaf=lambda x: isinstance(x, tuple),
        )
    }


def make_pp_train_step(cfg: LlamaConfig, mesh: Mesh, optimizer,
                       microbatches: int, schedule: str = "gpipe"):
    """Jitted pipeline train step: (params, opt_state, batch) →
    (params, opt_state, loss). Layer shards stay resident on their stage
    across steps (in_shardings pin them), so the optimizer update for a
    stage's layers also runs on that stage.

    ``schedule``: "gpipe" (autodiff through the forward schedule, O(M)
    activation stash) or "1f1b" (manual-VJP synchronous 1F1B, O(P) stash —
    pp_1f1b_loss_and_grads). Loss/grad equivalence between the two is
    asserted in tests and the pp dryrun leg."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")

    def step(params, opt_state, batch):
        if schedule == "1f1b":
            loss, grads = pp_1f1b_loss_and_grads(
                params, batch, cfg, mesh, microbatches)
        else:
            loss, grads = jax.value_and_grad(pp_loss_fn)(
                params, batch, cfg, mesh, microbatches)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(
            lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    pshard = pp_param_shardings(cfg, mesh)
    return jax.jit(
        step,
        in_shardings=(pshard, None, None),
        donate_argnums=(0, 1),
    )
