"""Serving-engine snapshots — preemption-safe drain/restore state.

TPU slices on GKE are preempted routinely (spot reclaim, maintenance
events); the scheduler exists to keep inference SLOs under exactly that
churn, yet until this module a preempted serving engine lost every
in-flight request. The paged ``ContinuousBatcher`` makes recovery cheap
because its entire state machine is already explicit and host-legible:
K/V live in fixed-size pool pages addressed by per-slot block tables,
``lens`` is simultaneously each slot's rope position / write address /
attention bound, and the radix prefix cache is just pages plus a
token-keyed tree. A :class:`ServingSnapshot` is that state machine
serialized:

- the KV **bytes of every referenced page** (live slot pages + prefix-
  cache pages; free pages are garbage by contract and are not shipped),
  gathered to host as ``[L, R, ps, Hkv, hd]`` arrays plus the int8 scale
  planes when the cache is quantized;
- the **page-id space**: which old pool ids those R rows were — restore
  re-lays them out through the fresh engine's allocator, so physical ids
  need not (and usually do not) match, and the restore pool may have a
  DIFFERENT ``n_pages`` than the drained one;
- the **per-slot machine**: block-table rows, ``lens``, ``last`` tokens,
  slot↔request binding, owned/shared page lists, prompt token mirrors;
- the **host bookkeeping**: remaining budgets, emitted streams, the
  waiting queue, eos scan offsets, request-id counter, arrival/TTFT
  clocks (re-based at restore so latency records survive a process
  boundary);
- the **prefix tree** as root-to-leaf token paths with their page ids,
  in LRU order, so reuse state survives too;
- the **flight recorder ring** (obs/flight.py): the per-step records of
  the drained engine's recent behavior, re-seeded into the restored
  engine so a post-preemption investigation can read the black box.

What is deliberately NOT preserved: speculative proposals (recomputed
from the token mirrors — the proposer indexes are pure functions of
prompt + emitted stream), deferred readbacks (drain flushes them), and
cumulative gauge counters (a restored engine starts fresh counters; the
``requests_resumed_total`` gauge records the handoff). Adaptive-gamma
state IS preserved (``spec_ema``/``spec_eff``/``spec_reserve`` per
request plus the fleet EMA): the accept-rate history is cheap to carry
and the pinned per-request page reservation is load-bearing — the
restored engine's effective verify windows must keep honoring the page
math the source engine admitted under.

Snapshots are MESH-AGNOSTIC by construction: drain gathers the full
kv-head dim of every shipped page to host, so the payload carries no
trace of the source engine's tp width and the fingerprint deliberately
omits it — restore/absorb re-shard the pages onto the TARGET's mesh
(serving._reshard_pool), which is what lets the fleet shed/failover
across heterogeneous replicas (tp=2 → tp=1 → tp=4 round trips are
token-identical, tests/test_sharded_serving.py). Model WEIGHTS are
likewise never part of a snapshot — whoever constructs the target
engine rebuilds them from config — so how a replica slices them
(serving ``weight_sharding``/``tp_combine``, Megatron column/row specs)
is invisible to the payload and to the fingerprint: a psum tp=2 drain
restores onto an all_gather tp=4 engine, or a legacy replicated one,
with no format work (pinned by the cross-combine round-trip test).

The snapshot runs through ``utils/checkpoint.py``'s orbax machinery via
``to_pytree``/``from_pytree``: every field becomes a numpy array (the
host bookkeeping rides as one JSON document encoded to uint8), so
``TrainCheckpointer.save(step, snap.to_pytree())`` just works and the
restore side needs no custom readers.

WIRE-FORMAT CONTRACT (graftcheck pass 11, ``wirecompat``): the pytree
leaves and the meta-doc keys ARE a wire format — shed snapshots ship
between replicas, and the cross-process fleet item makes them literal
network bytes. Their schema is pinned in
``tests/data/graftcheck/schemas/serving_snapshot.json`` at
``SNAPSHOT_VERSION`` = 1. Evolve by ADDING a field whose ``from_pytree``
default preserves old artifacts (the ``payload_shape`` /
``flight`` / ``partial`` / tier-sidecar precedents above), then
regenerate the golden (``python -m k8s_gpu_scheduler_tpu.analysis
--update-schemas``) in the same change; removing or retyping a field
requires a ``SNAPSHOT_VERSION`` bump with rationale. A pre-tiering
drain is committed at ``tests/data/wire/snapshot_pre_tiering.npz`` and
must keep loading (tests/test_wire_compat.py).
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

SNAPSHOT_VERSION = 1


class SnapshotError(ValueError):
    """Snapshot/engine mismatch: restoring this snapshot into that
    engine cannot preserve the token streams (or cannot fit)."""


@dataclass
class ServingSnapshot:
    """One drained paged serving engine, host-resident. Produced by
    ``ContinuousBatcher.drain()``, consumed by ``.restore()``; see the
    module docstring for what each field carries."""

    fingerprint: Dict[str, Any]            # engine-compat contract
    page_ids: List[int]                    # old pool ids of the R rows
    k_pages: np.ndarray                    # [L, R, ps, Hkv, hd]
    v_pages: np.ndarray
    k_scales: Optional[np.ndarray]         # [L, R, ps, Hkv, 1] (int8 mode)
    v_scales: Optional[np.ndarray]
    table: np.ndarray                      # [n_slots, n_blocks] old ids
    lens: np.ndarray                       # [n_slots] int32
    last: np.ndarray                       # [n_slots] int32
    slot_req: Dict[int, int]               # slot -> req id
    slot_pages: Dict[int, List[int]]       # slot -> owned old page ids
    slot_shared: Dict[int, List[int]]      # slot -> mounted shared ids
    slot_prompt: Dict[int, List[int]]      # slot -> prompt tokens
    budgets: Dict[int, int]                # req id -> tokens remaining
    out: Dict[int, List[int]]              # req id -> emitted tokens
    queue: List[Tuple[int, List[int]]]     # waiting (req id, prompt)
    next_id: int
    eos_scanned: Dict[int, int]
    tree_paths: List[Tuple[List[int], List[int]]]  # (tokens, pages), LRU order
    arrival: Dict[int, float] = field(default_factory=dict)
    first_tok: Dict[int, float] = field(default_factory=dict)
    drained_mono: float = 0.0              # Clock.monotonic() at drain
    drained_wall: float = 0.0              # Clock.wall() at drain
    skipped_tokens: int = 0
    # Flight-recorder ring (obs/flight.py to_payload(), JSON-safe per-step
    # records): the drained engine's black box, re-seeded into the
    # restored engine's recorder so post-preemption debugging can see
    # pre-preemption behavior. Default [] keeps older snapshots loading.
    flight: List[Dict[str, Any]] = field(default_factory=list)
    # PARTIAL snapshot (load shedding): a filter over ``slot_req`` —
    # only the shed slots' pages/bookkeeping, no queue, no prefix tree,
    # and the SOURCE engine keeps running. Consumed by
    # ``ContinuousBatcher.absorb()`` (which merges into a BUSY engine);
    # ``restore()`` rejects it — a partial snapshot is not a whole
    # engine. Default False keeps older snapshots loading.
    partial: bool = False
    # KV tiering (serving ``kv_tiering=True``): the host-DRAM tier's
    # committed page payloads, COLDEST FIRST (disk spills coldest of
    # all), so a restore into a smaller ``dram_pages`` budget keeps the
    # hottest tail. ``tree_paths`` reference a demoted chunk as
    # ``-(key + 1)`` — restore remaps the keys and truncates any path
    # whose entry was dropped. All default-empty: pre-tiering snapshots
    # load unchanged, untiered engines never populate them, and an
    # untiered RESTORE target simply drops the payloads.
    tier_keys: List[int] = field(default_factory=list)
    tier_k: Optional[np.ndarray] = None    # [L, R2, ps, Hkv, hd]
    tier_v: Optional[np.ndarray] = None
    tier_ks: Optional[np.ndarray] = None   # [L, R2, ps, Hkv, 1] (int8)
    tier_vs: Optional[np.ndarray] = None
    # Adaptive speculative gamma (serving ``spec_adaptive=True``): per-
    # request accept-rate EMAs, last effective windows, and the PINNED
    # overshoot-row reservations admission sized each request's pages
    # for, plus the fleet-level EMA that seeds new admissions. All
    # default-empty/1.0: pre-adaptive snapshots load unchanged and
    # non-adaptive engines ship empty dicts (the full gamma is then the
    # implicit reservation, exactly what their admission reserved).
    spec_ema: Dict[int, float] = field(default_factory=dict)
    spec_eff: Dict[int, int] = field(default_factory=dict)
    spec_reserve: Dict[int, int] = field(default_factory=dict)
    spec_fleet_ema: float = 1.0

    # -- derived -----------------------------------------------------------
    @property
    def n_requests_in_flight(self) -> int:
        """Interrupted requests this snapshot can resume: slots mid-decode
        plus the still-waiting queue."""
        return len(self.slot_req) + len(self.queue)

    def nbytes(self) -> int:
        """Approximate serialized size — the number the bench leg reports
        (page payload dominates; the JSON sidecar is KiBs)."""
        n = self.k_pages.nbytes + self.v_pages.nbytes
        if self.k_scales is not None:
            n += self.k_scales.nbytes + self.v_scales.nbytes
        for arr in (self.tier_k, self.tier_v, self.tier_ks,
                    self.tier_vs):
            if arr is not None:
                n += arr.nbytes
        n += self.table.nbytes + self.lens.nbytes + self.last.nbytes
        n += len(json.dumps(self._meta_doc()).encode())
        return n

    def validate(self) -> None:
        """Internal consistency: every page id referenced by a slot row or
        tree path must be in ``page_ids`` (its bytes shipped), page ids
        unique, array row count == len(page_ids)."""
        ids = list(self.page_ids)
        if len(ids) != len(set(ids)):
            raise SnapshotError(f"duplicate page ids in snapshot: {ids}")
        have = set(ids)
        if self.k_pages.shape[1] != len(ids) or \
                self.v_pages.shape[1] != len(ids):
            raise SnapshotError(
                f"page payload rows {self.k_pages.shape[1]} != "
                f"{len(ids)} page ids")
        referenced: set = set()
        for slot, pages in self.slot_pages.items():
            referenced.update(pages)
        for slot, pages in self.slot_shared.items():
            referenced.update(pages)
        demoted_ref: set = set()
        for _, pages in self.tree_paths:
            for p in pages:
                p = int(p)
                if p < 0:          # demoted chunk: -(tier key + 1)
                    demoted_ref.add(-p - 1)
                else:
                    referenced.add(p)
        missing = referenced - have
        if missing:
            raise SnapshotError(
                f"referenced pages missing payloads: {sorted(missing)}")
        tkeys = [int(k) for k in self.tier_keys]
        if len(tkeys) != len(set(tkeys)):
            raise SnapshotError(f"duplicate tier keys: {tkeys}")
        if self.partial and tkeys:
            raise SnapshotError(
                "partial snapshot must not carry a DRAM tier")
        missing_tier = demoted_ref - set(tkeys)
        if missing_tier:
            raise SnapshotError(
                f"tree paths reference demoted pages whose tier "
                f"payloads did not ship: keys {sorted(missing_tier)}")
        if tkeys:
            if self.tier_k is None or self.tier_v is None:
                raise SnapshotError(
                    f"{len(tkeys)} tier keys but no tier payload")
            if self.tier_k.shape[1] != len(tkeys) or \
                    self.tier_v.shape[1] != len(tkeys):
                raise SnapshotError(
                    f"tier payload rows {self.tier_k.shape[1]} != "
                    f"{len(tkeys)} tier keys")
        for rid in self.slot_req.values():
            if rid not in self.budgets:
                raise SnapshotError(f"in-flight request {rid} has no budget")

    # -- pytree codec ------------------------------------------------------
    def _meta_doc(self) -> Dict[str, Any]:
        """The host bookkeeping as one JSON-safe document. Dicts with int
        keys ride as pair lists (JSON would silently stringify the
        keys)."""
        return {
            "version": SNAPSHOT_VERSION,
            "fingerprint": self.fingerprint,
            "page_ids": [int(p) for p in self.page_ids],
            "slot_req": [[int(s), int(r)] for s, r in self.slot_req.items()],
            "slot_pages": [[int(s), [int(p) for p in pg]]
                           for s, pg in self.slot_pages.items()],
            "slot_shared": [[int(s), [int(p) for p in pg]]
                            for s, pg in self.slot_shared.items()],
            "slot_prompt": [[int(s), [int(t) for t in pr]]
                            for s, pr in self.slot_prompt.items()],
            "budgets": [[int(r), int(b)] for r, b in self.budgets.items()],
            "out": [[int(r), [int(t) for t in ts]]
                    for r, ts in self.out.items()],
            "queue": [[int(r), [int(t) for t in pr]]
                      for r, pr in self.queue],
            "next_id": int(self.next_id),
            "eos_scanned": [[int(r), int(n)]
                            for r, n in self.eos_scanned.items()],
            "tree_paths": [[[int(t) for t in toks], [int(p) for p in pgs]]
                           for toks, pgs in self.tree_paths],
            "arrival": [[int(r), float(t)] for r, t in self.arrival.items()],
            "first_tok": [[int(r), float(t)]
                          for r, t in self.first_tok.items()],
            "drained_mono": float(self.drained_mono),
            "drained_wall": float(self.drained_wall),
            "skipped_tokens": int(self.skipped_tokens),
            "flight": list(self.flight),
            "partial": bool(self.partial),
            # Payload geometry, so a ZERO-page snapshot (drain with all
            # slots finished — only the queue ships) can omit its empty
            # arrays from the pytree: orbax/tensorstore refuses to write
            # zero-size params, and from_pytree rebuilds them from here.
            "payload_shape": [int(x) for x in self.k_pages.shape],
            "payload_dtype": str(np.asarray(self.k_pages).dtype),
            "has_scales": self.k_scales is not None,
            # DRAM-tier sidecar (absent-tolerant on load, PR 9
            # convention): the payload arrays ride the pytree like the
            # page payload; empty tiers ship nothing.
            "tier_keys": [int(k) for k in self.tier_keys],
            # Adaptive-gamma sidecar (absent-tolerant on load, same
            # convention): int-keyed dicts as pair lists.
            "spec_ema": [[int(r), float(v)]
                         for r, v in self.spec_ema.items()],
            "spec_eff": [[int(r), int(v)]
                         for r, v in self.spec_eff.items()],
            "spec_reserve": [[int(r), int(v)]
                             for r, v in self.spec_reserve.items()],
            "spec_fleet_ema": float(self.spec_fleet_ema),
        }

    def to_pytree(self) -> Dict[str, np.ndarray]:
        """A pure-numpy pytree (orbax StandardSave-compatible): arrays as
        themselves, host bookkeeping as JSON bytes in a uint8 vector."""
        meta = np.frombuffer(
            json.dumps(self._meta_doc()).encode("utf-8"), dtype=np.uint8
        ).copy()
        tree: Dict[str, np.ndarray] = {
            "meta_json": meta,
            "table": np.asarray(self.table),
            "lens": np.asarray(self.lens),
            "last": np.asarray(self.last),
        }
        # Zero-size payloads stay out of the pytree (orbax cannot write
        # them); the meta doc's payload_shape/dtype rebuild them.
        if np.asarray(self.k_pages).size:
            tree["k_pages"] = np.asarray(self.k_pages)
            tree["v_pages"] = np.asarray(self.v_pages)
        if self.k_scales is not None and np.asarray(self.k_scales).size:
            tree["k_scales"] = np.asarray(self.k_scales)
            tree["v_scales"] = np.asarray(self.v_scales)
        if self.tier_k is not None and np.asarray(self.tier_k).size:
            tree["tier_k"] = np.asarray(self.tier_k)
            tree["tier_v"] = np.asarray(self.tier_v)
        if self.tier_ks is not None and np.asarray(self.tier_ks).size:
            tree["tier_ks"] = np.asarray(self.tier_ks)
            tree["tier_vs"] = np.asarray(self.tier_vs)
        return tree

    @classmethod
    def from_pytree(cls, tree: Dict[str, np.ndarray]) -> "ServingSnapshot":
        meta_arr = np.asarray(tree["meta_json"], dtype=np.uint8)
        doc = json.loads(bytes(meta_arr.tobytes()).decode("utf-8"))
        if doc.get("version") != SNAPSHOT_VERSION:
            raise SnapshotError(
                f"snapshot version {doc.get('version')} != "
                f"{SNAPSHOT_VERSION}")
        pairs = lambda key: {k: v for k, v in doc[key]}  # noqa: E731
        shape = tuple(doc.get("payload_shape", ()))
        dtype = np.dtype(doc.get("payload_dtype", "float32"))
        if "k_pages" in tree:
            k_pages = np.asarray(tree["k_pages"])
            v_pages = np.asarray(tree["v_pages"])
        else:                    # zero-page snapshot: payload omitted
            k_pages = np.zeros(shape, dtype)
            v_pages = np.zeros(shape, dtype)
        if "k_scales" in tree:
            k_scales = np.asarray(tree["k_scales"])
            v_scales = np.asarray(tree["v_scales"])
        elif doc.get("has_scales", False):
            k_scales = np.zeros(shape[:-1] + (1,), np.float32)
            v_scales = np.zeros(shape[:-1] + (1,), np.float32)
        else:
            k_scales = v_scales = None
        snap = cls(
            fingerprint=doc["fingerprint"],
            page_ids=list(doc["page_ids"]),
            k_pages=k_pages,
            v_pages=v_pages,
            k_scales=k_scales,
            v_scales=v_scales,
            table=np.asarray(tree["table"]),
            lens=np.asarray(tree["lens"]),
            last=np.asarray(tree["last"]),
            slot_req=pairs("slot_req"),
            slot_pages=pairs("slot_pages"),
            slot_shared=pairs("slot_shared"),
            slot_prompt=pairs("slot_prompt"),
            budgets=pairs("budgets"),
            out=pairs("out"),
            queue=[(r, list(p)) for r, p in doc["queue"]],
            next_id=doc["next_id"],
            eos_scanned=pairs("eos_scanned"),
            tree_paths=[(list(t), list(p)) for t, p in doc["tree_paths"]],
            arrival=pairs("arrival"),
            first_tok=pairs("first_tok"),
            drained_mono=doc["drained_mono"],
            drained_wall=doc["drained_wall"],
            skipped_tokens=doc["skipped_tokens"],
            flight=list(doc.get("flight", [])),
            partial=bool(doc.get("partial", False)),
            tier_keys=list(doc.get("tier_keys", [])),
            tier_k=(np.asarray(tree["tier_k"])
                    if "tier_k" in tree else None),
            tier_v=(np.asarray(tree["tier_v"])
                    if "tier_v" in tree else None),
            tier_ks=(np.asarray(tree["tier_ks"])
                     if "tier_ks" in tree else None),
            tier_vs=(np.asarray(tree["tier_vs"])
                     if "tier_vs" in tree else None),
            spec_ema={int(r): float(v)
                      for r, v in doc.get("spec_ema", [])},
            spec_eff={int(r): int(v)
                      for r, v in doc.get("spec_eff", [])},
            spec_reserve={int(r): int(v)
                          for r, v in doc.get("spec_reserve", [])},
            spec_fleet_ema=float(doc.get("spec_fleet_ema", 1.0)),
        )
        snap.validate()
        return snap

    # -- clock re-basing ---------------------------------------------------
    def rebased_clock(self, rid_ts: Dict[int, float],
                      now_mono: float, now_wall: float) -> Dict[int, float]:
        """Translate drained ``time.monotonic`` timestamps into the
        restoring process's monotonic frame, charging the real downtime
        (wall-clock drain→restore) to every in-flight request:
        ``now - new_ts == (drained_mono - old_ts) + downtime``. Across a
        process boundary the raw values would be meaningless (monotonic
        clocks share no epoch); rebased, TTFT/latency records stay
        honest — including the preemption gap itself."""
        downtime = max(0.0, now_wall - self.drained_wall)
        return {
            rid: now_mono - downtime - (self.drained_mono - ts)
            for rid, ts in rid_ts.items()
        }


def check_fingerprint(snap_fp: Dict[str, Any],
                      engine_fp: Dict[str, Any]) -> None:
    """Every fingerprint key except the pool size must match: page_size/
    layout/dtype mismatches would silently corrupt KV addressing, and
    chunk/gamma/spec mismatches would break the worst-case page
    reservations already encoded in the slot state. ``n_pages`` is
    exempt — re-layout through the allocator is the design."""
    for key in sorted(set(snap_fp) | set(engine_fp)):
        if key == "n_pages":
            continue
        if snap_fp.get(key) != engine_fp.get(key):
            raise SnapshotError(
                f"snapshot/engine mismatch on {key!r}: snapshot has "
                f"{snap_fp.get(key)!r}, engine has {engine_fp.get(key)!r}")
