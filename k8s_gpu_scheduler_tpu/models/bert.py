"""BERT-base encoder — the bin-packed inference workload (BASELINE config 3).

Pure-JAX encoder sharing the ops layer with the decoder: non-causal
dense_attention, learned position embeddings, GELU MLP, LayerNorm (post-LN,
the original BERT arrangement). Inference-shaped: ``encode`` returns final
hidden states, ``classify`` a pooled logit head; ``main()`` is the pod
entrypoint that reports achieved QPS against the SLO env the scheduler
scored it by.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..ops.attention import dense_attention


@dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    n_classes: int = 2
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny() -> "BertConfig":
        return BertConfig(vocab=128, d_model=32, n_layers=2, n_heads=4,
                          d_ff=64, max_seq=64)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-12) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def init_params(cfg: BertConfig, key: jax.Array) -> Dict:
    ks = jax.random.split(key, 8)
    D, H, hd, F, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(cfg.dtype)

    return {
        "tok_embed": norm(ks[0], cfg.vocab, D),
        "pos_embed": norm(ks[1], cfg.max_seq, D),
        "blocks": {
            "wqkv": norm(ks[2], L, D, 3 * H * hd),
            "wo": norm(ks[3], L, H * hd, D),
            "ln1_s": jnp.ones((L, D), cfg.dtype),
            "ln1_b": jnp.zeros((L, D), cfg.dtype),
            "w1": norm(ks[4], L, D, F),
            "w2": norm(ks[5], L, F, D),
            "ln2_s": jnp.ones((L, D), cfg.dtype),
            "ln2_b": jnp.zeros((L, D), cfg.dtype),
        },
        "final_ln_s": jnp.ones((D,), cfg.dtype),
        "final_ln_b": jnp.zeros((D,), cfg.dtype),
        "cls": norm(ks[6], D, cfg.n_classes),
    }


def encode(params: Dict, tokens: jax.Array, cfg: BertConfig) -> jax.Array:
    """tokens [B, T] → hidden [B, T, D] (bidirectional attention)."""
    B, T = tokens.shape
    x = (params["tok_embed"][tokens] + params["pos_embed"][:T]).astype(cfg.dtype)

    def block(x, blk):
        qkv = x @ blk["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, cfg.n_heads, cfg.head_dim)
        attn = dense_attention(q.reshape(shape), k.reshape(shape),
                               v.reshape(shape), causal=False)
        x = layer_norm(x + attn.reshape(B, T, -1) @ blk["wo"],
                       blk["ln1_s"], blk["ln1_b"])
        h = jax.nn.gelu(x @ blk["w1"]) @ blk["w2"]
        x = layer_norm(x + h, blk["ln2_s"], blk["ln2_b"])
        return x, None

    x, _ = jax.lax.scan(block, x, params["blocks"])
    return layer_norm(x, params["final_ln_s"], params["final_ln_b"])


def classify(params: Dict, tokens: jax.Array, cfg: BertConfig) -> jax.Array:
    """[CLS]-pooled logits [B, n_classes] — the serving surface."""
    hidden = encode(params, tokens, cfg)
    return (hidden[:, 0] @ params["cls"]).astype(jnp.float32)


def main() -> None:  # pragma: no cover — the deploy/workloads entrypoint
    import os
    import time

    from ..utils.enforcement import apply_env_limits

    throttle = apply_env_limits()   # HBM cap + duty pacing (scheduler env)
    cfg = BertConfig.base()
    params = init_params(cfg, jax.random.PRNGKey(0))
    B, T = 32, 128
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    infer = jax.jit(lambda p, t: classify(p, t, cfg))
    infer(params, tokens).block_until_ready()  # compile — graftcheck: ignore[host-sync] (sanctioned: warmup barrier)
    slo = float(os.environ.get("SLO", "0") or 0)
    from ..recommender.collector import make_workload_publisher

    publish = make_workload_publisher()
    while True:
        t0 = time.perf_counter()
        # graftcheck: ignore[host-sync] — sanctioned: per-step sync IS the qps measurement of this host-paced loop
        infer(params, tokens).block_until_ready()
        step_dt = time.perf_counter() - t0
        qps = B / step_dt
        if throttle is not None:
            throttle.pace(step_dt)
        print(f"bert-base qps={qps:.1f} slo={slo} "
              f"chips={os.environ.get('TPU_VISIBLE_CHIPS', '?')}", flush=True)
        if publish is not None:
            publish(qps)  # feedback loop (recommender/collector.py)
        time.sleep(1)


if __name__ == "__main__":  # pragma: no cover
    main()
