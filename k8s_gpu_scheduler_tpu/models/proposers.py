"""Pluggable speculative-proposal sources for the paged batcher.

The serving engine's verify/accept/rewind machinery never cared WHERE
proposals come from — the verify dispatch takes a [n_slots, gamma]
token window and commits the accepted prefix — but until this module
the proposal source was hard-wired to the host-mirror bigram lookup
inside ``ContinuousBatcher._propose``. This module makes the source a
constructor argument (``ContinuousBatcher(speculative=True,
proposer=...)``) behind one small protocol:

- :class:`BigramProposer` — the extracted prompt-lookup bigram rule
  (latest bigram match over prompt + committed stream, served by an
  incremental bigram → latest-position index). The DEFAULT: engines
  built without an explicit proposer behave exactly as before.
- :class:`NgramProposer` — the same deferred-tail incremental index
  generalized to (n-1)-token context matches; longer contexts trade
  match frequency for match precision on structured text.
- :class:`DraftModelProposer` — a small ``LlamaConfig`` draft model
  scored in ONE jitted dispatch batched over all active slots per
  verify step (the gamma autoregressive draft steps unroll inside the
  program, so the host pays one tunnel round trip, not gamma). It is
  the one DISTRIBUTIONAL proposer: it returns the per-position draft
  distributions q it actually sampled from, and the engine's
  rejection-sampling verify then applies the full
  ``min(1, p/q)`` accept + ``max(0, p-q)`` residual-resample rule.

Rejection-sampling contract (Leviathan et al. 2023; Chen et al. 2023):
a proposer either samples proposal i from an explicit distribution
q_i — ``distributional = True``, ``propose_batch`` returns ``(props,
q)`` — or proposes deterministically, which is the q = delta(prop)
special case: the accept probability ``min(1, p_i/q_i)`` collapses to
``p_i[prop_i]`` and the residual to p with the proposed token zeroed.
Both cases leave the emitted stream distributed EXACTLY as the target
sampler (models/serving.py ``_verify_chunk_paged_fn``); greedy engines
(temperature == 0) reduce to exact-match acceptance either way.

Determinism: proposers are part of the seeded-replay plane
(graftcheck pass 12 lints this file). Host-mirror proposers are pure
functions of the committed streams; the draft proposer derives all of
its sampling randomness on device from the engine's dispatch counter
(``fold_in`` chains, the ``_decode_chunk_paged_fn`` convention), so
replaying the same submissions yields the same proposals.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Protocol, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig, forward

_NEG_INF = -1e30


class SlotView:
    """What a proposer may read about one active slot: the committed
    stream (prompt + emitted tokens) and the identity needed to keep
    incremental per-slot state coherent across slot reuse."""

    __slots__ = ("slot", "rid", "prompt", "out")

    def __init__(self, slot: int, rid: int,
                 prompt: Sequence[int], out: Sequence[int]) -> None:
        self.slot = int(slot)
        self.rid = int(rid)
        self.prompt = prompt
        self.out = out


class Proposer(Protocol):
    """Proposal source protocol. ``name`` labels the accept-rate
    metrics; ``distributional`` tells the engine whether proposals come
    with explicit q distributions (full min(1, p/q) rejection) or are
    deterministic (delta-q); ``batched`` selects the engine's dispatch
    style — per-slot calls with per-request error isolation, or one
    batched call per verify step."""

    name: str
    distributional: bool
    batched: bool

    def propose(self, view: SlotView, gamma: int) -> List[int]:
        """gamma proposal tokens for one slot (``batched = False``)."""
        ...

    def propose_batch(
        self, views: Sequence[SlotView], gamma: int, seed: int,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """(props [len(views), gamma] int32, q [len(views), gamma,
        vocab] float32 or None) for all active slots at once
        (``batched = True``). ``seed`` is the engine's dispatch
        counter — the only randomness source a proposer may use."""
        ...

    def drop(self, slot: int) -> None:
        """Forget per-slot state (slot freed, failed, or shed)."""
        ...


class NgramProposer:
    """Prompt-lookup proposals by LATEST (n-1)-token context match
    against the slot's committed stream — ``generate_speculative``'s
    rule on a host mirror, generalized from bigrams to n-grams.

    The match is served by a per-slot incremental context → latest-
    position index with the DEFERRED-TAIL invariant: the n-gram ending
    at the current tail is recorded only once a token lands after it,
    so a lookup of the tail context always answers with the latest
    *previous* occurrence — steady-state cost O(tokens committed since
    the last dispatch) = O(gamma) per slot, and the index rebuilds from
    the prompt when the slot changes hands (O(prompt), once per
    admission). No match → zeros; garbage guesses are simply rejected
    by the verify, costing nothing beyond the window the dispatch pads
    to anyway."""

    distributional = False
    batched = False

    def __init__(self, n: int = 3) -> None:
        if n < 2:
            raise ValueError(f"n-gram proposer needs n >= 2, got {n}")
        self.n = int(n)
        self.name = f"{self.n}gram"
        # slot -> (rid, hist list, context-tuple -> latest tail index)
        self._mirror: Dict[int, Tuple[int, list, dict]] = {}

    def _append(self, hist: list, idx: dict, tk: int) -> None:
        if len(hist) >= self.n:
            idx[tuple(hist[-self.n:])] = len(hist) - 1
        hist.append(tk)

    def propose(self, view: SlotView, gamma: int) -> List[int]:
        mirror = self._mirror.get(view.slot)
        if mirror is None or mirror[0] != view.rid:  # slot reassigned
            mirror = (view.rid, [], {})
            self._mirror[view.slot] = mirror
            for tk in view.prompt:
                self._append(mirror[1], mirror[2], int(tk))
        _, hist, idx = mirror
        base = len(view.prompt)
        for tk in view.out[len(hist) - base:]:
            self._append(hist, idx, int(tk))
        if len(hist) < self.n:
            return [0] * gamma
        j = idx.get(tuple(hist[-self.n:]))
        if j is None:
            return [0] * gamma
        guess = [int(tk) for tk in hist[j + 1:j + 1 + gamma]]
        return guess + [0] * (gamma - len(guess))

    def drop(self, slot: int) -> None:
        self._mirror.pop(slot, None)


class BigramProposer(NgramProposer):
    """The original host-mirror bigram lookup (n = 2) — the default
    proposer, byte-for-byte the behavior speculative engines had before
    proposers were pluggable."""

    def __init__(self) -> None:
        super().__init__(n=2)
        self.name = "bigram"


class DraftModelProposer:
    """Small-draft-model proposals with explicit q distributions.

    One jitted program per verify step, batched over ALL active slots:
    each slot's recent committed context (right-padded to a static
    ``ctx`` window) runs through the draft ``forward`` and the gamma
    autoregressive draft steps unroll INSIDE the program — per-slot
    fold_in'd keys sample each proposal from the draft's temperature/
    top-k distribution, and exactly those distributions return as q, so
    the engine's ``min(1, p/q)`` accept + residual resample is correct
    by construction. A draft sharing the target's weights and sampler
    settings yields q == p — every proposal accepts (the full-accept
    identity cell in tests/test_speculative_batcher.py).

    The draft should be MUCH smaller than the target (the whole point:
    gamma cheap forwards buy one expensive verify), share its vocab,
    and run greedy (``temperature=0`` → delta-q argmax proposals) or
    match the target's sampler. Context is truncated to the last
    ``ctx`` tokens — q is still exact (it is whatever the draft
    actually sampled from), truncation only costs accept rate."""

    distributional = True
    batched = True
    name = "draft"

    def __init__(self, cfg: LlamaConfig, params: Dict,
                 temperature: float = 0.0, top_k: int = 0,
                 ctx: int = 32) -> None:
        if ctx < 1:
            raise ValueError(f"draft context must be >= 1, got {ctx}")
        self.cfg = cfg
        self.params = params
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.ctx = int(min(ctx, cfg.max_seq))
        self._jit: Dict[int, object] = {}      # gamma -> compiled program

    def _program(self, gamma: int):
        """Build (once per gamma) the jitted batched draft program:
        (params, ctx_tokens [B, ctx+gamma], lens [B], seed) →
        (props [B, gamma] int32, q [B, gamma, vocab] float32)."""
        fn = self._jit.get(gamma)
        if fn is not None:
            return fn
        cfg, temp, tk = self.cfg, self.temperature, self.top_k
        span = self.ctx + gamma

        def program(params, tokens, lens, seed):
            base = jax.random.fold_in(jax.random.PRNGKey(0), seed)
            keys = jax.vmap(
                lambda s: jax.random.fold_in(base, s)
            )(jnp.arange(tokens.shape[0]))
            pos = jnp.arange(span)[None, :]
            props, qs = [], []
            for i in range(gamma):
                logits = forward(params, tokens, cfg)   # [B, span, V]
                row = jnp.take_along_axis(
                    logits, (lens + i - 1)[:, None, None], axis=1
                )[:, 0].astype(jnp.float32)             # [B, V]
                if temp <= 0.0:
                    nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
                    q = jax.nn.one_hot(nxt, cfg.vocab,
                                       dtype=jnp.float32)
                else:
                    adj = row / temp
                    if tk > 0:
                        kth = jax.lax.top_k(adj, tk)[0][..., -1:]
                        adj = jnp.where(adj < kth, _NEG_INF, adj)
                    q = jax.nn.softmax(adj, axis=-1)
                    step_keys = jax.vmap(
                        lambda k: jax.random.fold_in(k, i))(keys)
                    nxt = jax.vmap(jax.random.categorical)(
                        step_keys, adj).astype(jnp.int32)
                props.append(nxt)
                qs.append(q)
                tokens = jnp.where(pos == (lens + i)[:, None],
                                   nxt[:, None], tokens)
            return (jnp.stack(props, axis=1),
                    jnp.stack(qs, axis=1))

        fn = jax.jit(program)
        self._jit[gamma] = fn
        return fn

    def propose_batch(
        self, views: Sequence[SlotView], gamma: int, seed: int,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        B = len(views)
        span = self.ctx + gamma
        tokens = np.zeros((B, span), np.int32)
        lens = np.zeros((B,), np.int32)
        for i, v in enumerate(views):
            stream = list(v.prompt) + list(v.out)
            tail = stream[-self.ctx:]
            tokens[i, :len(tail)] = tail
            lens[i] = len(tail)
        props, q = self._program(gamma)(
            self.params, jnp.asarray(tokens), jnp.asarray(lens),
            jnp.int32(seed))
        # graftcheck: ignore[host-sync] — sanctioned: proposal tokens gate the verify dispatch's window operand (content-dependent by nature, the spec step's one-readback contract; q rides the same transfer)
        props, q = jax.device_get((props, q))
        return np.asarray(props, np.int32), np.asarray(q, np.float32)

    def drop(self, slot: int) -> None:  # stateless per slot
        pass


def resolve_proposer(spec) -> "Proposer":
    """Constructor-argument sugar: None → the historical bigram
    default; "bigram"/"ngram"/"ngram:N" → host-mirror proposers; a
    Proposer instance passes through (the only way to get a draft
    proposer — it needs weights)."""
    if spec is None or spec == "bigram":
        return BigramProposer()
    if isinstance(spec, str):
        if spec == "ngram":
            return NgramProposer()
        if spec.startswith("ngram:"):
            return NgramProposer(int(spec.split(":", 1)[1]))
        raise ValueError(
            f"unknown proposer {spec!r}: expected 'bigram', 'ngram', "
            f"'ngram:N', or a Proposer instance")
    for attr in ("name", "distributional", "batched", "drop"):
        if not hasattr(spec, attr):
            raise ValueError(
                f"proposer {spec!r} does not implement the Proposer "
                f"protocol (missing {attr!r})")
    return spec
