"""Llama-family decoder — the flagship workload (BASELINE config 4).

Design is TPU-first, not a torch port:
- params are a plain pytree with layers STACKED on a leading axis and the
  forward pass runs ``lax.scan`` over them — one trace/compile per block
  stack instead of per layer, the XLA-friendly shape;
- ``jax.checkpoint`` on the scanned block trades FLOPs for HBM (remat);
- bf16 activations/weights, f32 norm/softmax stats (MXU-shaped matmuls);
- parallelism is declarative: logical axes on every param
  (``param_axes``) + the rules table in parallel/sharding.py produce
  PartitionSpecs; ``make_train_step`` jits with those shardings and lets
  GSPMD insert the tp all-reduces. Sequence parallelism (ring/Ulysses) is
  a ``shard_map`` island around the attention call only.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import dense_attention, ring_attention, ulysses_attention
from ..ops.layers import apply_rope, rms_norm, rope_freqs, swiglu
from ..parallel.sharding import logical_axis_rules, shard_map, spec_for


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "dense"  # dense | ring | ulysses | flash (pallas)
    # Serving decode-attention path (models/serving.py): "fused" streams
    # the KV cache through the Pallas flash-decode kernel
    # (ops/decode_attention.py — in-kernel GQA, fused int8-KV dequant,
    # O(pos) length-masked reads); speculative verify windows (t =
    # 1+gamma) fuse too, through the multi-query variant
    # (paged_verify_attention). "dense" keeps the grouped-einsum
    # reference. Fused falls back to dense automatically when the cache
    # length has no legal blocking, t > 1 outside a verify window
    # (prefill), or the cache is mesh-sharded.
    decode_attn: str = "dense"
    remat: bool = True
    # Mixture-of-Experts (ops/moe.py): n_experts 0 = dense FFN; > 1 swaps
    # every layer's SwiGLU for top-k routed experts sharded over the ep
    # mesh axis, with a Switch-style balance loss folded into loss_fn.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            d_ff=14336, max_seq=8192,
        )

    @staticmethod
    def tiny(attn_impl: str = "dense") -> "LlamaConfig":
        """Test/dryrun scale: full architecture, toy widths."""
        return LlamaConfig(
            vocab=256, d_model=64, n_layers=2, n_heads=8, n_kv_heads=8,
            d_ff=128, max_seq=128, attn_impl=attn_impl, remat=False,
        )

    def flops_per_token(self, seq_len: int = 0) -> float:
        """Train-step FLOPs/token (the MFU numerator bench.py uses):
        6×params matmul FLOPs, plus the causal attention matmuls when
        ``seq_len`` is given — QK^T and PV are each 2·T·d FLOPs/token/layer
        forward, 3× that with backward, halved because the flash kernels
        skip fully-masked causal blocks: 12·L·d·T·½ = 6·L·d·T per token.
        Standard model-FLOPs accounting (no remat counted)."""
        p_block = (
            self.d_model * self.n_heads * self.head_dim  # wq
            + 2 * self.d_model * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.d_model  # wo
            + 3 * self.d_model * self.d_ff  # gate/up/down
        )
        p_matmul = self.n_layers * p_block + 2 * self.vocab * self.d_model
        attn = 6.0 * self.n_layers * self.d_model * seq_len
        return 6.0 * p_matmul + attn


def param_axes(cfg: LlamaConfig) -> Dict:
    """Logical sharding axes for every param leaf (leading 'layers' axis on
    the stacked blocks is never sharded). MoE configs stack experts on a
    leading 'expert' axis (→ ep mesh axis) and add the router."""
    L = ("layers",)
    if cfg.n_experts > 1:
        mlp = {
            "mlp_norm": L + ("norm",),
            "router": L + ("embed", "expert"),
            "w_gate": L + ("expert", "embed", "mlp"),
            "w_up": L + ("expert", "embed", "mlp"),
            "w_down": L + ("expert", "mlp", "embed"),
        }
    else:
        mlp = {
            "mlp_norm": L + ("norm",),
            "w_gate": L + ("embed", "mlp"),
            "w_up": L + ("embed", "mlp"),
            "w_down": L + ("mlp", "embed"),
        }
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": L + ("norm",),
            "wq": L + ("embed", "heads"),
            "wk": L + ("embed", "kv_heads"),
            "wv": L + ("embed", "kv_heads"),
            "wo": L + ("heads", "embed"),
            **mlp,
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def param_specs(cfg: LlamaConfig, rules: Optional[Dict] = None) -> Dict:
    rules = rules or logical_axis_rules({"layers": None})
    return jax.tree.map(
        lambda axes: spec_for(axes, rules),
        param_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def serving_weight_specs(params: Dict, rules: Optional[Dict] = None) -> Dict:
    """Per-leaf PartitionSpecs for a SERVING params pytree under Megatron
    weight sharding (models/serving.py weight_sharding=True): the block
    projections and MLP weights slice per the parallel/sharding.py
    WEIGHT_SPECS table — column-parallel q/k/v/gate/up on their OUTPUT
    axis, row-parallel o/down on their INPUT axis — and everything else
    (embed, norms, lm_head) replicates. Walks the ACTUAL params tree, so
    weight-only int8 leaves (ops/quant.py ``{"q","s"}`` dicts) slice
    coherently: ``q`` follows the weight's spec and the per-output-
    channel scale ``s`` [L, 1, N] slices with a column's N and stays
    replicated for a row slice (the scale spans the FULL contraction —
    slicing after quantization keeps every shard's dequant exact).
    Dense-MLP trees only: MoE expert stacks route through qeinsum shapes
    this table does not describe, and the engine rejects them up front."""
    from ..parallel.sharding import WEIGHT_SPECS, weight_slice_spec

    def replicated(leaf):
        return jax.tree.map(lambda _: P(), leaf)

    def block_leaf(name, leaf):
        kind = WEIGHT_SPECS.get(name)
        if kind is None:
            return replicated(leaf)
        spec = weight_slice_spec(kind, rules)
        if isinstance(leaf, dict):                   # int8 {"q","s"}
            return {"q": spec,
                    "s": spec if kind == "column" else P()}
        return spec

    if "router" in params.get("blocks", {}):
        raise ValueError(
            "serving weight sharding covers dense-MLP trees only "
            "(MoE expert stacks shard over ep, not tp)")
    out = {k: replicated(v) for k, v in params.items() if k != "blocks"}
    out["blocks"] = {k: block_leaf(k, v)
                     for k, v in params["blocks"].items()}
    return out


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict:
    ks = jax.random.split(key, 8)
    D, H, Hkv, hd, F, L = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff,
        cfg.n_layers,
    )

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(cfg.dtype)

    if cfg.n_experts > 1:
        E = cfg.n_experts
        mlp = {
            "mlp_norm": jnp.ones((L, D), cfg.dtype),
            # Router stays f32: softmax-over-experts precision decides
            # placements, and the tensor is tiny.
            "router": jax.random.normal(
                jax.random.fold_in(ks[5], 1), (L, D, E), jnp.float32) * 0.02,
            "w_gate": norm(ks[5], L, E, D, F),
            "w_up": norm(ks[6], L, E, D, F),
            "w_down": norm(ks[7], L, E, F, D),
        }
    else:
        mlp = {
            "mlp_norm": jnp.ones((L, D), cfg.dtype),
            "w_gate": norm(ks[5], L, D, F),
            "w_up": norm(ks[6], L, D, F),
            "w_down": norm(ks[7], L, F, D),
        }
    return {
        "embed": norm(ks[0], cfg.vocab, D),
        "blocks": {
            "attn_norm": jnp.ones((L, D), cfg.dtype),
            "wq": norm(ks[1], L, D, H * hd),
            "wk": norm(ks[2], L, D, Hkv * hd),
            "wv": norm(ks[3], L, D, Hkv * hd),
            "wo": norm(ks[4], L, H * hd, D),
            **mlp,
        },
        "final_norm": jnp.ones((D,), cfg.dtype),
        "lm_head": norm(ks[0], D, cfg.vocab),
    }


def _attention(cfg: LlamaConfig, mesh: Optional[Mesh], q, k, v):
    """Dispatch dense vs sequence-parallel attention. q/k/v are GLOBAL
    [B, T, H(kv), hd]; the shard_map island re-chunks T over 'sp' and heads
    over 'tp' and runs the ring/all_to_all collectives inside."""
    seq_parallel = (mesh is not None and "sp" in mesh.axis_names
                    and mesh.shape["sp"] > 1)
    if cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention_diff

        if mesh is None or mesh.size == 1:
            return flash_attention_diff(q, k, v, True)
        if not seq_parallel:
            # Pallas calls don't partition under GSPMD (XLA would replicate
            # the operands), so shard batch/head dims explicitly and run the
            # kernel per shard — attention is embarrassingly parallel over
            # (dp·fsdp, tp) when the sequence axis is whole.
            spec = P(("dp", "fsdp"), None, "tp", None)
            fn = shard_map(
                lambda q, k, v: flash_attention_diff(q, k, v, True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
            return fn(q, k, v)
        # sp > 1: the sequence-parallel ring (flash-style running stats,
        # XLA collectives over ICI) is the equivalent-cost path.
    if cfg.attn_impl == "dense" or not seq_parallel:
        return dense_attention(q, k, v, causal=True)
    impl = (ulysses_attention if cfg.attn_impl == "ulysses"
            else ring_attention)
    spec = P(("dp", "fsdp"), "sp", "tp", None)
    fn = shard_map(
        partial(impl, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def forward(
    params: Dict, tokens: jax.Array, cfg: LlamaConfig, mesh: Optional[Mesh] = None
) -> jax.Array:
    """tokens [B, T] int32 → logits [B, T, vocab]."""
    logits, _ = forward_with_aux(params, tokens, cfg, mesh)
    return logits


def forward_with_aux(
    params: Dict, tokens: jax.Array, cfg: LlamaConfig, mesh: Optional[Mesh] = None
) -> "tuple[jax.Array, jax.Array]":
    """(logits [B, T, vocab], MoE balance aux — 0.0 for dense configs)."""
    B, T = tokens.shape
    angles = rope_freqs(cfg.head_dim, T, cfg.rope_theta)
    # FSDP-style lookup: all-gather the table explicitly, then gather with
    # (batch, seq)-sharded indices — each device reads only its rows. Left
    # implicit, GSPMD operand-passthroughs the table sharding onto the
    # activation and can only reach the activation sharding by full
    # rematerialization (the round-2 SPMD warnings in MULTICHIP_r02.json).
    # The transpose is a reduce-scatter back into the sharded table grad —
    # the same collective pair FSDP pays for every weight.
    tokens = _constrain(tokens, mesh, P(("dp", "fsdp"), "sp"))
    table = _constrain(params["embed"], mesh, P(None, None))
    x = table[tokens].astype(cfg.dtype)
    x = _constrain(x, mesh, P(("dp", "fsdp"), "sp", None))

    def block(x, blk):
        x = attn_sublayer(
            cfg, x, blk, angles, lambda q, k, v: _attention(cfg, mesh, q, k, v))
        x, aux = mlp_sublayer(cfg, x, blk)
        x = _constrain(x, mesh, P(("dp", "fsdp"), "sp", None))
        return x, aux

    block_fn = jax.checkpoint(block) if cfg.remat else block
    x, aux = jax.lax.scan(block_fn, x, params["blocks"])
    x = rms_norm(x, params["final_norm"])
    return (x @ params["lm_head"]).astype(jnp.float32), aux.mean()


def attn_sublayer(cfg: LlamaConfig, x, blk, angles, attention_fn):
    """pre-norm attention half of a decoder block — THE one definition;
    forward_with_aux and the pipeline path (models/pipeline.py) both call
    it, so block-math changes can never diverge between layouts."""
    B, T, _ = x.shape
    h = rms_norm(x, blk["attn_norm"])
    q = (h @ blk["wq"]).reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = (h @ blk["wk"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ blk["wv"]).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q, k = apply_rope(q, angles), apply_rope(k, angles)
    attn = attention_fn(q, k, v)
    return x + attn.reshape(B, T, cfg.n_heads * cfg.head_dim) @ blk["wo"]


def mlp_sublayer(cfg: LlamaConfig, x, blk, dropless: bool = False):
    """pre-norm MLP half: dense SwiGLU or routed experts. Returns
    (x, balance aux — 0 for dense).

    ``dropless``: route every token to its top-k experts with no capacity
    machinery (ops/moe.py moe_ffn_dropless), making the output a PER-TOKEN
    function — independent of co-batched tokens and padding. Serving paths
    use this (capacity drops are a training-throughput tradeoff; at
    inference they would make a request's completion depend on its
    neighbors and on prefill padding). Training keeps the Switch capacity
    path with cfg.moe_capacity_factor."""
    h = rms_norm(x, blk["mlp_norm"])
    if cfg.n_experts > 1:
        if dropless:
            from ..ops.moe import moe_ffn_dropless

            moe_out = moe_ffn_dropless(
                h, blk["router"], blk["w_gate"], blk["w_up"],
                blk["w_down"], top_k=cfg.moe_top_k)
            return x + moe_out, jnp.zeros((), jnp.float32)
        from ..ops.moe import moe_ffn

        moe_out, aux = moe_ffn(
            h, blk["router"], blk["w_gate"], blk["w_up"], blk["w_down"],
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
        )
        return x + moe_out, aux
    return (x + swiglu(h, blk["w_gate"], blk["w_up"], blk["w_down"]),
            jnp.zeros((), jnp.float32))


def _constrain(x, mesh, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def loss_fn(
    params: Dict, batch: Dict, cfg: LlamaConfig, mesh: Optional[Mesh] = None
) -> jax.Array:
    """Causal-LM cross entropy; batch = {tokens [B,T], targets [B,T]}.

    MoE configs add the Switch balance aux scaled by moe_aux_coef.

    nll = logsumexp(logits) - logits[target], NOT log_softmax + gather: the
    log_softmax form materializes a second [B, T, vocab] f32 array between
    two HBM-bound passes, while the logsumexp form is one reduction plus a
    gather that XLA fuses into the lm_head matmul's epilogue — measured
    ~9% step-time win on v5e at vocab 32000 (identical value and gradient:
    d/dlogits of both is softmax - onehot)."""
    logits, aux = forward_with_aux(params, batch["tokens"], cfg, mesh)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(
        logits, batch["targets"][..., None], axis=-1)[..., 0]
    loss = (lse - tgt).mean()
    if cfg.n_experts > 1:
        loss = loss + cfg.moe_aux_coef * aux
    return loss


def make_train_step(cfg: LlamaConfig, mesh: Optional[Mesh], optimizer):
    """Build the jitted SPMD train step: value_and_grad + optimizer update,
    params/opt-state sharded per param_specs, batch over (dp, fsdp)."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    if mesh is None:
        # Donation matters single-device too: without it every step keeps a
        # second copy of params+opt state live in HBM.
        return jax.jit(step, donate_argnums=(0, 1))

    pspecs = param_specs(cfg)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                          is_leaf=lambda x: isinstance(x, P))
    batch_shard = NamedSharding(mesh, P(("dp", "fsdp"), None))
    # Optimizer state mirrors param sharding leaf-for-leaf (adam's mu/nu have
    # param shapes; scalars replicate).
    return jax.jit(
        step,
        in_shardings=(pshard, None, {"tokens": batch_shard, "targets": batch_shard}),
        donate_argnums=(0, 1),
    )


def main() -> None:  # pragma: no cover — the deploy/workloads entrypoint
    """Gang-pod entrypoint: derive the mesh from the env the scheduler
    injected (TPU_WORKER_ID/TPU_WORKER_HOSTNAMES via the ConfigMap side
    channel — gang.py post_bind) and train/serve on synthetic data."""
    import argparse
    import os
    import time

    import optax

    parser = argparse.ArgumentParser()
    parser.add_argument("--serve", action="store_true")
    parser.add_argument("--prompt-len", type=int, default=512)
    parser.add_argument("--max-new", type=int, default=64)
    # Serving engine options (serving.py): weight-only int8, sampling,
    # EOS early stop — 0/unset keep greedy full-precision fixed-budget.
    parser.add_argument("--int8", action="store_true",
                        help="weight-only int8 serving (ops/quant.py)")
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--eos-id", type=int, default=None)
    # Elastic recovery (utils/checkpoint.py): gang pods evicted by the
    # scheduler's all-or-nothing collapse or preemption resume from the
    # latest step when the controller recreates them.
    parser.add_argument("--ckpt-dir", default=os.environ.get("TPU_CKPT_DIR"))
    parser.add_argument("--ckpt-every", type=int, default=100)
    args = parser.parse_args()

    # Enforce the scheduler-injected sharing limits BEFORE the backend
    # initializes: XLA mem fraction from TPU_HBM_LIMIT_BYTES, host pacing
    # from TPU_DUTY_CYCLE_PERCENTAGE (utils/enforcement.py — the MPS-env
    # contract the reference gets from the CUDA runtime for free).
    from ..utils.enforcement import apply_env_limits

    throttle = apply_env_limits()

    from ..parallel import distributed_init_from_env

    # The injected TPU_WORKER_HOSTNAMES are pod-reachable addresses (stable
    # pod DNS for StatefulSet gangs); worker 0 is the coordinator.
    distributed_init_from_env()
    # Rank comes from the live runtime, NOT the TPU_WORKER_ID scalar: gangs
    # whose members share one EnvFrom ConfigMap all read the last-written
    # id (distributed.py self_worker_id) — process_index is always ours.
    worker_id = jax.process_index()
    n = len(jax.devices())
    from ..parallel import MeshSpec, make_mesh

    tp = min(4, n)
    mesh = make_mesh(MeshSpec.for_devices(n, tp=tp)) if n > 1 else None

    cfg = LlamaConfig.llama3_8b() if not args.serve else LlamaConfig(
        vocab=32000, d_model=1024, n_layers=8, n_heads=16, n_kv_heads=16,
        d_ff=4096, max_seq=2048, remat=False,
    )
    B, T = (8, 2048) if not args.serve else (1, args.prompt_len)
    if mesh is not None:
        # Multi-process SPMD: host-local eager arrays cannot feed a jit
        # whose in_shardings span a non-fully-addressable mesh — build
        # params and data INSIDE jit with global out_shardings, so each
        # process materializes only its shards.
        pshard = jax.tree.map(
            lambda s: NamedSharding(mesh, s), param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P),
        )
        params = jax.jit(partial(init_params, cfg), out_shardings=pshard)(
            jax.random.PRNGKey(0)
        )
        tok_shard = NamedSharding(mesh, P(("dp", "fsdp"), None))
        tokens = jax.jit(
            lambda k: jax.random.randint(k, (B, T), 0, cfg.vocab),
            out_shardings=tok_shard,
        )(jax.random.PRNGKey(1))
    else:
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    slo = float(os.environ.get("SLO", "0") or 0)

    # Observed-throughput feedback (recommender/collector.py): when the pod
    # carries WORKLOAD_NAME and the registry is reachable, measured
    # intervals are published as Observations (live-neighbor tagged) — the
    # collector folds them into the train matrices and the recommender's
    # next prediction is anchored on reality instead of seed data. The ONE
    # wiring shared with the resnet/bert entrypoints.
    from ..recommender.collector import make_workload_publisher

    publish = make_workload_publisher(n_devices=n)

    if args.serve:
        # Serving (BASELINE config 5). Single-process (any local chip
        # count — the batcher takes the mesh): the continuous batcher
        # (serving.py — slot admission between decode chunks).
        # Multi-process SPMD: the static-batch handler (every worker must
        # run the identical program schedule, which per-process host-driven
        # admission does not guarantee).
        import numpy as _np

        Tp, max_new = args.prompt_len, args.max_new
        if jax.process_count() == 1:
            from .lifecycle import (
                PreemptionGuard, drain_to_checkpoint, resume_or_fresh,
            )
            from .serving import ContinuousBatcher

            sparams = params
            if args.int8:
                from ..ops.quant import quantize_llama_params

                sparams = quantize_llama_params(params)
            n_slots = 8
            # Paged is the preemption-safe production layout (drain/
            # snapshot/restore is pool pages + block tables); the paged
            # pool is single-chip for now, so a local mesh keeps the
            # contiguous cache and skips the snapshot lifecycle.
            layout = "paged" if mesh is None else "contiguous"

            def mk_engine():
                return ContinuousBatcher(
                    sparams, cfg, n_slots=n_slots, max_len=cfg.max_seq,
                    chunk=max_new, prefill_bucket=max(Tp, 16), mesh=mesh,
                    eos_id=args.eos_id, temperature=args.temperature,
                    top_k=args.top_k, kv_layout=layout)

            # Preemption lifecycle (models/lifecycle.py): boot resumes
            # the predecessor pod's drained snapshot when one exists on
            # the volume (restore_or-style); SIGTERM — GKE sends it
            # ~30 s before spot reclaim — requests a drain the wave
            # boundary below honors.
            snap_dir = (os.path.join(args.ckpt_dir, "serve_snapshot")
                        if args.ckpt_dir and layout == "paged" else None)
            eng, resumed = resume_or_fresh(mk_engine, snap_dir)
            if resumed:
                print(f"llama serve worker={worker_id} resumed "
                      f"{resumed} in-flight requests from {snap_dir}",
                      flush=True)
            guard = PreemptionGuard().install()
            rng = _np.random.default_rng(0)

            def prompt_arr():
                return rng.integers(0, cfg.vocab, Tp)

            eng.submit(prompt_arr(), max_new=max_new + 1)
            eng.run()                                   # compile both
            # Discard the warmup's latency record — it carries compile
            # time (seconds through the remote tunnel), and the FIRST
            # p99 published seeds the registry latency EWMA verbatim.
            eng.pop_request_metrics()
            while True:
                if guard.requested:
                    # Drain at the wave boundary (never mid-step), save
                    # to the pod volume, exit 0 — the replacement pod's
                    # resume_or_fresh above finishes the streams.
                    if snap_dir is not None:
                        snap = drain_to_checkpoint(eng, snap_dir)
                        print(f"llama serve worker={worker_id} drained "
                              f"{snap.n_requests_in_flight} requests to "
                              f"{snap_dir}", flush=True)
                    raise SystemExit(0)
                t0 = time.perf_counter()
                n_req = 4 * n_slots
                for _ in range(n_req):
                    eng.submit(prompt_arr(), max_new=max_new)
                done = eng.run()
                dt = time.perf_counter() - t0
                # Count tokens actually emitted — with --eos-id, early-
                # stopped requests decode fewer than max_new.
                n_tok = sum(len(v) for v in done.values())
                # Measured per-request latency (serving.py records it at
                # flush): publish the wave's p99 so the collector folds it
                # and the scheduler right-sizes against observed latency,
                # not only predicted QPS.
                lats = sorted(m["latency_s"] * 1000 for m in
                              eng.pop_request_metrics().values())
                p99 = lats[min(len(lats) - 1,
                               round(0.99 * (len(lats) - 1)))] if lats else 0.0
                print(f"llama serve qps={n_req / dt:.2f} "
                      f"decode_tok_s={n_tok / dt:.1f} "
                      f"prefill_tok={n_req * Tp} slo={slo} "
                      f"p99_ms={p99:.1f}", flush=True)
                if publish is not None:
                    publish(n_req / dt, p99_ms=p99)
                if throttle is not None:
                    throttle.pace(dt)
                # ~1 Hz pacing like the static loop: each publish is a
                # registry GET (live neighbors) + SET — a fast wave must
                # not turn one pod into a tens-of-Hz registry hammer.
                time.sleep(max(0.0, 1.0 - dt))
        if (args.int8 or args.temperature > 0 or args.top_k > 0
                or args.eos_id is not None):
            # Refuse rather than silently downgrade: the static multi-host
            # handler is full-precision greedy fixed-budget (per-process
            # host-driven admission can't keep SPMD workers in lockstep).
            raise SystemExit(
                "--int8/--temperature/--top-k/--eos-id need the continuous "
                "batcher, which is single-process only; this gang has "
                f"{jax.process_count()} processes")
        from .serving import make_server_step

        handler = make_server_step(cfg, mesh, max_new, max_len=cfg.max_seq)
        prompt = tokens[:, :Tp]
        handler(params, prompt).block_until_ready()  # compile — graftcheck: ignore[host-sync] (sanctioned: warmup barrier before the serve loop)
        while True:
            t0 = time.perf_counter()
            out = handler(params, prompt)
            # Host sync via block_until_ready: indexing a concrete element
            # would fetch a global-array slice that is non-addressable on
            # most workers when batch is sharded over (dp, fsdp) — jax
            # raises and multi-host serving dies. block_until_ready syncs
            # on every worker without materializing remote shards.
            jax.block_until_ready(out)  # graftcheck: ignore[host-sync] — sanctioned: the documented multi-host serve-loop sync (comment above)
            dt = time.perf_counter() - t0
            b = prompt.shape[0]
            print(f"llama serve qps={b / dt:.2f} "
                  f"decode_tok_s={b * max_new / dt:.1f} "
                  f"prefill_tok={b * Tp} slo={slo}", flush=True)
            if publish is not None:
                publish(b / dt)
            if throttle is not None:
                throttle.pace(dt)
            time.sleep(max(0.0, 1.0 - dt))
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
    opt = optax.adamw(3e-4)
    # jit keeps the optimizer state's shards following the params' shards
    # (eager zeros_like would be fine single-host; multi-host needs it).
    state = jax.jit(opt.init)(params)
    step = make_train_step(cfg, mesh, opt)

    ckpt = None
    step_no = 0
    if args.ckpt_dir:
        from ..utils.checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(args.ckpt_dir)
        # The fresh (params, state) is the restore template: it carries
        # the pytree structure AND the mesh shardings, so a multi-host
        # restore lands shards where the train step expects them.
        step_no, (params, state) = ckpt.restore_or(lambda: (params, state))
        if step_no:
            print(f"llama pretrain worker={worker_id} resumed at step "
                  f"{step_no} from {args.ckpt_dir}", flush=True)
    try:
        while True:
            t0 = time.perf_counter()
            params, state, loss = step(params, state, batch)
            step_dt = time.perf_counter() - t0
            step_no += 1
            tok_s = B * T / step_dt
            print(f"llama pretrain worker={worker_id} step={step_no} "
                  f"tok/s={tok_s:.0f} loss={float(loss):.3f}", flush=True)
            if throttle is not None:
                throttle.pace(step_dt)
            if ckpt is not None:
                ckpt.maybe_save(step_no, (params, state),
                                every=args.ckpt_every)
            if publish is not None and worker_id == 0:
                publish(tok_s)
    finally:
        if ckpt is not None:
            ckpt.close()                             # drain async saves + release


if __name__ == "__main__":  # pragma: no cover
    main()
