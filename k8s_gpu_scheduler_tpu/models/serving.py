"""KV-cache autoregressive serving for the Llama decoder.

Replaces the round-2 "--serve" loop (full 512-token forward once per
second — VERDICT.md weak #3) with a real inference path:

- **Prefill**: one forward over the prompt writing every layer's K/V into a
  preallocated [L, B, max_seq, Hkv, hd] cache (static shapes — XLA compiles
  exactly two programs: prefill at the prompt length, decode at t=1).
- **Decode**: per-token forward attending to the cache through a length
  mask; the whole decode loop runs as one ``lax.scan`` inside jit, so a
  request costs one dispatch, not max_new round-trips (critical under the
  axon tunnel, whose host↔device round trip is ~100 ms).
- **Sharding**: the cache is an activation — batch over (dp, fsdp), heads
  over tp, like every other activation (parallel/sharding.py conventions).
  ``generate`` constrains it when a mesh is passed, so multi-chip serving
  shards the cache instead of replicating it.

The reference has no serving engine at all (it schedules inference pods,
SURVEY.md §0); this is the workload side of BASELINE config 5
(serving + training co-located), which the TPU plugin right-sizes against
the recommender's QPS predictions.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import _repeat_kv
from ..ops.layers import apply_rope, rms_norm, rope_freqs, swiglu
from .llama import LlamaConfig, _constrain

_NEG_INF = -1e30

# Cache layout [L, B, S, Hkv, hd]: batch over (dp, fsdp), kv heads over tp.
CACHE_SPEC = P(None, ("dp", "fsdp"), None, "tp", None)


def init_cache(cfg: LlamaConfig, batch: int,
               max_len: Optional[int] = None) -> Dict[str, jax.Array]:
    """Preallocated zeros cache; ``len`` tracks the filled prefix."""
    S = max_len or cfg.max_seq
    shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array) -> jax.Array:
    """Attention of q [B, t, H, hd] (absolute positions pos..pos+t-1)
    against the cache [B, S, Hkv, hd], masked to entries < pos+t with
    causal order inside the new window. Dense over S — decode is a
    [1, S]·[S, hd] matvec, bandwidth-bound by the cache read, which is the
    irreducible cost."""
    b, t, n_heads, d = q.shape
    s = k_cache.shape[1]
    k = _repeat_kv(k_cache, n_heads)
    v = _repeat_kv(v_cache, n_heads)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    q_pos = pos + jnp.arange(t)[:, None]          # [t, 1] absolute
    k_pos = jnp.arange(s)[None, :]                # [1, S]
    scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def forward_with_cache(
    params: Dict, tokens: jax.Array, cfg: LlamaConfig,
    cache: Dict[str, jax.Array], mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tokens [B, t] starting at absolute position cache["len"] →
    (logits [B, t, vocab], updated cache). t is static (prefill: prompt
    length; decode: 1); the position is traced, so both programs compile
    once and serve any request length ≤ max_seq."""
    B, t = tokens.shape
    pos = cache["len"]
    angles = jax.lax.dynamic_slice_in_dim(
        rope_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta), pos, t, 0)
    x = params["embed"][tokens].astype(cfg.dtype)
    x = _constrain(x, mesh, P(("dp", "fsdp"), None, None))

    def block(x, layer):
        blk, k_cache, v_cache = layer
        h = rms_norm(x, blk["attn_norm"])
        q = (h @ blk["wq"]).reshape(B, t, cfg.n_heads, cfg.head_dim)
        k = (h @ blk["wk"]).reshape(B, t, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ blk["wv"]).reshape(B, t, cfg.n_kv_heads, cfg.head_dim)
        q, k = apply_rope(q, angles), apply_rope(k, angles)
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, pos, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, pos, axis=1)
        attn = cached_attention(q, k_cache, v_cache, pos)
        x = x + attn.reshape(B, t, cfg.n_heads * cfg.head_dim) @ blk["wo"]
        h = rms_norm(x, blk["mlp_norm"])
        x = x + swiglu(h, blk["w_gate"], blk["w_up"], blk["w_down"])
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    k_new = _constrain(k_new, mesh, CACHE_SPEC)
    v_new = _constrain(v_new, mesh, CACHE_SPEC)
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": k_new, "v": v_new, "len": pos + t}


def generate(
    params: Dict, prompt: jax.Array, cfg: LlamaConfig, max_new: int,
    mesh: Optional[Mesh] = None, max_len: Optional[int] = None,
) -> jax.Array:
    """Greedy decode: prefill the prompt, then scan max_new single-token
    steps inside one jit program. Returns [B, max_new] token ids."""
    B, t_prompt = prompt.shape
    S = min(max_len or cfg.max_seq, cfg.max_seq)
    if t_prompt + max_new > S:
        # dynamic_update_slice CLAMPS out-of-range starts — without this
        # check an overlong request would silently overwrite the last cache
        # slot (and read stale rope angles) instead of failing.
        raise ValueError(
            f"prompt ({t_prompt}) + max_new ({max_new}) exceeds cache/rope "
            f"capacity ({S})")
    cache = init_cache(cfg, B, max_len)
    cache["k"] = _constrain(cache["k"], mesh, CACHE_SPEC)
    cache["v"] = _constrain(cache["v"], mesh, CACHE_SPEC)
    logits, cache = forward_with_cache(params, prompt, cfg, cache, mesh)
    last = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)

    def dec(carry, _):
        last, cache = carry
        logits, cache = forward_with_cache(
            params, last[:, None], cfg, cache, mesh)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(last.dtype)
        return (nxt, cache), last

    (_, _), toks = jax.lax.scan(dec, (last, cache), None, length=max_new)
    return jnp.swapaxes(toks, 0, 1)              # [B, max_new]


def make_server_step(cfg: LlamaConfig, mesh: Optional[Mesh], max_new: int,
                     max_len: Optional[int] = None):
    """Jitted request handler: (params, prompt [B, Tp]) → [B, max_new]."""
    fn = partial(generate, cfg=cfg, max_new=max_new, mesh=mesh,
                 max_len=max_len)
    return jax.jit(fn)
