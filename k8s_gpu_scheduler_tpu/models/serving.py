"""KV-cache autoregressive serving for the Llama decoder.

Replaces the round-2 "--serve" loop (full 512-token forward once per
second — VERDICT.md weak #3) with a real inference path:

- **Prefill**: one forward over the prompt writing every layer's K/V into a
  preallocated [L, B, max_seq, Hkv, hd] cache (static shapes — XLA compiles
  exactly two programs: prefill at the prompt length, decode at t=1).
- **Decode**: per-token forward attending to the cache through a length
  mask; the whole decode loop runs as one ``lax.scan`` inside jit, so a
  request costs one dispatch, not max_new round-trips (critical under the
  axon tunnel, whose host↔device round trip is ~100 ms).
- **Sharding**: the cache is an activation — batch over (dp, fsdp), heads
  over tp, like every other activation (parallel/sharding.py conventions).
  ``generate`` constrains it when a mesh is passed, so multi-chip serving
  shards the cache instead of replicating it.

Decode attention itself has two implementations (``LlamaConfig.
decode_attn``): the grouped-einsum dense path (no ``_repeat_kv``
materialization — GQA contracts through a [B, Hkv, g, ...] head-group
axis) and the fused Pallas flash-decode kernel
(``ops/decode_attention.py``: block-streamed cache reads, in-kernel GQA,
fused int8-KV dequant, O(pos) length-masked traffic, split-K), with
automatic fallback to dense wherever the kernel doesn't apply.

On top of the static path: ``ContinuousBatcher`` (slot admission between
decode chunks, batched one-dispatch prefill with a bucket ladder for long
prompts, deferred readbacks, EOS early-stop, temperature/top-k sampling,
int8 weights via ops/quant.py) with TWO cache layouts — the contiguous
shared-cursor cache and a vLLM-style PAGED cache (``kv_layout="paged"``:
fixed-size page pool + per-slot block tables + models/paging.py's host
allocator; no admission contiguity constraint, no epoch roll, block
tables ride the fused kernel as a scalar-prefetch operand), a
SHARED-PREFIX radix cache over the paged pool (``prefix_cache=True``,
models/prefix_cache.py: reaped prompts donate their full pages into a
token-chunk tree, admission mounts the longest cached prefix read-only
and prefills only the novel tail — ref-counted pages, copy-on-write at
page granularity, LRU eviction) — and SPECULATIVE DECODING, two ways:
``generate_speculative`` (single-request prompt-lookup speculation,
draft-model-free — the reference implementation) and the paged batcher's
``speculative=True`` (per-slot prompt-lookup proposals on the host token
mirror, one batched multi-query verify dispatch over all slots through
``ops.paged_verify_attention``, vectorized accept/reject, rewind by
clamping each slot's ``lens`` — up to gamma+1 committed tokens per slot
per dispatch) — and CHUNKED PREFILL (``prefill_chunk_tokens=N``,
Sarathi-Serve-style): admission only reserves pages and binds the slot,
and each step spends at most N prompt tokens advancing partially-
prefilled slots (oldest first) before the decode/verify chunk, so a
long-prompt arrival costs every active decode slot a bounded per-step
overhead instead of one whole-prefill stall. A continuation chunk IS
the prefix-cache tail-prefill program — the "hit" is the rows this
slot's own earlier chunks made resident — so chunked == unchunked
token identity rides the same argument as cache-on == cache-off.

The reference has no serving engine at all (it schedules inference pods,
SURVEY.md §0); this is the workload side of BASELINE config 5
(serving + training co-located), which the TPU plugin right-sizes against
the recommender's QPS predictions.
"""
from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import FlightRecorder, SYSTEM_CLOCK
from ..parallel.sharding import (
    DEFAULT_RULES, KV_POOL_AXES, shard_map as _shard_map, spec_for,
)
from ..ops.decode_attention import (
    DEFAULT_PAGE_SIZE, contiguous_as_paged, decode_plan,
    dense_decode_reference, dense_verify_reference, flash_decode_attention,
    gather_paged_kv, paged_decode_attention, paged_plan,
    paged_prefill_attention, paged_verify_attention, prefill_plan,
    verify_plan,
)
from ..ops.layers import apply_rope, rms_norm, rope_freqs
from ..ops.quant import qdot
from ..testing.faults import Preempted
from .llama import LlamaConfig, _constrain, mlp_sublayer
from .paging import NULL_PAGE, HostTierStore, PageAllocator
from .prefix_cache import PrefixCache
from .proposers import SlotView, resolve_proposer
from .snapshot import ServingSnapshot, SnapshotError, check_fingerprint

_NEG_INF = -1e30

# Adaptive-gamma accept-rate smoothing: the per-request EMA reacts fast
# (a request's self-repetition regime shifts within tens of tokens); the
# fleet EMA — which seeds new requests AND sizes their pinned page
# reservation — moves slowly so one pathological stream cannot whipsaw
# admission math.
_SPEC_EMA_ALPHA = 0.3
_SPEC_FLEET_ALPHA = 0.05

# Cache layout [L, B, S, Hkv, hd]: batch over (dp, fsdp), kv heads over tp.
CACHE_SPEC = P(None, ("dp", "fsdp"), None, "tp", None)

# Paged pool layout [L, n_pages, ps, Hkv, hd]: KV HEADS over tp, everything
# else replicated — DERIVED from parallel/sharding.py's rules table
# (`spec_for(KV_POOL_AXES, DEFAULT_RULES)` — the same "kv_heads → tp"
# entry every activation uses), so each chip holds Hkv/tp heads of EVERY
# page and the Pallas decode/verify kernels run unchanged per shard
# inside a shard_map island (pallas_call does not partition under GSPMD;
# shard_map makes the shards explicit). The graftcheck GSPMD audit
# derives its expected island mapping from the same table, so the
# runtime and the guard rail cannot drift. Normalized (trailing None
# trimmed): shard_map outputs come back with trailing replicated axes
# trimmed from the spec, and the donated-through pool must keep ONE jit
# cache key across dispatches — an un-normalized initial placement would
# retrace once at the first output→input hand-back.
TP_AXIS = str(DEFAULT_RULES["kv_heads"])


def _trim_spec(spec: P) -> P:
    entries = list(spec)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


POOL_SPEC = _trim_spec(spec_for(KV_POOL_AXES, DEFAULT_RULES))


# -- fused→dense downgrade visibility -----------------------------------------
#
# A config that ASKS for decode_attn="fused" and silently gets the dense
# path is a quiet ~10x on cache traffic. Every downgrade decision funnels
# through here: counted per reason (exported as
# tpu_serve_decode_fallback_total{reason=}) and warned ONCE per reason per
# process. Decisions happen at trace/engine-build time — per compiled
# program, not per token — so the counter measures configs that lost the
# kernel, not traffic.
_decode_fallback_counts: Dict[str, int] = {}


def _note_decode_fallback(reason: str, msg: Optional[str] = None) -> None:
    import warnings

    first = reason not in _decode_fallback_counts
    _decode_fallback_counts[reason] = \
        _decode_fallback_counts.get(reason, 0) + 1
    if first:
        warnings.warn(
            msg or (
                f"decode_attn='fused' downgraded to the dense path "
                f"(reason={reason}): the config asked for the Pallas "
                f"decode kernel and is not getting it — see "
                f"tpu_serve_decode_fallback_total{{reason={reason!r}}}"),
            RuntimeWarning, stacklevel=3)


def fallback_notes_suppressed(*reasons: str):
    """Context manager for DELIBERATE-downgrade engine builds (the
    graftcheck audit registries, fixtures): the build neither warns nor
    counts — counter AND warn-once state for ``reasons`` are restored
    on exit, so the first REAL engine still warns and
    ``tpu_serve_decode_fallback_total`` counts only production
    decisions, never the audit's throwaway engines."""
    import warnings
    from contextlib import contextmanager

    @contextmanager
    def cm():
        before = {r: _decode_fallback_counts.get(r) for r in reasons}
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                yield
        finally:
            # Restore even if the wrapped build raises mid-__init__
            # (after its _note_decode_fallback but before finishing) —
            # otherwise the reason's warn-once is permanently consumed
            # and the counter keeps an audit-throwaway engine's mark.
            for r, v in before.items():
                if v is None:
                    _decode_fallback_counts.pop(r, None)
                else:
                    _decode_fallback_counts[r] = v

    return cm()


def decode_fallback_counts() -> Dict[str, int]:
    """{reason: downgrade decisions} since process start (or the last
    reset) — the exporter maps this onto the labeled
    ``tpu_serve_decode_fallback_total`` counter."""
    return dict(_decode_fallback_counts)


def reset_decode_fallback_counts() -> None:
    _decode_fallback_counts.clear()


def init_cache(cfg: LlamaConfig, batch: int,
               max_len: Optional[int] = None) -> Dict[str, jax.Array]:
    """Preallocated zeros cache; ``len`` tracks the filled prefix."""
    S = max_len or cfg.max_seq
    shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array, impl: str = "dense",
                     interpret: Optional[bool] = None,
                     verify: bool = False) -> jax.Array:
    """Attention of q [B, t, H, hd] (absolute positions pos..pos+t-1)
    against the cache [B, S, Hkv, hd], masked to entries < pos+t with
    causal order inside the new window.

    ``impl="fused"`` routes the decode shape (t == 1) through the Pallas
    flash-decode kernel (ops/decode_attention.py): cache rows stream
    through VMEM once with in-kernel GQA and blocks past ``pos`` skipped,
    so the step costs O(pos) HBM traffic instead of O(max_seq).
    ``verify=True`` extends the fused route to t > 1 — the speculative
    1+gamma verify window — through the MULTI-QUERY kernel
    (ops.paged_verify_attention), the contiguous cache viewed as a paged
    pool with an iota block table (contiguous_as_paged: a reshape, no
    copy). Shapes the blocking cannot cover — and every other t > 1 call
    (prefill) — fall back automatically to the dense path, which
    contracts through a grouped [B, Hkv, g, ...] head axis rather than
    materializing an H/Hkv-times `_repeat_kv` copy of the cache."""
    b, t, n_heads, d = q.shape
    s, h_kv = k_cache.shape[1], k_cache.shape[2]
    if impl == "fused" and t == 1 and n_heads % h_kv == 0 \
            and decode_plan(s) is not None:
        out = flash_decode_attention(
            q[:, 0], k_cache, v_cache, pos + 1, interpret=interpret)
        return out[:, None]
    if impl == "fused" and verify and t > 1 and n_heads % h_kv == 0 \
            and decode_plan(s) is not None:
        block_k = decode_plan(s)[0]
        if verify_plan(s // block_k, block_k, t) is not None:
            kp, table = contiguous_as_paged(k_cache, block_k)
            vp, _ = contiguous_as_paged(v_cache, block_k)
            return paged_verify_attention(q, kp, vp, table, pos,
                                          interpret=interpret)
    g = n_heads // h_kv
    qg = q.reshape(b, t, h_kv, g, d)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache).astype(jnp.float32) * scale
    q_pos = pos + jnp.arange(t)[:, None]          # [t, 1] absolute
    k_pos = jnp.arange(s)[None, :]                # [1, S]
    scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v_cache)
    return out.reshape(b, t, n_heads, d)


def forward_with_cache(
    params: Dict, tokens: jax.Array, cfg: LlamaConfig,
    cache: Dict[str, jax.Array], mesh: Optional[Mesh] = None,
    verify: bool = False,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tokens [B, t] starting at absolute position cache["len"] →
    (logits [B, t, vocab], updated cache). t is static (prefill: prompt
    length; decode: 1); the position is traced, so both programs compile
    once and serve any request length ≤ max_seq. ``verify=True`` marks a
    speculative 1+gamma verify window, letting ``decode_attn="fused"``
    route the t > 1 attention through the multi-query kernel instead of
    the dense fallback (prefill calls stay dense — the flag is how the
    two t > 1 shapes are told apart). MoE configs route
    DROPLESS (mlp_sublayer dropless=True): at inference a capacity drop
    would make a request's completion depend on co-batched tokens and on
    prefill padding, so serving output is a per-token function; it matches
    the training forward wherever training didn't drop."""
    B, t = tokens.shape
    pos = cache["len"]
    # Fused Pallas decode attention only off-mesh HERE: pallas_call does
    # not partition under GSPMD, so a mesh-CONSTRAINED contiguous cache
    # keeps the dense einsum path (XLA shards it like any other
    # activation). The downgrade is counted + warned — never silent. The
    # PAGED engine serves fused ON a mesh through its shard_map islands
    # (ContinuousBatcher(mesh=...)); this gate covers only the static
    # generate/contiguous path.
    attn_impl = getattr(cfg, "decode_attn", "dense")
    if attn_impl == "fused" and mesh is not None:
        _note_decode_fallback("mesh_constrained_cache")
        attn_impl = "dense"
    angles = jax.lax.dynamic_slice_in_dim(
        rope_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta), pos, t, 0)
    x = params["embed"][tokens].astype(cfg.dtype)
    x = _constrain(x, mesh, P(("dp", "fsdp"), None, None))

    def block(x, layer):
        blk, k_cache, v_cache = layer
        h = rms_norm(x, blk["attn_norm"])
        q = qdot(h, blk["wq"]).reshape(B, t, cfg.n_heads, cfg.head_dim)
        k = qdot(h, blk["wk"]).reshape(B, t, cfg.n_kv_heads, cfg.head_dim)
        v = qdot(h, blk["wv"]).reshape(B, t, cfg.n_kv_heads, cfg.head_dim)
        q, k = apply_rope(q, angles), apply_rope(k, angles)
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, pos, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, pos, axis=1)
        attn = cached_attention(q, k_cache, v_cache, pos, impl=attn_impl,
                                verify=verify)
        x = x + qdot(attn.reshape(B, t, cfg.n_heads * cfg.head_dim), blk["wo"])
        x, _ = mlp_sublayer(cfg, x, blk, dropless=True)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    k_new = _constrain(k_new, mesh, CACHE_SPEC)
    v_new = _constrain(v_new, mesh, CACHE_SPEC)
    x = rms_norm(x, params["final_norm"])
    logits = qdot(x, params["lm_head"]).astype(jnp.float32)
    return logits, {"k": k_new, "v": v_new, "len": pos + t}


def generate(
    params: Dict, prompt: jax.Array, cfg: LlamaConfig, max_new: int,
    mesh: Optional[Mesh] = None, max_len: Optional[int] = None,
) -> jax.Array:
    """Greedy decode: prefill the prompt, then scan max_new single-token
    steps inside one jit program. Returns [B, max_new] token ids."""
    B, t_prompt = prompt.shape
    S = min(max_len or cfg.max_seq, cfg.max_seq)
    if t_prompt + max_new > S:
        # dynamic_update_slice CLAMPS out-of-range starts — without this
        # check an overlong request would silently overwrite the last cache
        # slot (and read stale rope angles) instead of failing.
        raise ValueError(
            f"prompt ({t_prompt}) + max_new ({max_new}) exceeds cache/rope "
            f"capacity ({S})")
    cache = init_cache(cfg, B, max_len)
    cache["k"] = _constrain(cache["k"], mesh, CACHE_SPEC)
    cache["v"] = _constrain(cache["v"], mesh, CACHE_SPEC)
    logits, cache = forward_with_cache(params, prompt, cfg, cache, mesh)
    last = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)

    def dec(carry, _):
        last, cache = carry
        logits, cache = forward_with_cache(
            params, last[:, None], cfg, cache, mesh)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(last.dtype)
        return (nxt, cache), last

    (_, _), toks = jax.lax.scan(dec, (last, cache), None, length=max_new)
    return jnp.swapaxes(toks, 0, 1)              # [B, max_new]


def make_server_step(cfg: LlamaConfig, mesh: Optional[Mesh], max_new: int,
                     max_len: Optional[int] = None):
    """Jitted request handler: (params, prompt [B, Tp]) → [B, max_new]."""
    fn = partial(generate, cfg=cfg, max_new=max_new, mesh=mesh,
                 max_len=max_len)
    return jax.jit(fn)


def generate_speculative(
    params: Dict, prompt: jax.Array, cfg: LlamaConfig, max_new: int,
    gamma: int = 4, max_len: Optional[int] = None,
    temperature: float = 0.0, top_k: int = 0, seed: int = 0,
) -> jax.Array:
    """Decode with PROMPT-LOOKUP speculation (n-gram speculative
    decoding, draft-model-free): each iteration proposes ``gamma`` tokens
    by bigram match against the sequence so far and verifies them in ONE
    (1+gamma)-token forward.

    ``temperature == 0`` (default) accepts the longest prefix agreeing
    with greedy argmax — plus the model's own next token at the first
    disagreement. Output matches ``generate`` (acceptance is exact-match
    against the verify pass's own argmax; the only divergence source is a
    float near-tie between the differently-shaped passes). ``temperature
    > 0`` runs SPECULATIVE-SAMPLING REJECTION (Leviathan et al. 2023) in
    its deterministic-proposer (delta-q) form: proposal i accepts with
    prob p_i[prop_i] under the temperature/top-k target distribution, the
    first rejection resamples from p with the proposed token zeroed, and
    a full accept draws the bonus token from p_gamma — the emitted stream
    is distributed exactly as the target sampler's, same rule as the
    paged batcher's verify branch. Either way text with
    self-repetition (code, long documents) decodes up to gamma+1 tokens
    per model pass, and pathological inputs degrade to one token per
    pass, never below.

    Single request only (B=1): acceptance length varies per row, which a
    batch cannot share — the REFERENCE implementation; the paged
    ContinuousBatcher (``speculative=True``) runs the same propose/verify
    /accept loop across every slot at once. The cache rewind is safe
    because stale rows past the rewound ``len`` sit inside the NEXT
    verify's write window (width 1+gamma at the new position), and
    forward_with_cache writes each row before any query can attend it.

    With ``cfg.decode_attn="fused"`` the (1+gamma)-token verify pass runs
    through the multi-query Pallas kernel (ops.paged_verify_attention via
    ``verify=True`` — it previously fell back to the dense path, leaving
    speculation off the fused hot path); dense configs keep the dense
    verify, token-identical either way up to float near-ties.
    """
    B, t_prompt = prompt.shape
    if B != 1:
        raise ValueError(f"speculative decode is single-request (B=1), got {B}")
    S = min(max_len or cfg.max_seq, cfg.max_seq)
    if t_prompt + max_new + gamma > S:
        # Overshoot room: a verify may write gamma rows past the last
        # accepted position before the rewind.
        raise ValueError(
            f"prompt ({t_prompt}) + max_new ({max_new}) + gamma ({gamma}) "
            f"exceeds cache/rope capacity ({S})")

    S_buf = t_prompt + max_new + gamma + 1
    seq = jnp.zeros((1, S_buf), jnp.int32)
    seq = jax.lax.dynamic_update_slice(seq, prompt.astype(jnp.int32), (0, 0))

    sampled = temperature > 0.0
    base_key = jax.random.fold_in(jax.random.PRNGKey(0), seed)

    cache = init_cache(cfg, 1, max_len)
    logits, cache = forward_with_cache(params, prompt, cfg, cache)
    if sampled:
        first = _sample_tokens(logits[:, -1],
                               jax.random.fold_in(base_key, t_prompt),
                               temperature, top_k).astype(jnp.int32)
    else:
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    seq = jax.lax.dynamic_update_slice(seq, first[:, None], (0, t_prompt))
    # Invariant: seq[:, :n] are decided tokens; cache holds K/V for
    # seq[:, :n-1] (the newest token is fed to the next forward).
    n0 = jnp.int32(t_prompt + 1)

    idx = jnp.arange(S_buf)

    def propose(seq, n):
        """Latest j <= n-2 with seq[j-1:j+1] == seq[n-2:n] → guess
        seq[j+1 : j+1+gamma]; garbage guesses when no match (they are
        simply rejected by the verify)."""
        last2 = jax.lax.dynamic_slice(seq, (0, n - 2), (1, 2))[0]
        prev = jnp.roll(seq[0], 1)
        hit = (prev == last2[0]) & (seq[0] == last2[1])
        valid = (idx >= 1) & (idx <= n - 2)
        j = jnp.max(jnp.where(hit & valid, idx, -1))
        return jax.lax.dynamic_slice(seq, (0, jnp.maximum(j, 0) + 1),
                                     (1, gamma))

    def body(carry):
        seq, n, cache = carry
        prop = propose(seq, n)
        last = jax.lax.dynamic_slice(seq, (0, n - 1), (1, 1))
        x = jnp.concatenate([last, prop], axis=1)    # [1, 1+gamma]
        logits, cache = forward_with_cache(params, x, cfg, cache,
                                           verify=True)
        if sampled:
            # Delta-q rejection against the temperature/top-k target law
            # — the B=1 mirror of _verify_chunk_paged_fn's sampling
            # branch, keyed by the decided-token count n (replay-stable:
            # the same seed and submissions re-draw the same uniforms).
            adj = logits[0].astype(jnp.float32) / temperature
            if top_k > 0:
                kth = jax.lax.top_k(adj, top_k)[0][..., -1:]
                adj = jnp.where(adj < kth, _NEG_INF, adj)
            p = jax.nn.softmax(adj, axis=-1)         # [1+gamma, V]
            kn = jax.random.fold_in(base_key, n)
            u = jax.random.uniform(jax.random.fold_in(kn, 0), (gamma,))
            p_prop = jnp.take_along_axis(
                p[:gamma], prop[0][:, None], axis=-1)[:, 0]
            accept = jnp.cumprod(
                (u < p_prop).astype(jnp.int32)).sum()
            p_at = p[accept]
            rej = prop[0][jnp.minimum(accept, gamma - 1)]
            resid = p_at * (1.0 - jax.nn.one_hot(rej, p.shape[-1],
                                                 dtype=p_at.dtype))
            dist = jnp.where(accept >= gamma, p_at, resid)
            corr = jax.random.categorical(
                jax.random.fold_in(kn, 1),
                jnp.log(dist + 1e-20)).astype(jnp.int32)
            prop_pad = jnp.concatenate([prop[0], prop[0][-1:]])
            toks = jnp.where(jnp.arange(1 + gamma) == accept,
                             corr, prop_pad)
        else:
            greedy = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
            accept = jnp.cumprod(
                (prop[0] == greedy[:-1]).astype(jnp.int32)).sum()
            toks = greedy
        # Emit the accepted guesses plus the continuation at the first
        # miss: exactly toks[0..accept] — a fixed-width write of the
        # whole vector, advancing n by only accept+1, keeps shapes
        # static (rows past n+accept are scratch, overwritten before
        # ever being read).
        seq = jax.lax.dynamic_update_slice(seq, toks[None, :], (0, n))
        # Rewind: keep K/V only for the accepted prefix. Stale rows in
        # (n+accept-1, n+gamma-1] fall inside the next verify's write
        # window starting at the rewound len.
        cache = {**cache, "len": n - 1 + 1 + accept}
        return seq, n + accept + 1, cache

    def cond(carry):
        _, n, _ = carry
        return n - t_prompt < max_new

    seq, n, _ = jax.lax.while_loop(cond, body, (seq, n0, cache))
    out = jax.lax.dynamic_slice(seq, (0, t_prompt), (1, max_new))
    return out.astype(prompt.dtype)                  # match generate's contract


def make_speculative_server_step(cfg: LlamaConfig, max_new: int,
                                 gamma: int = 4,
                                 max_len: Optional[int] = None,
                                 temperature: float = 0.0,
                                 top_k: int = 0, seed: int = 0):
    """Jitted handler: (params, prompt [1, Tp]) → [1, max_new] — the
    make_server_step analog for the speculative path (one compiled program
    per prompt length; eager calls would pay per-op dispatch under the
    ~100 ms tunnel round trip)."""
    fn = partial(generate_speculative, cfg=cfg, max_new=max_new,
                 gamma=gamma, max_len=max_len, temperature=temperature,
                 top_k=top_k, seed=seed)
    return jax.jit(fn)


# -- continuous batching ------------------------------------------------------
#
# The static-batch path above decodes one request batch to completion: a
# finished request's slot idles until the WHOLE batch drains, and a new
# request waits for the next batch — the waste continuous batching removes
# (Orca/vLLM's insight, rebuilt TPU-style: static shapes, two compiled
# programs, slot admission between decode chunks).
#
# The cache write position is ONE SHARED SCALAR CURSOR, not a per-slot
# vector: per-slot write positions require either a batched scatter (XLA
# lowers it to a serialized loop on TPU — measured 32 ms/token at d1024/L4)
# or a masked full-cache rewrite whose read-after-write blocks the layout
# hoisting the attention einsum relies on (measured 16 ms/token). With a
# scalar cursor the write is the same dynamic_update_slice the static path
# uses (2.4 ms/token — 6-13x faster). Slots at different request offsets
# are reconciled by two per-slot vectors instead: ``rope_pos`` (the slot's
# request-relative position, driving rotary embedding) and a [B, S]
# validity BITMAP that masks attention to exactly the rows each slot has
# actually written. Admission writes the prompt BACKWARD from the cursor
# (rows cursor-P..cursor-1 of the freed slot — stale rows of a finished
# request, invisible to everyone else), so admissions do not advance the
# shared cursor; only decode steps do. When the cursor nears S and all
# slots drain, the engine resets cursor+bitmap (epoch roll) — the
# steady-state cost is one idle boundary per ~S decode steps.


def _kv_quant(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-token-per-head symmetric int8 for K/V rows: x [..., hd] →
    (int8 [..., hd], f32 scale [..., 1]). Dynamic (each written row gets its
    own scale), so no calibration pass and no outlier clipping across
    tokens; the scale plane adds 4/hd bytes per element — ~3% at hd 128 —
    so cache HBM traffic drops to ~0.53× of bf16. Decode is bound by
    exactly that traffic once weights are int8 (VERDICT r4 weak #3: the
    bf16 cache was the residual traffic the 1.36× weight-only gain left on
    the table). Halved bytes also double slot-count (or max_len) at fixed
    HBM."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


def _sample_tokens(logits, key, temperature: float, top_k: int):
    """Next-token choice from [..., vocab] logits: greedy argmax when
    temperature == 0 (both are compile-time constants), else temperature/
    top-k categorical sampling — each batch row draws independently from
    the one key."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    return jax.random.categorical(key, logits)


def _decode_chunk_fn(params, cfg: LlamaConfig, chunk: int,
                     mesh: Optional[Mesh], k, v, bitmap, cursor, rope_pos,
                     last, active, seed, temperature: float = 0.0,
                     top_k: int = 0, k_s=None, v_s=None):
    """Advance every active slot ``chunk`` tokens; inactive slots carry
    through (their cache row at the cursor is written with garbage but
    never marked valid). Returns the emitted tokens [B, chunk]. ``seed``
    (traced) is the engine's dispatch counter — sampling keys derive from
    it on device, so no PRNG state rides the tunnel.

    ``k_s``/``v_s`` non-None = int8 KV cache mode: k/v are int8 and the
    scale planes [L, B, S, Hkv, 1] ride along — rows quantize at the write
    (_kv_quant) and dequantize at the attention read (the int8→dtype
    convert+multiply fuses into the einsum's cache read, like qdot's
    weight dequant). A trace-time branch, so the bf16 path compiles
    byte-identical to before."""
    quant = k_s is not None
    B = last.shape[0]
    S = k.shape[2]
    # Fused Pallas decode kernel (ops/decode_attention.py) when the config
    # asks for it, the cache is unsharded (pallas_call does not partition
    # under GSPMD; the PAGED engine is the sharded fused path) and the
    # blocking covers S; else the grouped dense reference — EITHER way no
    # _repeat_kv materialization. A downgrade is counted + warned.
    fused = (getattr(cfg, "decode_attn", "dense") == "fused"
             and mesh is None and decode_plan(S) is not None)
    if getattr(cfg, "decode_attn", "dense") == "fused" and not fused:
        _note_decode_fallback(
            "mesh_contiguous" if mesh is not None else "no_contiguous_plan")
    angles_full = rope_freqs(cfg.head_dim, S, cfg.rope_theta)
    col = jnp.arange(S)[None, :]
    base_key = jax.random.fold_in(jax.random.PRNGKey(0), seed)

    def one_token(carry, tick):
        k, v, k_s, v_s, bitmap, cursor, rope_pos, last = carry
        # Mark the row being written valid for active slots BEFORE
        # attention — the new token attends itself.
        bitmap = bitmap | ((col == cursor) & active[:, None])
        x = params["embed"][last[:, None]].astype(cfg.dtype)   # [B, 1, D]
        angles = angles_full[rope_pos][:, None, :]             # [B, 1, hd/2]

        def block(x, layer):
            blk, k_cache, v_cache, ks_c, vs_c = layer          # [B,S,Hkv,hd]
            h = rms_norm(x, blk["attn_norm"])
            q = qdot(h, blk["wq"]).reshape(B, 1, cfg.n_heads, cfg.head_dim)
            kk = qdot(h, blk["wk"]).reshape(B, 1, cfg.n_kv_heads, cfg.head_dim)
            vv = qdot(h, blk["wv"]).reshape(B, 1, cfg.n_kv_heads, cfg.head_dim)
            q, kk = apply_rope(q, angles), apply_rope(kk, angles)
            if quant:
                kq, ksn = _kv_quant(kk)
                vq, vsn = _kv_quant(vv)
                k_cache = jax.lax.dynamic_update_slice_in_dim(
                    k_cache, kq, cursor, axis=1)
                v_cache = jax.lax.dynamic_update_slice_in_dim(
                    v_cache, vq, cursor, axis=1)
                ks_c = jax.lax.dynamic_update_slice_in_dim(
                    ks_c, ksn, cursor, axis=1)
                vs_c = jax.lax.dynamic_update_slice_in_dim(
                    vs_c, vsn, cursor, axis=1)
            else:
                k_cache = jax.lax.dynamic_update_slice_in_dim(
                    k_cache, kk, cursor, axis=1)
                v_cache = jax.lax.dynamic_update_slice_in_dim(
                    v_cache, vv, cursor, axis=1)
            scales = dict(k_scale=ks_c, v_scale=vs_c) if quant else {}
            if fused:
                # Streamed-block kernel: the cursor bounds every valid bit
                # (the row written above is at `cursor`), so blocks past
                # cursor+1 are skipped — O(filled rows), not O(S); the
                # bitmap still masks exactly per slot inside the window.
                attn = flash_decode_attention(
                    q[:, 0], k_cache, v_cache, cursor + 1, bitmap=bitmap,
                    **scales)
            else:
                # Grouped dense reference: per-row scales factor onto
                # scores/probs ([B,Hkv,g,S] work instead of [B,S,H,hd] —
                # a head_dim-fold cut in dequant VPU time), and the int8→
                # dtype convert fuses into the einsum's cache read, so HBM
                # traffic stays int8.
                attn = dense_decode_reference(
                    q[:, 0], k_cache, v_cache, bitmap=bitmap, **scales)
            x = x + qdot(attn.reshape(B, 1, cfg.n_heads * cfg.head_dim),
                         blk["wo"])
            x, _ = mlp_sublayer(cfg, x, blk, dropless=True)
            return x, (k_cache, v_cache, ks_c, vs_c)

        x, (k, v, k_s, v_s) = jax.lax.scan(
            block, x, (params["blocks"], k, v, k_s, v_s))
        k = _constrain(k, mesh, CACHE_SPEC)
        v = _constrain(v, mesh, CACHE_SPEC)
        if quant:
            k_s = _constrain(k_s, mesh, CACHE_SPEC)
            v_s = _constrain(v_s, mesh, CACHE_SPEC)
        x = rms_norm(x, params["final_norm"])
        logits = qdot(x[:, 0], params["lm_head"]).astype(jnp.float32)
        nxt = _sample_tokens(
            logits, jax.random.fold_in(base_key, tick), temperature, top_k
        ).astype(last.dtype)
        emitted = jnp.where(active, nxt, -1)
        last = jnp.where(active, nxt, last)
        rope_pos = rope_pos + active.astype(rope_pos.dtype)
        return (k, v, k_s, v_s, bitmap, cursor + 1, rope_pos, last), emitted

    (k, v, k_s, v_s, bitmap, cursor, rope_pos, last), toks = jax.lax.scan(
        one_token, (k, v, k_s, v_s, bitmap, cursor, rope_pos, last),
        jnp.arange(chunk))
    return k, v, k_s, v_s, bitmap, cursor, rope_pos, last, jnp.swapaxes(
        toks, 0, 1)


def _prefill_multi_fn(params, cfg: LlamaConfig, mesh: Optional[Mesh],
                      k, v, bitmap, rope_pos, last, slots, cursors, tokens,
                      real_lens, seed, temperature: float = 0.0,
                      top_k: int = 0, k_s=None, v_s=None):
    """Prefill M freed slots from right-padded prompts [M, tb] in ONE
    dispatch: compute every prompt's K/V in a self-contained batched mini
    cache (rope from 0), then write each entry's tb rows into its slot's
    row window ending at its cursor (rows cursor-real_len ..
    cursor-real_len+tb-1). Only the real_len prompt rows are marked valid;
    the padded tail lands ahead of the cursor and is overwritten by the
    slot's own decode steps before it could ever be attended.

    M is static — the host pads the admission list to a fixed M by
    REPEATING its last entry, so exactly one program compiles and a step
    admitting 1 or n_slots requests costs the same single dispatch (the
    round-2/3 one-dispatch-per-slot shape spent one tunnel round trip per
    admission — the dominant term of the serving bench). A duplicated
    entry re-writes byte-identical rows and re-applies the same bitmap/
    rope_pos/last updates, so padding is idempotent on device state; the
    host simply ignores the duplicate first-tokens.

    The host guarantees, per entry: cursor >= real_len and
    cursor - real_len + tb <= S (dynamic_update_slice clamps silently
    otherwise).

    ``k_s``/``v_s`` non-None = int8 KV cache mode (see _decode_chunk_fn):
    the prompt's K/V compute in the bf16 mini cache as usual, then quantize
    ONCE on the way into the slot windows — prefill math is untouched, only
    the persistent cache stores int8."""
    quant = k_s is not None
    B = last.shape[0]
    S = k.shape[2]
    M, tb = tokens.shape
    mini = {
        "k": jnp.zeros((cfg.n_layers, M, tb, cfg.n_kv_heads, cfg.head_dim),
                       cfg.dtype),
        "v": jnp.zeros((cfg.n_layers, M, tb, cfg.n_kv_heads, cfg.head_dim),
                       cfg.dtype),
        "len": jnp.zeros((), jnp.int32),
    }
    logits, mini = forward_with_cache(params, tokens, cfg, mini, mesh=None)
    if quant:
        mini_kq, mini_ks = _kv_quant(mini["k"])
        mini_vq, mini_vs = _kv_quant(mini["v"])
    col = jnp.arange(S)
    row_ids = jnp.arange(B)
    base_key = jax.random.fold_in(jax.random.PRNGKey(1), seed)
    firsts = []
    for i in range(M):                               # static unroll
        slot, cursor, real_len = slots[i], cursors[i], real_lens[i]
        start = cursor - real_len
        if quant:
            k = jax.lax.dynamic_update_slice(
                k, mini_kq[:, i:i + 1], (0, slot, start, 0, 0))
            v = jax.lax.dynamic_update_slice(
                v, mini_vq[:, i:i + 1], (0, slot, start, 0, 0))
            k_s = jax.lax.dynamic_update_slice(
                k_s, mini_ks[:, i:i + 1], (0, slot, start, 0, 0))
            v_s = jax.lax.dynamic_update_slice(
                v_s, mini_vs[:, i:i + 1], (0, slot, start, 0, 0))
        else:
            k = jax.lax.dynamic_update_slice(
                k, mini["k"][:, i:i + 1], (0, slot, start, 0, 0))
            v = jax.lax.dynamic_update_slice(
                v, mini["v"][:, i:i + 1], (0, slot, start, 0, 0))
        is_slot = (row_ids == slot)[:, None]
        rows = (col >= start) & (col < cursor)
        bitmap = jnp.where(is_slot, rows[None, :], bitmap)
        # Key by SLOT, not loop index: pad rows duplicate a real entry and
        # must re-draw the SAME token, or the duplicate's write would
        # overwrite `last` with a different sample (argmax never cared).
        first = _sample_tokens(
            logits[i, real_len - 1], jax.random.fold_in(base_key, slot),
            temperature, top_k,
        ).astype(last.dtype)
        rope_pos = jnp.where(is_slot[:, 0], real_len, rope_pos)
        last = jnp.where(is_slot[:, 0], first, last)
        firsts.append(first)
    k = _constrain(k, mesh, CACHE_SPEC)
    v = _constrain(v, mesh, CACHE_SPEC)
    if quant:
        k_s = _constrain(k_s, mesh, CACHE_SPEC)
        v_s = _constrain(v_s, mesh, CACHE_SPEC)
    return k, v, k_s, v_s, bitmap, rope_pos, last, jnp.stack(firsts)


# -- paged KV cache -----------------------------------------------------------
#
# The contiguous engine above reconciles per-slot positions against ONE
# shared cursor — which costs a hard contiguity constraint (admission needs
# a whole window below S) and an epoch roll that idles the entire batch
# every ~S decode steps. The paged engine removes both, vLLM-style: K/V
# live in a pool of fixed-size pages [L, n_pages, ps, Hkv, hd]; each slot
# names its pages through a [n_slots, n_blocks] block table; logical row r
# of a slot lives at (table[slot, r // ps], r % ps). Admission takes pages
# wherever they are free (PageAllocator — worst-case reservation, so no
# mid-decode stalls), finished requests free them immediately, and the
# per-slot length vector replaces cursor+bitmap+rope_pos in one: lens IS
# the rope position, the attention bound, and the write address. The block
# table rides into the fused kernel as a scalar-prefetch operand
# (ops.paged_decode_attention), so decode keeps the O(pos) block-streamed
# reads; the pool and table are donated every dispatch, preserving the
# recompile guard's zero-retrace/zero-copy steady state (tables vary in
# CONTENT across chunks, never in shape).
#
# The decode write is a B-row scatter (each slot targets its own page/
# offset) instead of the cursor's single dynamic_update_slice — the price
# of per-slot positions; it is B rows of [Hkv, hd], not the full-cache
# masked rewrite that motivated the cursor design. Inactive slots redirect
# their write to the reserved null page (paging.NULL_PAGE), whose contents
# are garbage by contract and only ever read under a mask.


def _tp_heads(x, tp_axis: str, n_local: int, axis: int):
    """This shard's contiguous head block of a full-head projection: the
    q heads of kv head h are the contiguous group h·g..h·g+g-1, so a
    contiguous slice of H/tp q heads (or Hkv/tp kv heads) is exactly the
    head family this shard's pool slice serves, for q and kv alike."""
    return jax.lax.dynamic_slice_in_dim(
        x, jax.lax.axis_index(tp_axis) * n_local, n_local, axis)


# -- Megatron-sliced weights (weight_sharding=True) ---------------------------
#
# PR 12's islands kept every weight matrix REPLICATED: each chip computed
# the FULL q/k/v/o and MLP projections and then sliced out its local head
# family (_tp_heads), so per-chip HBM weight bytes and projection FLOPs
# didn't scale with tp at all. With weight sharding the params pytree
# itself rides the island sliced per parallel/sharding.py's WEIGHT_SPECS
# (models/llama.py serving_weight_specs): column-parallel q/k/v/gate/up
# slices [d, N/tp] compute each shard's contiguous head/ffn family
# DIRECTLY (a matmul's output columns are independent — the slice is
# byte-identical to slicing the full product, no combine needed), and
# row-parallel o/down slices [K/tp, d] contract the shard's 1/tp input
# slice with ONE combine per projection:
#
# - combine="all_gather" (default): all_gather the activation AND the
#   weight slice, then run the full matmul — data movement only, the
#   arithmetic is the monolithic dot, so sharded streams stay
#   byte-identical to replicated-weight and tp=1 runs (the PR 12
#   identity contract, preserved);
# - combine="psum": contract locally and psum the partial products —
#   1/tp the FLOPs and no weight bytes on the wire, but the reduction
#   ORDER differs from the monolithic dot, so this mode is
#   tolerance-checked rather than byte-pinned.
#
# Weight-only int8 leaves ({"q","s"}, ops/quant.py) slice AFTER
# quantization: the per-output-channel scale spans the full contraction
# dim, so a column slice takes its scale columns and a row slice keeps
# the scale whole — every shard's dequant is exact either way.


def _map_weight_tree(params, specs, fn):
    """Walk a params pytree and its mirror-shaped spec tree together
    (plain nested dicts with array — or int8 ``{"q","s"}`` — leaves;
    the shape serving_weight_specs emits). jax.tree.map is avoided on
    purpose: PartitionSpec is itself a sequence and tree-flattening it
    against array leaves is version-dependent."""
    if isinstance(params, dict):
        return {k: _map_weight_tree(params[k], specs[k], fn)
                for k in params}
    return fn(params, specs)


def _gather_weight(w, tp_axis: str, axis: int = 0):
    """All-gather a row-parallel weight slice back to the full matrix
    (movement-only — tiled concat in shard order matches the unsliced
    layout). int8 leaves gather ``q``; the per-output-channel scale is
    replicated for row slices and multiplies after the full dot."""
    if isinstance(w, dict):
        return {"q": jax.lax.all_gather(w["q"], tp_axis, axis=axis,
                                        tiled=True),
                "s": w["s"]}
    return jax.lax.all_gather(w, tp_axis, axis=axis, tiled=True)


def _psum_qdot(x, w, tp_axis: str):
    """Row-parallel qdot, psum combine: each shard contracts its 1/tp
    input slice and the partial products accumulate in f32 across the
    island. The per-output-channel int8 scale applies AFTER the psum —
    it is constant across shards, so scale(psum) == psum(scale) exactly
    in real arithmetic; the float reduction order still differs from the
    monolithic dot, hence tolerance-checked."""
    if isinstance(w, dict):
        y = x @ w["q"].astype(x.dtype)
        y = jax.lax.psum(y.astype(jnp.float32), tp_axis)
        return (y * w["s"]).astype(x.dtype)
    return jax.lax.psum((x @ w).astype(jnp.float32),
                        tp_axis).astype(x.dtype)


def _qkv_local(cfg: LlamaConfig, h, blk, angles, lead, tp_axis,
               tp: int, wsharded: bool):
    """Roped q/k/v for this shard's head family — THE one projection
    block every island body shares (decode tick, verify window, both
    prefill tail branches; ``lead`` is the (batch, rows) shape prefix).
    Weight-sharded islands compute the local family DIRECTLY from the
    Megatron column slices (byte-exact — output columns are
    independent); legacy islands compute the full projections from
    replicated weights and slice (_tp_heads — rope is per-head
    elementwise, so rope-then-slice equals slice-then-rope and both
    layouts produce identical bytes per family)."""
    hd = cfg.head_dim
    if wsharded:
        q = qdot(h, blk["wq"]).reshape(*lead, cfg.n_heads // tp, hd)
        kk = qdot(h, blk["wk"]).reshape(*lead, cfg.n_kv_heads // tp, hd)
        vv = qdot(h, blk["wv"]).reshape(*lead, cfg.n_kv_heads // tp, hd)
        return apply_rope(q, angles), apply_rope(kk, angles), vv
    q = qdot(h, blk["wq"]).reshape(*lead, cfg.n_heads, hd)
    kk = qdot(h, blk["wk"]).reshape(*lead, cfg.n_kv_heads, hd)
    vv = qdot(h, blk["wv"]).reshape(*lead, cfg.n_kv_heads, hd)
    q, kk = apply_rope(q, angles), apply_rope(kk, angles)
    if tp_axis is not None:
        ax = len(lead)
        q = _tp_heads(q, tp_axis, cfg.n_heads // tp, ax)
        kk = _tp_heads(kk, tp_axis, cfg.n_kv_heads // tp, ax)
        vv = _tp_heads(vv, tp_axis, cfg.n_kv_heads // tp, ax)
    return q, kk, vv


def _attn_residual(x, attn, wo, lead, gather_axis: int, tp_axis,
                   wsharded: bool, combine: str):
    """Residual + output projection with the island head combine. attn
    is the shard's LOCAL head-family output ([..., Hloc(, g), hd]);
    legacy replicated-weight islands all_gather it and multiply the full
    wo (PR 12, byte-identical), weight-sharded islands combine per the
    module comment above. Off-island this is exactly the unsharded
    epilogue."""
    if tp_axis is not None and (not wsharded or combine == "all_gather"):
        # Exact head-axis reassembly (movement only — each q head's
        # whole kv group is shard-local, so no cross-shard arithmetic).
        attn = jax.lax.all_gather(attn, tp_axis, axis=gather_axis,
                                  tiled=True)
    flat = attn.reshape(*lead, -1)
    if tp_axis is None or not wsharded:
        return x + qdot(flat, wo)
    if combine == "all_gather":
        return x + qdot(flat, _gather_weight(wo, tp_axis))
    return x + _psum_qdot(flat, wo, tp_axis)


def _mlp_residual(cfg: LlamaConfig, x, blk, tp_axis, wsharded: bool,
                  combine: str):
    """MLP half of a serving block: the shared ``mlp_sublayer`` off the
    island / with replicated weights, the Megatron-sliced dense SwiGLU
    inside a weight-sharded island — gate/up column slices compute the
    shard's ffn family directly (exact), down combines per the module
    comment (all_gather = byte-identical, psum = one reduction)."""
    if tp_axis is None or not wsharded:
        x, _ = mlp_sublayer(cfg, x, blk, dropless=True)
        return x
    h = rms_norm(x, blk["mlp_norm"])
    act = jax.nn.silu(qdot(h, blk["w_gate"])) * qdot(h, blk["w_up"])
    if combine == "all_gather":
        act = jax.lax.all_gather(act, tp_axis, axis=act.ndim - 1,
                                 tiled=True)
        return x + qdot(act, _gather_weight(blk["w_down"], tp_axis))
    return x + _psum_qdot(act, blk["w_down"], tp_axis)


def _decode_chunk_paged_fn(params, cfg: LlamaConfig, chunk: int,
                           page_size: int, k, v, table, lens, last, active,
                           seed, temperature: float = 0.0, top_k: int = 0,
                           k_s=None, v_s=None, tp_axis=None, tp: int = 1,
                           wsharded: bool = False,
                           combine: str = "all_gather"):
    """Advance every active slot ``chunk`` tokens against the paged pool
    k/v [L, n_pages, ps, Hkv, hd] with block table [B, n_blocks] and
    per-slot filled lengths [B]. The table is read-only here (pages are
    reserved at admission) and returned as-is so the jit donation aliases
    it through; ``lens`` advances per active slot per tick and is the rope
    position, the write address, and the attention length bound at once —
    the cursor/bitmap/rope_pos triple of the contiguous engine collapsed
    into one vector.

    ``tp_axis`` non-None = MULTI-CHIP island mode: this body runs inside
    a ``shard_map`` over that mesh axis with the pool (and scale planes)
    sharded on the kv-heads dim ([L, n_pages, ps, Hkv/tp, hd] per shard)
    and every other operand replicated. Each shard computes the FULL
    q/k/v projections from the replicated weights (identical on every
    chip), slices its own contiguous head family (_tp_heads), writes its
    kv-head slice into its pool shard, and runs the UNCHANGED kernel
    body on local shapes; the per-head attention outputs are then
    ``all_gather``ed back to the full head set — an exact (movement-only,
    no-arithmetic) combine, so the sharded stream is byte-identical to
    the unsharded one — and the residual/mlp/logit tail proceeds
    replicated. The decode step's dominant cost — the O(pos) pool read —
    is what shards 1/tp; per-chip pool residency shards with it."""
    quant = k_s is not None
    B = last.shape[0]
    n_blocks = table.shape[1]
    S = n_blocks * page_size
    fused = (getattr(cfg, "decode_attn", "dense") == "fused"
             and cfg.n_heads % cfg.n_kv_heads == 0
             and paged_plan(n_blocks, page_size) is not None)
    if getattr(cfg, "decode_attn", "dense") == "fused" and not fused:
        _note_decode_fallback("no_paged_plan")
    angles_full = rope_freqs(cfg.head_dim, S, cfg.rope_theta)
    row_ids = jnp.arange(B)
    base_key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
    active_i = jnp.asarray(active)

    def one_token(carry, tick):
        k, v, k_s, v_s, lens, last = carry
        x = params["embed"][last[:, None]].astype(cfg.dtype)   # [B, 1, D]
        angles = angles_full[lens][:, None, :]                 # [B, 1, hd/2]
        # Physical address of the row being written: active slots append at
        # logical row `lens`; inactive slots are redirected to the null
        # page (their stale lens may even sit at capacity — the table
        # gather clamps, the write lands in garbage-by-contract rows).
        pg = table[row_ids, jnp.minimum(lens // page_size, n_blocks - 1)]
        off = lens % page_size
        pg_w = jnp.where(active_i, pg, NULL_PAGE)
        off_w = jnp.where(active_i, off, 0)

        def block(x, layer):
            blk, k_pg, v_pg, ks_p, vs_p = layer      # [n_pages, ps, Hkv, hd]
            h = rms_norm(x, blk["attn_norm"])
            # Local head family — sliced weights or legacy full+slice
            # (_qkv_local); the kernel below sees exactly the
            # per-shard pool shapes either way.
            q, kk, vv = _qkv_local(cfg, h, blk, angles, (B, 1),
                                   tp_axis, tp, wsharded)
            if quant:
                kq, ksn = _kv_quant(kk)
                vq, vsn = _kv_quant(vv)
                k_pg = k_pg.at[pg_w, off_w].set(kq[:, 0])
                v_pg = v_pg.at[pg_w, off_w].set(vq[:, 0])
                ks_p = ks_p.at[pg_w, off_w].set(ksn[:, 0])
                vs_p = vs_p.at[pg_w, off_w].set(vsn[:, 0])
            else:
                k_pg = k_pg.at[pg_w, off_w].set(kk[:, 0])
                v_pg = v_pg.at[pg_w, off_w].set(vv[:, 0])
            scales = dict(k_scale=ks_p, v_scale=vs_p) if quant else {}
            if fused:
                # Table-indirected streamed kernel: logical blocks past
                # ceil((lens+1)/ps) are skipped, so the step costs O(pos)
                # pool traffic regardless of where the pages physically
                # sit.
                attn = paged_decode_attention(
                    q[:, 0], k_pg, v_pg, table, lens + 1, **scales)
            else:
                # Dense fallback: materialize the sequence-contiguous view
                # through the table and reuse the grouped reference — the
                # same O(allocated S) read the contiguous dense path pays.
                dsc = {}
                if quant:
                    dsc = dict(k_scale=gather_paged_kv(ks_p, table),
                               v_scale=gather_paged_kv(vs_p, table))
                attn = dense_decode_reference(
                    q[:, 0], gather_paged_kv(k_pg, table),
                    gather_paged_kv(v_pg, table), lengths=lens + 1, **dsc)
            x = _attn_residual(x, attn, blk["wo"], (B, 1), 1, tp_axis,
                               wsharded, combine)
            x = _mlp_residual(cfg, x, blk, tp_axis, wsharded, combine)
            return x, (k_pg, v_pg, ks_p, vs_p)

        x, (k, v, k_s, v_s) = jax.lax.scan(
            block, x, (params["blocks"], k, v, k_s, v_s))
        x = rms_norm(x, params["final_norm"])
        logits = qdot(x[:, 0], params["lm_head"]).astype(jnp.float32)
        nxt = _sample_tokens(
            logits, jax.random.fold_in(base_key, tick), temperature, top_k
        ).astype(last.dtype)
        emitted = jnp.where(active_i, nxt, -1)
        last = jnp.where(active_i, nxt, last)
        lens = lens + active_i.astype(lens.dtype)
        return (k, v, k_s, v_s, lens, last), emitted

    (k, v, k_s, v_s, lens, last), toks = jax.lax.scan(
        one_token, (k, v, k_s, v_s, lens, last), jnp.arange(chunk))
    return k, v, k_s, v_s, table, lens, last, jnp.swapaxes(toks, 0, 1)


def _verify_chunk_paged_fn(params, cfg: LlamaConfig, gamma: int,
                           page_size: int, k, v, table, lens, last, props,
                           active, seed=0, eff=None, q=None,
                           temperature: float = 0.0, top_k: int = 0,
                           k_s=None, v_s=None, tp_axis=None,
                           tp: int = 1, wsharded: bool = False,
                           combine: str = "all_gather"):
    """One batched speculative VERIFY dispatch over every slot of the
    paged pool: score the t = 1+gamma window [last, props...] of each
    active slot in a single forward, accept the longest valid proposal
    prefix, and commit exactly the accepted tokens plus one model token
    — the multi-slot analog of generate_speculative's loop body, with
    pages as the rewind unit.

    The accept rule branches AT TRACE TIME on ``temperature`` (a Python
    constant, like every sampling knob in this engine):

    - ``temperature == 0`` — exact-match: the longest proposal prefix
      agreeing with the verify pass's own greedy argmax, byte-identical
      to the pre-sampling speculative path (no PRNG touches the trace).
    - ``temperature > 0`` — SPECULATIVE-SAMPLING REJECTION (Leviathan
      et al. 2023; Chen et al. 2023): per-row target distributions p_i
      come from the verify logits through the ``_sample_tokens``
      temperature/top-k machinery; per-slot keys fold from ``seed``
      (the dispatch counter — no PRNG state crosses the tunnel).
      Proposal i accepts with prob ``min(1, p_i[prop_i]/q_i[prop_i])``
      — ``q`` None means a DETERMINISTIC proposer, the q = delta(prop)
      special case where the accept prob collapses to ``p_i[prop_i]``.
      On the first rejection the committed continuation resamples from
      the renormalized residual ``max(0, p - q)`` (delta-q: p with the
      proposed token zeroed); on full acceptance it samples the BONUS
      token from p at the position past the window. Emitted tokens are
      therefore distributed exactly as the target sampler's — the
      tokens-per-dispatch multiplier with no distribution drift.

    ``eff`` [B] (None = the full gamma) is the per-slot EFFECTIVE
    window: proposal rows at positions >= eff are masked out of
    acceptance (never accepted, their writes rewound like any
    rejection), which is how adaptive per-slot gamma keeps the dispatch
    shape static at 1+gamma while low-accept slots stop paying for —
    and stop reserving — overshoot they never land.

    The window's K/V rows scatter at logical rows lens..lens+gamma of
    each slot BEFORE attention (the same write-then-attend order as the
    decode step, t rows at once); attention is the multi-query kernel
    (ops.paged_verify_attention — per-row causal bound lens+i+1) or the
    gathered dense verify reference. ``lens`` then advances by the TRACED
    commit length accept+1 only: the up-to-gamma rejected overshoot rows
    sit above the new lens — inside the slot's own reserved pages, since
    admission reserves the overshoot window too (_rows_needed) — masked
    by every later read until the next verify window overwrites them
    (new window = rows lens'..lens'+gamma ⊇ the stale extent). That lens
    clamp IS the rewind: no page moves, no shared prefix page is ever
    touched (writes land at rows >= lens >= hit_len — the copy-on-write
    argument of the decode scatter, verbatim, enforced by the graftcheck
    alias scenario).

    Inactive slots redirect their window writes to the null page and
    carry lens/last through. Returns the donated pool/scale/table chain
    plus per-slot ``emitted`` [B, 1+gamma] (-1 past the commit length
    and for inactive slots) and ``accepts`` [B] (the number of
    PROPOSALS accepted, 0..gamma).

    ``tp_axis`` non-None = shard_map island mode, exactly the decode
    chunk's contract (_decode_chunk_paged_fn): pool/scales sharded on kv
    heads, full projections sliced to this shard's head family, kernel
    body unchanged on local shapes, attention heads ``all_gather``ed back
    (exact combine — byte identity), accept/resample math replicated
    (per-slot keys fold from the replicated seed, so every shard draws
    the same uniforms)."""
    quant = k_s is not None
    B = last.shape[0]
    t = 1 + gamma
    n_blocks = table.shape[1]
    S = n_blocks * page_size
    fused = (getattr(cfg, "decode_attn", "dense") == "fused"
             and cfg.n_heads % cfg.n_kv_heads == 0
             and verify_plan(n_blocks, page_size, t) is not None)
    if getattr(cfg, "decode_attn", "dense") == "fused" and not fused:
        _note_decode_fallback("no_verify_plan")
    angles_full = rope_freqs(cfg.head_dim, S, cfg.rope_theta)
    row_ids = jnp.arange(B)
    active_i = jnp.asarray(active)
    window = jnp.concatenate(
        [last[:, None], jnp.asarray(props, last.dtype)], axis=1)  # [B, t]
    # Physical addresses of the window rows: active slots append at
    # logical rows lens..lens+gamma; inactive slots (stale lens, possibly
    # at capacity — gathers clamp) redirect to the null page.
    pos = lens[:, None] + jnp.arange(t, dtype=lens.dtype)[None, :]  # [B, t]
    pg = table[row_ids[:, None],
               jnp.minimum(pos // page_size, n_blocks - 1)]
    off = pos % page_size
    pg_w = jnp.where(active_i[:, None], pg, NULL_PAGE)
    off_w = jnp.where(active_i[:, None], off, 0)
    angles = angles_full[jnp.minimum(pos, S - 1)]        # [B, t, hd/2]
    x = params["embed"][window].astype(cfg.dtype)        # [B, t, D]

    def block(x, layer):
        blk, k_pg, v_pg, ks_p, vs_p = layer      # [n_pages, ps, Hkv, hd]
        h = rms_norm(x, blk["attn_norm"])
        # Local head family (see _qkv_local — same contract as the
        # decode tick, t window rows instead of one).
        q, kk, vv = _qkv_local(cfg, h, blk, angles, (B, t), tp_axis,
                               tp, wsharded)
        if quant:
            kq, ksn = _kv_quant(kk)
            vq, vsn = _kv_quant(vv)
            k_pg = k_pg.at[pg_w, off_w].set(kq)
            v_pg = v_pg.at[pg_w, off_w].set(vq)
            ks_p = ks_p.at[pg_w, off_w].set(ksn)
            vs_p = vs_p.at[pg_w, off_w].set(vsn)
        else:
            k_pg = k_pg.at[pg_w, off_w].set(kk)
            v_pg = v_pg.at[pg_w, off_w].set(vv)
        scales = dict(k_scale=ks_p, v_scale=vs_p) if quant else {}
        if fused:
            # Multi-query streamed kernel: per-row causal bound inside
            # the window, blocks past lens+t skipped — O(pos) traffic
            # for the whole window in one sweep of the cache.
            attn = paged_verify_attention(q, k_pg, v_pg, table, lens,
                                          **scales)
        else:
            dsc = {}
            if quant:
                dsc = dict(k_scale=gather_paged_kv(ks_p, table),
                           v_scale=gather_paged_kv(vs_p, table))
            attn = dense_verify_reference(
                q, gather_paged_kv(k_pg, table),
                gather_paged_kv(v_pg, table), lens, **dsc)
        x = _attn_residual(x, attn, blk["wo"], (B, t), 2, tp_axis,
                           wsharded, combine)
        x = _mlp_residual(cfg, x, blk, tp_axis, wsharded, combine)
        return x, (k_pg, v_pg, ks_p, vs_p)

    x, (k, v, k_s, v_s) = jax.lax.scan(
        block, x, (params["blocks"], k, v, k_s, v_s))
    x = rms_norm(x, params["final_norm"])
    logits = qdot(x, params["lm_head"]).astype(jnp.float32)  # [B, t, vocab]
    eff_i = (jnp.full((B,), gamma, jnp.int32) if eff is None
             else jnp.asarray(eff, jnp.int32))
    pos_ok = jnp.arange(gamma)[None, :] < eff_i[:, None]     # [B, gamma]
    if temperature <= 0.0:
        # Exact-match acceptance against the verify pass's own argmax —
        # generate_speculative's rule, vectorized over slots. With the
        # full effective window this is byte-identical to the
        # pre-sampling path (pos_ok is all-true and folds away).
        greedy = jnp.argmax(logits, axis=-1).astype(last.dtype)  # [B, t]
        hits = ((window[:, 1:] == greedy[:, :-1])
                & pos_ok).astype(jnp.int32)
        accepts = jnp.cumprod(hits, axis=1).sum(axis=1)      # [B] 0..gamma
        toks = greedy
    else:
        # Rejection sampling. Target distributions through the same
        # temperature/top-k shaping _sample_tokens applies, normalized:
        # p[:, i] is the sampler's next-token law after window[:, :i+1].
        adj = logits / temperature
        if top_k > 0:
            kth = jax.lax.top_k(adj, top_k)[0][..., -1:]
            adj = jnp.where(adj < kth, _NEG_INF, adj)
        p = jax.nn.softmax(adj, axis=-1)                     # [B, t, V]
        base_key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        keys = jax.vmap(
            lambda s: jax.random.fold_in(base_key, s))(row_ids)
        u = jax.vmap(lambda kk: jax.random.uniform(kk, (gamma,)))(
            jax.vmap(lambda kk: jax.random.fold_in(kk, 0))(keys))
        prop_t = window[:, 1:]                               # [B, gamma]
        p_prop = jnp.take_along_axis(
            p[:, :gamma], prop_t[..., None], axis=-1)[..., 0]
        if q is None:
            # Deterministic proposer: q = delta(prop), accept with
            # prob p itself.
            a_prob = p_prop
        else:
            q_prop = jnp.take_along_axis(
                jnp.asarray(q, jnp.float32), prop_t[..., None],
                axis=-1)[..., 0]
            a_prob = jnp.minimum(1.0, p_prop / jnp.maximum(q_prop, 1e-20))
        acc = (pos_ok & (u < a_prob)).astype(jnp.int32)
        accepts = jnp.cumprod(acc, axis=1).sum(axis=1)       # [B] 0..gamma
        # Continuation token at position `accepts`: the BONUS draw from
        # p itself on full acceptance (accepts == eff — including
        # eff == 0, where this is exactly plain sampled decode), else
        # the residual max(0, p - q) renormalized (delta-q: p with the
        # rejected proposal zeroed; categorical-over-log normalizes).
        p_at = jnp.take_along_axis(
            p, accepts[:, None, None], axis=1)[:, 0]         # [B, V]
        safe = jnp.minimum(accepts, gamma - 1)
        if q is None:
            rej = jnp.take_along_axis(prop_t, safe[:, None], axis=1)[:, 0]
            resid = p_at * (1.0 - jax.nn.one_hot(
                rej, p.shape[-1], dtype=p_at.dtype))
        else:
            q_at = jnp.take_along_axis(
                jnp.asarray(q, jnp.float32), safe[:, None, None],
                axis=1)[:, 0]
            resid = jnp.maximum(p_at - q_at, 0.0)
        full_acc = accepts >= eff_i
        corr_dist = jnp.where(full_acc[:, None], p_at, resid)
        corr = jax.vmap(jax.random.categorical)(
            jax.vmap(lambda kk: jax.random.fold_in(kk, 1))(keys),
            jnp.log(corr_dist + 1e-20)).astype(last.dtype)
        # Committed tokens: the accepted proposals verbatim, then the
        # resampled/bonus continuation at position `accepts`.
        idx_t = jnp.arange(t)[None, :]
        prop_pad = jnp.concatenate([prop_t, prop_t[:, -1:]], axis=1)
        toks = jnp.where(idx_t == accepts[:, None], corr[:, None],
                         prop_pad).astype(last.dtype)
    commit = jnp.arange(t)[None, :] <= accepts[:, None]      # [B, t]
    emitted = jnp.where(commit & active_i[:, None], toks,
                        jnp.full_like(toks, -1))
    new_last = jnp.take_along_axis(toks, accepts[:, None], axis=1)[:, 0]
    last = jnp.where(active_i, new_last, last)
    lens = lens + jnp.where(active_i, accepts + 1, 0).astype(lens.dtype)
    accepts = jnp.where(active_i, accepts, 0)
    return k, v, k_s, v_s, table, lens, last, emitted, accepts


def scatter_pool_pages(k, v, ks, vs, idx, kp, vp, ksp, vsp):
    """Pure page-relocation primitive: land host page payloads
    (``kp``/``vp`` [L, len(idx), ps, Hkv, hd], + int8 scale planes when
    the pool carries them) into pool pages ``idx`` — ONE scatter per
    plane, shared by the snapshot restore/absorb LUT move and the KV
    tier's promotion upload. graftcheck's traffic registry traces
    exactly this function (``traffic_promote_upload``): the payload is
    O(moved pages), the only pool-scale values are the update chain
    itself. ``ks``/``vs`` are None on f32 pools."""
    k = k.at[:, idx].set(jnp.asarray(kp, k.dtype))
    v = v.at[:, idx].set(jnp.asarray(vp, v.dtype))
    if ks is not None:
        ks = ks.at[:, idx].set(jnp.asarray(ksp, jnp.float32))
        vs = vs.at[:, idx].set(jnp.asarray(vsp, jnp.float32))
    return k, v, ks, vs


def _prefill_multi_paged_fn(params, cfg: LlamaConfig, page_size: int,
                            k, v, lens, last, slots, page_ids,
                            prefix_tables, hit_lens, tokens, tail_lens,
                            seed, temperature: float = 0.0,
                            top_k: int = 0, k_s=None, v_s=None,
                            tp_axis=None, tp: int = 1,
                            prefill_attn: str = "auto",
                            wsharded: bool = False,
                            combine: str = "all_gather"):
    """Prefill M freed slots from right-padded prompts [M, tb] in ONE
    dispatch, paged edition: the batched mini cache computes every
    prompt's K/V exactly as the contiguous path, then ONE page-granular
    scatter writes the [M, tb] rows into the pool at ``page_ids``
    [M, tb/ps] — each row of which the host fills with the entry's
    reserved pages, padding the beyond-need tail with the null page
    (bucket tb can overshoot the rows the request will ever own). Pad
    entries repeat a REAL entry, so duplicate page ids carry identical
    values and the scatter stays idempotent, mirroring the contiguous
    path's padding contract. Only ``tail_len`` logical rows become
    attendable (lens is set to hit_len + tail_len); the garbage the
    padded tail writes inside the last page sits above lens until the
    slot's own decode steps overwrite it.

    PREFIX-CACHE tail prefill: when ``prefix_tables`` [M, hb] is
    non-empty (hb > 0, a trace-time branch — the hb == 0 program is the
    plain path, unchanged), ``tokens`` holds only the UNCACHED TAIL of
    each prompt: the first ``hit_len`` rows of the slot already live in
    shared read-only pages (the radix prefix cache's match,
    models/prefix_cache.py), listed in ``prefix_tables`` (null-padded to
    the hb bucket). The tail's queries attend the gathered prefix K/V
    (dequantized from the pool in int8 mode — the SAME values decode
    reads) plus themselves causally at absolute positions hit_len..
    hit_len+tb-1, so prefill FLOPs and pool writes scale with the NOVEL
    suffix; the scatter targets only the entry's own pages — shared
    pages are never written (copy-on-write at page granularity, enforced
    by the graftcheck shared-page audit).

    Parity note: the cached prefix holds exactly the bytes this
    request's own prefill would have written (prefill KV of a prefix is
    a deterministic function of the prefix tokens), so in bf16/f32 mode
    the only cache-on/off divergence is float reduction order — the same
    noise class as dense-vs-fused, which the token-identity suites
    already absorb. In int8-KV mode there is one real numeric delta:
    these tail queries attend the DEQUANTIZED prefix (what decode also
    attends) where the cache-off full prefill attends its pre-
    quantization bf16 mini cache — greedy argmax only flips on a
    near-exact logit tie, and the parity tests pin it, but it is
    quantization-noise-bounded rather than structural.

    ``tp_axis`` non-None = shard_map island mode (the decode chunk's
    contract): the pool/scale scatter targets are per-shard kv-head
    slices, so the hb == 0 path computes the batched mini cache
    replicated (identical on every chip) and slices this shard's kv-head
    family at the scatter, while the hb > 0 tail attends the LOCAL
    prefix heads with the matching local q family and ``all_gather``s
    the head axis back before the output projection — exact combines
    throughout, so sharded prefill is byte-identical per shard slice."""
    quant = k_s is not None
    B = last.shape[0]
    M, tb = tokens.shape
    npg = page_ids.shape[1]
    hb = prefix_tables.shape[1]
    hkv_loc = cfg.n_kv_heads // tp
    if hb == 0 and not wsharded:
        # Plain path: tokens are whole prompts, nothing cached. Weight-
        # sharded islands cannot take it — forward_with_cache reshapes
        # to the FULL head set, which a 1/tp weight slice cannot feed —
        # so they route hb == 0 through the tail branch below with an
        # empty prefix (hp = 0): the same per-shard block walk, tail-
        # only causal attention, and the column slices shard the
        # prefill projections too.
        mini = {
            "k": jnp.zeros((cfg.n_layers, M, tb, cfg.n_kv_heads,
                            cfg.head_dim), cfg.dtype),
            "v": jnp.zeros((cfg.n_layers, M, tb, cfg.n_kv_heads,
                            cfg.head_dim), cfg.dtype),
            "len": jnp.zeros((), jnp.int32),
        }
        logits, mini = forward_with_cache(params, tokens, cfg, mini,
                                          mesh=None)
        mk, mv = mini["k"], mini["v"]
        if tp_axis is not None:
            # Replicated full-head mini cache → this shard's kv-head
            # slice, the rows its pool shard stores ([L, M, tb, Hkv/tp,
            # hd] — a slice of the exact bytes the unsharded path
            # scatters).
            mk = _tp_heads(mk, tp_axis, hkv_loc, 3)
            mv = _tp_heads(mv, tp_axis, hkv_loc, 3)
    else:
        hp = hb * page_size
        g = cfg.n_heads // cfg.n_kv_heads
        scale = 1.0 / (cfg.head_dim ** 0.5)
        # Prefix-attention implementation pick (trace-time — once per
        # compiled (tb, hb) rung, the _note_decode_fallback contract):
        # "kernel" forces the Pallas path, "gather" forces the dense
        # materializing path (the parity reference), "auto" follows the
        # config's decode_attn the way the decode/verify dispatches do.
        # The kernel streams [prefix pages via the table indirection] ++
        # [the tail's own K/V] blockwise with NO [L, M, hb·ps, Hkv, hd]
        # gather and no full-dtype dequant buffer — O(hit+tail) VMEM
        # traffic where the gather was O(hit_len) HBM materialization
        # per dispatch, growing with exactly the cache hits the fleet
        # router optimizes for.
        want_kernel = prefill_attn == "kernel" or (
            prefill_attn == "auto"
            and getattr(cfg, "decode_attn", "dense") == "fused")
        # hb == 0 reaches this branch only on weight-sharded islands
        # (the plain path cannot feed full-head reshapes from 1/tp
        # slices) and stays on the DENSE tail attention deliberately:
        # the unsharded plain prefill is dense (forward_with_cache),
        # and byte-identity of the all_gather combine requires the same
        # softmax arithmetic — there is no cached prefix to stream, so
        # the kernel has nothing to win here anyway. Not a downgrade,
        # so nothing is counted.
        use_kernel = (hb > 0 and want_kernel
                      and cfg.n_heads % cfg.n_kv_heads == 0
                      and tb % page_size == 0
                      and prefill_plan(hb + tb // page_size,
                                       page_size, tb * g) is not None)
        if hb > 0 and want_kernel and not use_kernel:
            _note_decode_fallback("no_prefill_plan")
        # Per-entry absolute positions: tail row i sits at hit_len + i
        # (clamped — the bucket's padded tail may overshoot the rope
        # table; those rows are never attended).
        pos_q = hit_lens[:, None] + jnp.arange(tb)[None, :]     # [M, tb]
        angles = rope_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta)[
            jnp.minimum(pos_q, cfg.max_seq - 1)]                # [M,tb,hd/2]
        x = params["embed"][tokens].astype(cfg.dtype)

        if use_kernel:
            def block(x, layer):
                # Per-layer POOL slices ride as scan xs — a dynamic
                # slice per layer, never a gathered prefix buffer. In
                # island mode they are this shard's kv-head slice, so
                # the kernel runs on its local head family exactly like
                # the decode/verify dispatches.
                blk, k_pg, v_pg, ks_p, vs_p = layer
                h = rms_norm(x, blk["attn_norm"])
                # Local head family (see _qkv_local), tb tail rows.
                q, kk, vv = _qkv_local(cfg, h, blk, angles, (M, tb),
                                       tp_axis, tp, wsharded)
                scales = (dict(k_scale=ks_p, v_scale=vs_p)
                          if quant else {})
                # Two-regime streamed attention: cached prefix pages
                # through the table (dequantized in registers — the
                # SAME bytes decode attends), then the tail's own K/V
                # (exact dtype, per-row causal) — the gather path's
                # mask semantics, blockwise.
                attn = paged_prefill_attention(
                    q, k_pg, v_pg, prefix_tables, hit_lens, kk, vv,
                    **scales)
                x = _attn_residual(x, attn, blk["wo"], (M, tb), 2,
                                   tp_axis, wsharded, combine)
                x = _mlp_residual(cfg, x, blk, tp_axis, wsharded,
                                  combine)
                return x, (kk, vv)

            x, (mk, mv) = jax.lax.scan(
                block, x, (params["blocks"], k, v, k_s, v_s))
        else:
            def gather_prefix(pool):
                # [L, n_pages, ps, Hkv, x] -> [L, M, hb*ps, Hkv, x]
                got = pool[:, prefix_tables]     # [L, M, hb, ps, Hkv, x]
                return got.reshape(pool.shape[0], M, hp, *pool.shape[3:])

            if quant:
                pk = (gather_prefix(k).astype(jnp.float32)
                      * gather_prefix(k_s)).astype(cfg.dtype)
                pv = (gather_prefix(v).astype(jnp.float32)
                      * gather_prefix(v_s)).astype(cfg.dtype)
            else:
                pk, pv = gather_prefix(k), gather_prefix(v)
            kcol = jnp.arange(hp + tb)[None, None, :]
            # Prefix col c valid iff c < hit_len; tail col hp+j causal
            # within the window (query i attends tail rows j <= i).
            valid = jnp.where(
                kcol < hp, kcol < hit_lens[:, None, None],
                (kcol - hp) <= jnp.arange(tb)[None, :, None])   # [M,tb,K]

            def block(x, layer):
                blk, pk_l, pv_l = layer          # prefix K/V [M, hp, Hkv, hd]
                h = rms_norm(x, blk["attn_norm"])
                # Local head family (see _qkv_local) — it lines up with
                # the gathered prefix (pk_l/pv_l IS this shard's
                # kv-head slice of the pool), and the scan ys (kk, vv)
                # stay local: exactly the rows this shard's pool
                # scatter stores.
                q, kk, vv = _qkv_local(cfg, h, blk, angles, (M, tb),
                                       tp_axis, tp, wsharded)
                h_kv = kk.shape[2]
                qg = q.reshape(M, tb, h_kv, g, cfg.head_dim)
                kf = jnp.concatenate([pk_l, kk], axis=1)  # [M,hp+tb,Hkv,hd]
                vf = jnp.concatenate([pv_l, vv], axis=1)
                scores = jnp.einsum(
                    "bqhgd,bkhd->bhgqk", qg, kf).astype(jnp.float32) * scale
                scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
                probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
                attn = jnp.einsum("bhgqk,bkhd->bqhgd", probs, vf)
                x = _attn_residual(x, attn, blk["wo"], (M, tb), 2,
                                   tp_axis, wsharded, combine)
                x = _mlp_residual(cfg, x, blk, tp_axis, wsharded,
                                  combine)
                return x, (kk, vv)

            x, (mk, mv) = jax.lax.scan(block, x, (params["blocks"], pk, pv))
        x = rms_norm(x, params["final_norm"])
        logits = qdot(x, params["lm_head"]).astype(jnp.float32)

    def page_blocks(a):
        # [L, M, tb, Hkv, x] -> [L, M*npg, ps, Hkv, x] page-granular blocks
        return a.reshape(a.shape[0], M * npg, page_size, *a.shape[3:])

    ids = page_ids.reshape(M * npg)
    if quant:
        mkq, mks = _kv_quant(mk)
        mvq, mvs = _kv_quant(mv)
        k = k.at[:, ids].set(page_blocks(mkq))
        v = v.at[:, ids].set(page_blocks(mvq))
        k_s = k_s.at[:, ids].set(page_blocks(mks))
        v_s = v_s.at[:, ids].set(page_blocks(mvs))
    else:
        k = k.at[:, ids].set(page_blocks(mk))
        v = v.at[:, ids].set(page_blocks(mv))
    row_ids = jnp.arange(B)
    base_key = jax.random.fold_in(jax.random.PRNGKey(1), seed)
    firsts = []
    for i in range(M):                               # static unroll
        slot, tail_len = slots[i], tail_lens[i]
        is_slot = row_ids == slot
        # Key by SLOT (see _prefill_multi_fn): pad rows duplicate a real
        # entry and must re-draw the same token.
        first = _sample_tokens(
            logits[i, tail_len - 1], jax.random.fold_in(base_key, slot),
            temperature, top_k,
        ).astype(last.dtype)
        lens = jnp.where(is_slot, hit_lens[i] + tail_len, lens)
        last = jnp.where(is_slot, first, last)
        firsts.append(first)
    return k, v, k_s, v_s, lens, last, jnp.stack(firsts)


class ContinuousBatcher:
    """Host-side orchestrator: admit requests into free cache slots between
    decode chunks; finished slots free immediately for the next waiting
    request. The chunk is the continuous-batching granularity (chunked so
    the ~100 ms axon host↔device round trip amortizes). BASELINE config
    5's serving engine.

    ``kv_layout="paged"`` swaps the shared-cursor contiguous cache for the
    paged pool + block table (see the section comment above): admission
    needs free PAGES instead of a contiguous cursor window, finished
    requests free theirs immediately, and there is no epoch roll.

    ``speculative=True`` (paged + greedy only) lifts prompt-lookup
    speculation out of ``generate_speculative`` into the batcher: each
    step proposes ``gamma`` tokens per slot by bigram match on the host
    token mirror (prompt + emitted stream), verifies every slot's
    1+gamma window in ONE batched dispatch (_verify_chunk_paged_fn), and
    commits the agreeing prefix — up to gamma+1 tokens per slot per
    dispatch on self-repetitive text, never below 1. Rewind is free:
    rejected overshoot rows sit above the committed ``lens`` inside the
    slot's own reserved pages (admission reserves the gamma window —
    _rows_needed), so no page ever moves and shared prefix pages are
    never touched. Verify windows pad to the fixed 1+gamma and the
    commit length is traced, so steady-state decode stays zero-retrace
    with the pool/scales/table donated every dispatch. Acceptance is
    content-dependent (the host must see each step's tokens to propose
    the next), so speculative steps flush per dispatch like eos mode —
    the deferred-drain fast path doesn't apply.

    ``prefill_chunk_tokens=N`` (paged only) makes prefill INCREMENTAL:
    admission reserves the worst-case pages and binds the slot as
    before, but dispatches nothing — each step a token-budget scheduler
    (``_advance_prefill``) spends at most N prompt tokens advancing
    partially-prefilled slots oldest-first, then the normal decode/
    verify chunk runs over the fully-prefilled slots. A continuation
    chunk reuses the prefix-cache tail-prefill program verbatim (the
    resident rows below ``prefill_done`` ride as the hb>0 prefix
    tables, the chunk resumes at per-slot rope offsets via
    ``hit_lens``), so the dispatch shapes stay the bounded (tb, hb)
    rung ladder and steady-state mixed prefill+decode is zero-retrace
    with the pool donated throughout. The FINAL chunk emits the
    request's first token; mid-prefill slots are simply inactive in
    decode/verify dispatches. This bounds the worst-case decode-step
    latency by the chunk budget regardless of arriving prompt length —
    the TTFT/decode-interference fix (Sarathi-Serve/DistServe), and
    stage (a) of the ROADMAP disaggregation item.

    ``mesh=`` (paged layout) turns on MULTI-CHIP SHARDED serving: every
    dispatch wraps in a ``shard_map`` island over the mesh's ``tp`` axis
    with the pool + scale planes sharded on the kv-heads dim
    ([L, n_pages, ps, Hkv/tp, hd] per chip — POOL_SPEC) and the block
    table / ``lens`` / ``last`` replicated. The Pallas kernel bodies run
    unchanged per shard on their local head family; attention heads
    reassemble via exact all_gathers, so sharded streams are
    byte-identical to unsharded ones, donation and zero-retrace survive
    the island boundary, and admission / chunked prefill / prefix
    mounting / speculative rewind — all host-side block-table and lens
    edits — are shard-agnostic and run untouched.

    ``weight_sharding=True`` (the default on a tp > 1 mesh) rides the
    WEIGHTS through those islands Megatron-sliced per the
    parallel/sharding.py WEIGHT_SPECS table (see the module comment at
    _gather_weight): column-parallel q/k/v/gate/up compute each shard's
    head/ffn family directly from a [·, ·/tp] slice, row-parallel
    o/down combine once per projection — ``tp_combine="all_gather"``
    (movement-only, byte-identity preserved) or ``"psum"`` (1/tp the
    row-matmul FLOPs, tolerance-checked). Per-chip HBM then holds 1/tp
    of every sliced weight next to 1/tp of the pool — the scale-UP axis
    no single chip provides (the fleet tier is the scale-OUT axis);
    unsliceable dims fail loudly at construction with the valid tp
    divisors, and ``weight_sharding=False`` keeps the legacy
    replicated-weight islands (warn-once + counted). Snapshots stay
    mesh-agnostic (drain gathers full kv heads; weights never ride a
    snapshot — targets rebuild them from config), so shed/failover
    works across replicas of different tp and combine modes."""

    def __init__(self, params, cfg: LlamaConfig, n_slots: int = 8,
                 max_len: Optional[int] = None, chunk: int = 8,
                 prefill_bucket: int = 128, mesh: Optional[Mesh] = None,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, kv_dtype: Optional[str] = None,
                 kv_layout: str = "contiguous",
                 page_size: int = DEFAULT_PAGE_SIZE,
                 n_pages: Optional[int] = None,
                 prefix_cache: bool = False,
                 kv_tiering: bool = False,
                 dram_pages: Optional[int] = None,
                 kv_tier_disk: Optional[str] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 role: str = "mixed",
                 speculative: bool = False, gamma: int = 4,
                 proposer=None, spec_adaptive: bool = False,
                 prefill_attn: Optional[str] = None,
                 donate_decoded: bool = True,
                 weight_sharding: bool = True,
                 tp_combine: str = "all_gather",
                 fault_injector=None, tracer=None, clock=None,
                 flight_capacity: int = 256):
        self.params = params
        # Observability (obs/): ``clock`` is the injected time source
        # every duration/timestamp in the engine reads (chaos and trace
        # tests pass a VirtualClock); ``tracer`` (obs.Tracer, None in
        # production — one `is None` check per phase) collects the
        # request-lifecycle spans queue|admit|prefill|decode_chunk|
        # verify|rewind|reap (plus demote|promote on tiered engines);
        # the flight recorder (always on — one host
        # dict append per step, capacity 0 disables) keeps the per-step
        # ring that drain() folds into the snapshot. ``_obs_mu`` guards
        # the cross-thread observability state so pool_metrics() exports
        # ONE consistent lock snapshot (watchdog age, spec gauges and
        # the drained phase batch can never tear against each other
        # mid-step).
        self._clock = clock or SYSTEM_CLOCK
        self._tracer = tracer
        self._flight = (FlightRecorder(flight_capacity, self._clock)
                        if flight_capacity else None)
        self._obs_mu = threading.Lock()
        # Bounded like every other obs buffer ("never block, never
        # grow"): a traced engine nobody scrapes — or a contiguous
        # engine, whose pool_metrics() is {} — must not leak host
        # memory; overflow drops the OLDEST phase observations.
        self._phase_buf: deque = deque(maxlen=4096)
        # Per-admission prefix-cache hit lengths (tokens), drained by
        # pool_metrics() into the tpu_serve_prefix_hit_tokens histogram
        # — the DISTRIBUTION the cumulative hit counters cannot show
        # (one warm conversation mounting 10k tokens vs a thousand
        # 8-token system-prompt hits are different fleets). Bounded
        # drop-oldest like every obs buffer.
        self._hit_tok_buf: deque = deque(maxlen=4096)
        # The PROMOTED subset of those hit lengths (tokens whose pages
        # were re-uploaded from the host tier at admission) — drained in
        # the same pool_metrics() lock snapshot into the
        # tpu_serve_promoted_hit_tokens histogram: how much of the hit
        # mass actually paid an upload.
        self._promoted_hit_buf: deque = deque(maxlen=4096)
        self._timelines: "OrderedDict[int, list]" = OrderedDict()
        self._rid_label: Dict[int, str] = {}
        self._step_faults: list = []
        self._step_admitted = 0
        # Chaos harness hook (testing/faults.py): the step loop fires
        # ``serve.step`` (drop/delay/preempt/page-pressure) and the
        # speculative proposer fires ``serve.propose`` per slot. None in
        # production — one `is None` check per step.
        self._faults = fault_injector
        self._chaos_pages: list = []         # page-pressure hostages
        # Lifecycle robustness (drain/snapshot/restore — models/snapshot
        # .py): a drained engine refuses further work; restore() fills a
        # FRESH engine from a snapshot. Per-request error isolation
        # (``errors``) records poison-request failures without
        # unwinding the step for the other slots.
        self._drained = False
        self._drain_s: Optional[float] = None
        self._restore_s: Optional[float] = None
        self._resumed = 0
        self._shed_total = 0                 # requests shed to a peer
        self._request_errors = 0
        self.errors: Dict[int, str] = {}
        # Watchdog/liveness: monotonic timestamp of the last step start —
        # pool_metrics() derives tpu_serve_last_step_age_seconds from it,
        # the gauge an external liveness probe alerts on when the step
        # loop wedges (the failure drain/restore exists to bound).
        self._last_step_t = self._clock.monotonic()
        self.cfg = cfg
        self.n_slots = n_slots
        self.chunk = chunk
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got "
                f"{kv_layout!r}")
        self.layout = kv_layout
        # Disaggregated serving (fleet/router.py pools=): ``role`` marks
        # which phase this replica serves. "mixed" (default) is today's
        # colocated engine. "prefill" runs admission + the chunked
        # advance phase but NEVER dispatches a decode/verify step — the
        # step loop holds ready slots until the fleet router drains them
        # to a decode replica (drain→absorb, pages LUT-remapped).
        # "decode" is an advisory placement label: the engine behaves
        # exactly like mixed (it can still prefill, e.g. a failover
        # replay landing on it), the router just never routes NEW
        # admissions to it when pools are configured. Deliberately
        # EXCLUDED from fingerprint(): roles differ across the pools of
        # one fleet by design, like mesh/tp/prefill_chunk_tokens.
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"role must be 'mixed', 'prefill' or 'decode', got "
                f"{role!r}")
        if role == "prefill" and kv_layout != "paged":
            raise ValueError(
                "role='prefill' requires kv_layout='paged' (handoff "
                "drains the slot's pages to a decode replica; the "
                "contiguous cache has no migratable pages)")
        self.role = role
        # prefill_attn: the hb>0 tail-prefill attention implementation.
        # None/"auto" follows cfg.decode_attn (fused configs stream the
        # cached prefix through the Pallas prefix-attention kernel,
        # dense configs keep the materializing gather); "kernel"/
        # "gather" force one side — the token-identity suites and the
        # multiturn bench drive both on the same trace. Rungs the
        # kernel's plan cannot cover fall back to the gather, counted
        # via tpu_serve_decode_fallback_total{reason="no_prefill_plan"}.
        if prefill_attn not in (None, "auto", "kernel", "gather"):
            raise ValueError(
                f"prefill_attn must be None/'auto'/'kernel'/'gather', "
                f"got {prefill_attn!r}")
        if prefill_attn in ("kernel", "gather") and kv_layout != "paged":
            raise ValueError(
                "prefill_attn requires kv_layout='paged' (the prefix-"
                "attention prefill streams pool pages by block table)")
        self._prefill_attn = prefill_attn or "auto"
        # donate_decoded: at reap, donate the DECODED suffix's full
        # pages into the radix prefix tree alongside the prompt pages,
        # so a multi-turn conversation's next turn mounts the whole
        # previous transcript instead of re-prefilling its own answer
        # (_retire_pages; no-op without prefix_cache). Off = PR 4's
        # prompt-only donation — the multiturn bench's baseline.
        self._donate_decoded = bool(donate_decoded)
        # kv_dtype: None keeps the cache in cfg.dtype; "int8" stores K/V
        # int8 with per-token-per-head scale planes (_kv_quant) — halves
        # cache HBM traffic AND capacity cost (2x slots at fixed HBM).
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        if prefill_bucket < 1:
            raise ValueError(f"prefill_bucket must be >= 1, got {prefill_bucket}")
        self.bucket = prefill_bucket
        # eos_id: a request finishes at its first eos token (output is
        # truncated INCLUDING the eos) or at max_new, whichever first. EOS
        # makes completion content-dependent, so run() flushes per step
        # instead of deferring every readback to the drain (one tunnel
        # round trip per chunk instead of per drain — the price of early
        # stopping; max_new-only workloads keep the fast path).
        # temperature/top_k: 0 = greedy argmax (compiled out); >0 =
        # temperature/top-k categorical sampling, seeded per dispatch from
        # a device-side counter fold (no PRNG state crosses the tunnel).
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        if self.top_k > cfg.vocab:
            # Caught here, where the other params are validated — inside
            # jit, lax.top_k fails at trace time with an obscure shape error.
            raise ValueError(f"top_k {self.top_k} exceeds vocab {cfg.vocab}")
        self._dispatch_no = 0
        self._eos_scanned: Dict[int, int] = {}       # req id -> tokens scanned
        self.spec = bool(speculative)
        self.gamma = int(gamma)
        self.spec_adaptive = bool(spec_adaptive) and self.spec
        if self.spec:
            if kv_layout != "paged":
                raise ValueError(
                    "speculative=True requires kv_layout='paged' (rewind "
                    "is a lens clamp inside the slot's own pages)")
            if self.gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            # Pluggable proposal source (models/proposers.py): the
            # historical host-mirror bigram by default. temperature > 0
            # engines run the verify's speculative-sampling rejection
            # branch — distributional proposers (draft model) supply
            # their q for the full min(1, p/q) rule, deterministic ones
            # are the delta-q special case.
            self._proposer = resolve_proposer(proposer)
            # Speculation gauges (pool_metrics → tpu_serve_spec_*): how
            # many proposals each verify accepted, tokens committed per
            # active slot per dispatch, and the overshoot rows rewound.
            self._spec_dispatches = 0
            self._spec_slot_steps = 0
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._spec_emitted = 0
            self._spec_rewound = 0
            # Per-dispatch accept rates, drained by pool_metrics() into
            # the proposer-labeled tpu_serve_spec_accept histogram —
            # bounded drop-oldest like every obs buffer.
            self._spec_accept_buf: deque = deque(maxlen=4096)
            # Adaptive per-slot gamma: an accept-rate EMA per request
            # drives the EFFECTIVE verify window in 0..gamma (dispatch
            # stays padded to 1+gamma — static shapes — rows >= eff are
            # masked out of acceptance). _spec_reserve pins, per rid AT
            # ADMISSION, the overshoot rows its pages were reserved for;
            # the effective window never exceeds it, so accepted rows
            # always land inside reserved pages even as the fleet EMA
            # moves. All three ride ServingSnapshot across drain/absorb.
            self._spec_ema: Dict[int, float] = {}
            self._spec_eff_last: Dict[int, int] = {}
            self._spec_reserve: Dict[int, int] = {}
            self._spec_fleet_ema = 1.0
        self.S = min(max_len or cfg.max_seq, cfg.max_seq)
        # Multi-chip sharded paged serving: a mesh with a 'tp' axis wraps
        # every paged dispatch (decode chunk / verify window / (tb, hb)
        # prefill rung) in a shard_map island with the pool + scale
        # planes sharded POOL_SPEC (kv heads over tp) and everything
        # host-legible — block table, lens, last, prompts — replicated,
        # so admission, chunked prefill, prefix-cache mounting and
        # speculative rewind are shard-agnostic and run untouched.
        self._mesh = mesh if kv_layout == "paged" else None
        self._tp = 1
        # Megatron-sliced weights through the islands (the module
        # comment above _gather_weight): on by default wherever a tp > 1
        # mesh is attached — each chip then HOLDS and MULTIPLIES only
        # its 1/tp slice of every projection/MLP weight. The legacy
        # replicated-weight islands stay behind weight_sharding=False,
        # warn-once + counted like every other serving downgrade.
        self._wsharded = False
        if tp_combine not in ("all_gather", "psum"):
            raise ValueError(
                f"tp_combine must be 'all_gather' (movement-only, "
                f"byte-identical) or 'psum' (partial-product reduce, "
                f"tolerance-checked), got {tp_combine!r}")
        self._combine = tp_combine
        if self._mesh is not None:
            if TP_AXIS not in self._mesh.shape:
                raise ValueError(
                    f"sharded paged serving needs a mesh with a "
                    f"'{TP_AXIS}' axis; got axes "
                    f"{tuple(self._mesh.axis_names)}")
            tp = int(self._mesh.shape[TP_AXIS])
            want_ws = bool(weight_sharding) and tp > 1
            if want_ws and cfg.n_experts > 1:
                raise ValueError(
                    "weight_sharding covers dense-MLP configs only (MoE "
                    "expert stacks shard over ep, not tp); pass "
                    "weight_sharding=False for replicated-weight islands")
            bad = [("kv heads", cfg.n_kv_heads)] if cfg.n_kv_heads % tp \
                else []
            if want_ws and cfg.d_ff % tp:
                bad.append(("d_ff", cfg.d_ff))
            if bad:
                # Fail LOUDLY with the workable widths instead of
                # silently replicating: a 70B config quietly falling
                # back to replicated weights is exactly the HBM wall
                # this engine exists to remove.
                dims = [cfg.n_kv_heads] + ([cfg.d_ff] if want_ws else [])
                valid = [d for d in range(1, max(dims) + 1)
                         if all(v % d == 0 for v in dims)]
                what = " and ".join(f"{n} ({v})" for n, v in bad)
                raise ValueError(
                    f"{what} not divisible by tp={tp}: the pool shards "
                    f"the kv-heads dim and weight sharding slices the "
                    f"q/k/v/MLP weights — valid tp divisors for this "
                    f"config: {valid}")
            self._tp = tp
            self._wsharded = want_ws
            if tp > 1 and not want_ws:
                _note_decode_fallback(
                    "weights_replicated",
                    msg=(f"weight_sharding=False on a tp={tp} island: "
                         f"every chip holds and multiplies the FULL "
                         f"weight matrices — per-chip weight bytes do "
                         f"not scale with tp; see tpu_serve_decode_"
                         f"fallback_total{{reason='weights_replicated'}}"
                         ))
        # KV tiering (host-DRAM second tier + optional disk third tier
        # behind the radix tree): validated HERE, built below once the
        # pool geometry is known. Pure capacity/scheduling knobs —
        # deliberately absent from fingerprint(), like n_pages.
        if kv_tiering and kv_layout != "paged":
            raise ValueError(
                "kv_tiering=True requires kv_layout='paged' (the tier "
                "demotes page-pool pages behind the radix tree)")
        if kv_tiering and not prefix_cache:
            raise ValueError(
                "kv_tiering=True requires prefix_cache=True (demotion "
                "parks CACHED tree pages; without the tree there is "
                "nothing to tier)")
        if not kv_tiering and (dram_pages is not None
                               or kv_tier_disk is not None):
            raise ValueError(
                "dram_pages/kv_tier_disk require kv_tiering=True")
        self._tier: Optional[HostTierStore] = None
        if kv_layout == "paged":
            if self.S % page_size:
                raise ValueError(
                    f"cache capacity {self.S} not divisible by page_size "
                    f"{page_size}")
            self.page_size = page_size
            self.n_blocks = self.S // page_size
            # Default pool: the same row capacity the contiguous cache
            # would allocate (n_slots full windows), plus the reserved
            # null page. Smaller pools oversubscribe deliberately —
            # admission then waits on free pages, not on a cursor window.
            n_pages = n_pages or (1 + n_slots * self.n_blocks)
            self._alloc = PageAllocator(n_pages)
            pool = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads,
                    cfg.head_dim)
            if kv_dtype == "int8":
                self._k = jnp.zeros(pool, jnp.int8)
                self._v = jnp.zeros(pool, jnp.int8)
                self._ks = jnp.zeros(pool[:-1] + (1,), jnp.float32)
                self._vs = jnp.zeros(pool[:-1] + (1,), jnp.float32)
            else:
                self._k = jnp.zeros(pool, cfg.dtype)
                self._v = jnp.zeros(pool, cfg.dtype)
                self._ks = self._vs = None
            if self._mesh is not None:
                # Shard the pool across the island's mesh from birth:
                # each chip holds [L, n_pages, ps, Hkv/tp, hd] — pool
                # residency scales 1/tp, the capacity headroom the whole
                # feature exists for.
                self._reshard_pool()
            # Per-chip pool residency, computed ONCE from the static
            # shapes (POOL_SPEC shards the kv-heads dim evenly, so shard
            # bytes are exactly total/tp). pool_metrics() must NOT read
            # the live arrays for this: they are donated every dispatch,
            # and a scrape thread racing a step would hit a deleted
            # buffer and die (observed: addressable_shards raising
            # "Array has been deleted" out of a scraper thread).
            self._kv_pool_dev_bytes = int(sum(
                a.nbytes for a in (self._k, self._v, self._ks, self._vs)
                if a is not None) // self._tp)
            # Megatron-sliced weights: build the per-leaf WEIGHT_SPECS
            # pytree, land each slice on its chips (per-chip HBM then
            # holds exactly 1/tp of every sliced matrix — the scale-UP
            # headroom this PR exists for), and record the per-chip
            # residency as build-time constants (same contract as
            # kv_pool_device_bytes: NEVER read live arrays from a
            # scrape thread). ``weight_sliced`` covers the leaves the
            # WEIGHT_SPECS table slices — exactly 1/tp by construction;
            # embed/norms/lm_head stay replicated and ride the total.
            self._wspecs = None
            try:
                from .llama import serving_weight_specs

                wspecs = serving_weight_specs(self.params)
            except ValueError:                       # MoE tree
                wspecs = None
            total_b = sliced_b = 0
            if wspecs is not None:
                def _acc(leaf, spec):
                    nonlocal total_b, sliced_b
                    n = int(leaf.nbytes)
                    if TP_AXIS in tuple(spec):
                        sliced_b += n
                    total_b += n
                    return leaf

                _map_weight_tree(self.params, wspecs, _acc)
            else:
                total_b = int(sum(a.nbytes
                                  for a in jax.tree.leaves(self.params)))
            if self._wsharded:
                self._wspecs = wspecs
                self._reshard_params()
                self._weight_dev_bytes = \
                    (total_b - sliced_b) + sliced_b // self._tp
                self._weight_sliced_dev_bytes = sliced_b // self._tp
            else:
                self._weight_dev_bytes = total_b
                self._weight_sliced_dev_bytes = sliced_b
            # Host mirror of the block table; the device copy is uploaded
            # (4 bytes/block — KiBs) only on steps whose admissions/frees
            # changed it, and otherwise donated through decode dispatches
            # untouched.
            self._table_np = np.zeros((n_slots, self.n_blocks), np.int32)
            self._table = self._table_np.copy()
            self._table_dirty = False
            self._lens = jnp.zeros((n_slots,), jnp.int32)
            self._slot_pages: Dict[int, list] = {}   # slot -> OWNED page ids
            self._slot_shared: Dict[int, list] = {}  # slot -> shared (hit)
            self._slot_prompt: Dict[int, list] = {}  # slot -> prompt tokens
            self._last_denied: Optional[int] = None  # req id, dedupes metric
            # Radix prefix cache (models/prefix_cache.py): reaped prompts
            # donate their full-page KV into a token-chunk tree; admission
            # mounts the longest cached page-aligned prefix read-only and
            # prefills only the novel tail.
            # KV tiering: LRU eviction DEMOTES cached leaves into a
            # host-DRAM store (default capacity = the pool itself)
            # instead of forgetting them; a later match through a
            # demoted path re-uploads the pages ahead of the slot's
            # first prefill (_admit_paged). ``kv_tier_disk`` arms the
            # disk third tier: DRAM-capacity sheds spill there instead
            # of forgetting (demote-before-forget, disk only when DRAM
            # is full).
            if kv_tiering:
                self._tier = HostTierStore(
                    int(dram_pages) if dram_pages is not None
                    else int(n_pages),
                    disk_dir=kv_tier_disk)
            self._prefix = (PrefixCache(self._alloc, page_size,
                                        tier=self._tier)
                            if prefix_cache else None)
            self._skipped_tokens = 0                 # prefill rows reused
            # Chunked prefill: the per-STEP prompt-token budget the
            # advance phase spends on partially-prefilled slots. None =
            # whole prompts dispatch at admission (pre-chunking
            # behavior, byte-identical). Page-multiple so every
            # non-final chunk ends page-aligned — the chunk scatter
            # writes whole pages and the next chunk's resident prefix
            # must be whole pages.
            if prefill_chunk_tokens is not None:
                prefill_chunk_tokens = int(prefill_chunk_tokens)
                if (prefill_chunk_tokens < page_size
                        or prefill_chunk_tokens % page_size):
                    raise ValueError(
                        f"prefill_chunk_tokens ({prefill_chunk_tokens}) "
                        f"must be a positive multiple of page_size "
                        f"({page_size})")
            self._prefill_chunk = prefill_chunk_tokens
            # slot -> prompt tokens already resident (page-aligned until
            # the final chunk). Insertion order IS the FCFS budget
            # order. Populated by chunked admission and by restore/
            # absorb of a mid-prefill snapshot — so it exists (and the
            # advance phase runs) even with chunking off.
            self._prefill_pending: "OrderedDict[int, int]" = OrderedDict()
            self._prefill_chunks_total = 0
        else:
            if prefix_cache:
                raise ValueError(
                    "prefix_cache=True requires kv_layout='paged' (the "
                    "contiguous cursor cache has no shareable pages)")
            if prefill_chunk_tokens is not None:
                raise ValueError(
                    "prefill_chunk_tokens requires kv_layout='paged' "
                    "(chunks land page-granular through the block "
                    "tables)")
            if kv_dtype == "int8":
                shape = (cfg.n_layers, n_slots, self.S, cfg.n_kv_heads,
                         cfg.head_dim)
                self._k = jnp.zeros(shape, jnp.int8)
                self._v = jnp.zeros(shape, jnp.int8)
                self._ks = jnp.zeros(shape[:-1] + (1,), jnp.float32)
                self._vs = jnp.zeros(shape[:-1] + (1,), jnp.float32)
            else:
                cache = init_cache(cfg, n_slots, self.S)
                self._k, self._v = cache["k"], cache["v"]
                self._ks = self._vs = None
            self._bitmap = jnp.zeros((n_slots, self.S), bool)
            self._cursor = 0
            self._rope_pos = jnp.zeros((n_slots,), jnp.int32)
        self._last = jnp.zeros((n_slots,), jnp.int32)
        if self._mesh is not None:
            self._pin_host_state()
        # Host-side bookkeeping (active mask is derived from it each chunk).
        self._slot_req: Dict[int, int] = {}          # slot -> req id
        self._budget: Dict[int, int] = {}            # req id -> tokens left
        self._out: Dict[int, list] = {}              # req id -> tokens
        self._queue: list = []                       # (req id, prompt list)
        self._reads: list = []                       # deferred readbacks
        self._next_id = 0
        # Per-request wall-clock (Clock.monotonic): submit → first token
        # VISIBLE TO THE HOST (TTFT) → completion. Timestamps are taken at
        # flush, not dispatch: a token a deferred readback hasn't
        # materialized yet cannot be sent to a client, so flush time is the
        # honest serving latency. Open-loop callers (step()-driven) get
        # per-step flushes; run()'s no-eos fast path defers every readback
        # to the drain, so all its requests complete at drain time — an
        # accurate description of that batch mode. VERDICT r4 weak #2/#1:
        # an SLO you never measure cannot be verified.
        self._arrival: Dict[int, float] = {}
        self._first_tok: Dict[int, float] = {}
        self._metrics: Dict[int, Dict[str, float]] = {}
        # params flow through as a runtime argument — binding them via
        # partial would inline every weight into the compiled program as a
        # constant. Caches/bitmap (contiguous) or pool/table (paged) are
        # donated: each dispatch consumes and replaces them; without
        # donation every call holds two full copies.
        temp, tk = self.temperature, self.top_k
        if kv_layout == "paged":
            ps = self.page_size
            # Island mode threads the tp axis through the dispatch
            # bodies; PS_/RE_ are the pool-sharded / replicated specs the
            # shard_map wrapper (_jit_island) binds per operand.
            tp_kw = ({} if self._mesh is None
                     else dict(tp_axis=TP_AXIS, tp=self._tp,
                               wsharded=self._wsharded,
                               combine=self._combine))
            PS_, RE_ = POOL_SPEC, P()
            # Params island spec: the WEIGHT_SPECS pytree when the
            # weights ride sliced (each body leaf is then the shard's
            # [·, ·/tp] slice), replicated otherwise (the PR 12 legacy
            # layout).
            W_ = self._wspecs if self._wsharded else RE_
            if self.spec:
                gm = self.gamma
                # The verify dispatch replaces the decode chunk: one
                # (1+gamma)-window forward per step instead of `chunk`
                # single-token ticks; the donation contract is identical
                # (pool + scales + table consumed every dispatch). New
                # since the sampling branch: seed (dispatch counter —
                # PRNG derives on device), eff (per-slot effective
                # windows, = gamma when non-adaptive) and, for
                # distributional proposers only, the q distributions —
                # all replicated, none donated, shapes static.
                if self._proposer.distributional:
                    self._decode = self._jit_island(
                        lambda p, k, v, ks, vs, tbl, lens, last, props,
                        active, seed, eff, q: _verify_chunk_paged_fn(
                            p, cfg, gm, ps, k, v, tbl, lens, last, props,
                            active, seed=seed, eff=eff, q=q,
                            temperature=temp, top_k=tk, k_s=ks, v_s=vs,
                            **tp_kw),
                        in_specs=(W_, PS_, PS_, PS_, PS_, RE_, RE_, RE_,
                                  RE_, RE_, RE_, RE_, RE_),
                        out_specs=(PS_, PS_, PS_, PS_, RE_, RE_, RE_,
                                   RE_, RE_),
                        donate=(1, 2, 3, 4, 5),
                    )
                else:
                    self._decode = self._jit_island(
                        lambda p, k, v, ks, vs, tbl, lens, last, props,
                        active, seed, eff: _verify_chunk_paged_fn(
                            p, cfg, gm, ps, k, v, tbl, lens, last, props,
                            active, seed=seed, eff=eff,
                            temperature=temp, top_k=tk, k_s=ks, v_s=vs,
                            **tp_kw),
                        in_specs=(W_, PS_, PS_, PS_, PS_, RE_, RE_, RE_,
                                  RE_, RE_, RE_, RE_),
                        out_specs=(PS_, PS_, PS_, PS_, RE_, RE_, RE_,
                                   RE_, RE_),
                        donate=(1, 2, 3, 4, 5),
                    )
            else:
                self._decode = self._jit_island(
                    lambda p, k, v, ks, vs, tbl, lens, last, active, seed:
                    _decode_chunk_paged_fn(
                        p, cfg, chunk, ps, k, v, tbl, lens, last, active,
                        seed, temp, tk, k_s=ks, v_s=vs, **tp_kw),
                    in_specs=(W_, PS_, PS_, PS_, PS_, RE_, RE_, RE_, RE_,
                              RE_),
                    out_specs=(PS_, PS_, PS_, PS_, RE_, RE_, RE_, RE_),
                    donate=(1, 2, 3, 4, 5),
                )
            pfa = self._prefill_attn
            self._prefill = self._jit_island(
                lambda p, k, v, ks, vs, lens, last, slots, pids, ptbl,
                hlens, tokens, tlens, seed: _prefill_multi_paged_fn(
                    p, cfg, ps, k, v, lens, last, slots, pids, ptbl,
                    hlens, tokens, tlens, seed, temp, tk, k_s=ks, v_s=vs,
                    prefill_attn=pfa, **tp_kw),
                in_specs=(W_, PS_, PS_, PS_, PS_, RE_, RE_, RE_, RE_,
                          RE_, RE_, RE_, RE_, RE_),
                out_specs=(PS_, PS_, PS_, PS_, RE_, RE_, RE_),
                donate=(1, 2, 3, 4),
            )
        else:
            self._decode = jax.jit(
                lambda p, k, v, ks, vs, bm, cur, rp, last, active, seed:
                _decode_chunk_fn(
                    p, cfg, chunk, mesh, k, v, bm, cur, rp, last, active,
                    seed, temp, tk, k_s=ks, v_s=vs),
                donate_argnums=(1, 2, 3, 4, 5),
            )
            self._prefill = jax.jit(
                lambda p, k, v, ks, vs, bm, rp, last, slots, curs, tokens,
                real_lens, seed: _prefill_multi_fn(
                    p, cfg, mesh, k, v, bm, rp, last, slots, curs, tokens,
                    real_lens, seed, temp, tk, k_s=ks, v_s=vs),
                donate_argnums=(1, 2, 3, 4, 5),
            )

    # -- multi-chip islands ------------------------------------------------
    def _jit_island(self, fn, in_specs, out_specs, donate):
        """jit one paged dispatch — wrapped in the multi-chip shard_map
        island when a mesh is attached. Donation goes through the island
        boundary: the pool/scale inputs and outputs carry the same
        POOL_SPEC sharding, so jit aliases the per-chip buffers exactly
        as it does the single-chip ones, and the table rides donated-
        through replicated. Every non-pool output is computed replicated
        inside the body (the only cross-shard ops are the exact
        all_gather head combines), so replicated out_specs are sound;
        ``check_vma=False`` matches the repo's other islands — 0.4.x
        ``check_rep`` cannot see through the axis_index-driven head
        slices."""
        if self._mesh is None:
            return jax.jit(fn, donate_argnums=donate)
        return jax.jit(
            _shard_map(fn, mesh=self._mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False),
            donate_argnums=donate)

    def _reshard_pool(self) -> None:
        """Pin the pool (+ scale planes) onto the island's POOL_SPEC
        placement. The initial allocation and every restore/absorb
        scatter funnel through here: eager ``.at[].set`` updates pick
        their own output sharding, and the island jit keys on input
        shardings — so re-pinning is simultaneously the "re-shard onto
        the target's mesh" half of snapshot portability (a tp=2 snapshot
        restores onto a tp=4 mesh by landing its host pages through this
        put) and what keeps steady-state dispatches on one compiled
        program. device_put onto an identical sharding is a no-op."""
        sh = NamedSharding(self._mesh, POOL_SPEC)
        # graftcheck: ignore[host-sync] — sanctioned: engine-birth/restore-boundary placement (never in the step loop); identical-sharding re-pins are no-ops
        self._k = jax.device_put(self._k, sh)
        self._v = jax.device_put(self._v, sh)  # graftcheck: ignore[host-sync] — sanctioned: same placement boundary
        if self._ks is not None:
            # graftcheck: ignore[host-sync] — sanctioned: same placement boundary (scale planes)
            self._ks = jax.device_put(self._ks, sh)
            self._vs = jax.device_put(self._vs, sh)  # graftcheck: ignore[host-sync] — sanctioned: same placement boundary

    def _reshard_params(self) -> None:
        """Land the params pytree on the island's WEIGHT_SPECS placement
        (models/llama.py serving_weight_specs): column slices on their
        output axis, row slices on their input axis, everything else
        replicated — after this put each chip's HBM holds only its 1/tp
        slice of every projection/MLP weight, which is the whole point.
        Engine birth only (params never change afterwards); jit keys on
        the committed shardings, so every dispatch reuses one program
        with zero per-call weight movement beyond the declared
        combines."""
        sh = partial(NamedSharding, self._mesh)

        def put(leaf, spec):
            # graftcheck: ignore[host-sync] — sanctioned: engine-birth weight placement (never in the step loop)
            return jax.device_put(leaf, sh(spec))

        self.params = _map_weight_tree(self.params, self._wspecs, put)

    def _pin_host_state(self) -> None:
        """Commit ``lens``/``last`` replicated onto the island mesh. jit
        keys include committed shardings, and these vectors alternate
        between host-built values (engine birth, restore/absorb writes)
        and donated-through island outputs — pinning both forms onto the
        same replicated placement keeps steady state on ONE compiled
        program instead of retracing at every host-write boundary."""
        if self._mesh is None:
            return
        rep = NamedSharding(self._mesh, P())
        # graftcheck: ignore[host-sync] — sanctioned: engine-birth/restore-boundary committal of two [n_slots] vectors (never in the step loop)
        self._lens = jax.device_put(self._lens, rep)
        self._last = jax.device_put(self._last, rep)  # graftcheck: ignore[host-sync] — sanctioned: same committal boundary

    # -- API ---------------------------------------------------------------
    def _ladder(self, prompt_len: int) -> int:
        """Prefill bucket for a prompt: the base bucket doubled until it
        fits, clamped to the cache capacity (one compiled prefill program
        per rung actually used, so long prompts up to the cache capacity
        are accepted without compiling a program per length — the vLLM
        bucketed-prefill idea with static shapes). At the S rung the
        prefill window only fits with cursor == prompt_len, i.e. at an
        epoch start — the admission check blocks such a request until the
        roll provides one."""
        tb = self.bucket
        while tb < prompt_len:
            tb *= 2
        return min(tb, self.S)

    # -- observability -----------------------------------------------------
    _TIMELINE_MAX = 1024                  # completed-request timeline cap

    def _rid(self, req_id: int) -> str:
        """Span correlation label for a request: the caller-supplied
        trace id (submit(trace_id=...)) or ``req-<n>`` — the scheduler
        plane tags its spans with the pod name, so a caller that uses
        one string for both gets a single scheduler→engine timeline."""
        return self._rid_label.get(req_id, f"req-{req_id}")

    def _obs_span(self, phase: str, t0: float, t1: float,
                  rid: Optional[int] = None, lane: str = "engine",
                  fold: bool = True, **attrs) -> None:
        """Record one phase span: to the tracer (with the rid label) and
        — when ``fold`` — into the phase-duration batch pool_metrics()
        drains atomically into the Prometheus histogram. Per-slot lane
        copies of an engine-wide dispatch span pass fold=False so the
        histogram counts each dispatch once."""
        label = None if rid is None else self._rid(rid)
        self._tracer.record(phase, t0, t1, lane=lane, rid=label, **attrs)
        evicted: list = []
        with self._obs_mu:
            if fold:
                self._phase_buf.append((phase, t1 - t0))
            if rid is not None:
                tl = self._timelines.get(rid)
                if tl is None:
                    while len(self._timelines) >= self._TIMELINE_MAX:
                        evicted.append(self._timelines.popitem(last=False)[0])
                    tl = self._timelines.setdefault(rid, [])
                tl.append({"phase": phase, "t0": t0, "t1": t1, **attrs})
        for old in evicted:
            # The trace label lives exactly as long as the timeline that
            # needs it — no slow leak across millions of requests
            # (GIL-atomic dict pop; _rid_label is not lock-owned state).
            self._rid_label.pop(old, None)

    def request_timeline(self, rid) -> Optional[Dict[str, object]]:
        """Per-request timeline summary (tracer attached; None when the
        request was never traced): the ordered phase events plus a
        per-phase rollup {count, total_s}. ``rid`` is the integer
        request id or its trace label."""
        if isinstance(rid, str):
            # .copy() is one C-level op under the GIL — iterating the
            # live dict here would race submit()'s inserts and the
            # timeline eviction's pops (RuntimeError mid-iteration).
            matches = [i for i, lbl in self._rid_label.copy().items()
                       if lbl == rid]
            if not matches and rid.startswith("req-"):
                try:
                    matches = [int(rid[4:])]
                except ValueError:
                    matches = []
            if not matches:
                return None
            rid = matches[-1]
        with self._obs_mu:
            events = [dict(e) for e in self._timelines.get(rid, [])]
        if not events:
            return None
        phases: Dict[str, Dict[str, float]] = {}
        for e in events:
            p = phases.setdefault(e["phase"], {"count": 0, "total_s": 0.0})
            p["count"] += 1
            p["total_s"] += e["t1"] - e["t0"]
        return {"request": rid, "trace_id": self._rid(rid),
                "events": events, "phases": phases}

    def submit(self, prompt, max_new: int, trace_id: Optional[str] = None) -> int:
        """Queue one request; returns its id. prompt: 1-D int sequence up
        to the cache capacity (padded to the next bucket rung).
        ``trace_id`` labels the request's spans for cross-plane
        correlation (defaults to ``req-<id>``)."""
        if self._drained:
            raise RuntimeError(
                "engine is drained: admission is stopped; restore() the "
                "snapshot into a fresh engine")
        prompt = list(int(t) for t in prompt)
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        # Feasible at an epoch start (cursor == P): the prefill window ends
        # at cursor-P+tb == tb <= S by the ladder clamp, and the decode
        # rows end at P+rows (the padded tail past P is overwritten by this
        # slot's own decode steps, so it does NOT consume decode capacity).
        if len(prompt) + self._rows_needed(max_new) > self.S:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({max_new}) exceeds "
                f"cache capacity {self.S}")
        if self.layout == "paged":
            # Worst-case reservation must fit the POOL, not just the
            # per-slot logical window — otherwise the request could never
            # admit and admission (strict FCFS) would spin forever.
            need = self._pages_needed(len(prompt), max_new)
            usable = self._alloc.n_pages - 1
            if need > usable:
                raise ValueError(
                    f"request needs {need} pages worst-case but the pool "
                    f"has only {usable} usable pages")
        req_id = self._next_id
        self._next_id += 1
        self._budget[req_id] = max_new
        self._out[req_id] = []
        if self.spec:
            # Pin the overshoot window this request's pages are reserved
            # for AT SUBMIT TIME: the adaptive effective window may
            # never exceed it (accepted rows must stay inside reserved
            # pages), and admission math below must keep quoting the
            # same figure across retries even as the fleet EMA moves.
            self._spec_reserve[req_id] = self._spec_overshoot()
            self._spec_ema[req_id] = self._spec_fleet_ema
        self._arrival[req_id] = self._clock.monotonic()
        if trace_id is not None:
            self._rid_label[req_id] = str(trace_id)
        self._queue.append((req_id, prompt))
        return req_id

    @property
    def pending(self) -> int:
        return len(self._queue) + len(self._slot_req)

    def _spec_overshoot(self, rid: Optional[int] = None) -> int:
        """Overshoot rows to reserve beyond the committed stream: the
        full gamma window normally; under adaptive gamma, the request's
        PINNED reservation (set at submit from the fleet accept-rate
        EMA, never revised — admission math must be stable per request),
        or the current fleet estimate for a request not yet pinned. The
        effective verify window is capped at this figure, so every
        ACCEPTED row provably lands inside reserved pages; rejected
        overshoot rows beyond it spill harmlessly onto rows the write
        scatter clamps inside the slot's last reserved block or the
        shared null page, and the lens clamp rewinds them either way."""
        if not self.spec_adaptive:
            return self.gamma
        if rid is not None and rid in self._spec_reserve:
            return self._spec_reserve[rid]
        est = int(math.ceil(self._spec_fleet_ema * self.gamma))
        return max(1, min(self.gamma, est))

    def _rows_needed(self, budget: int, rid: Optional[int] = None) -> int:
        """Worst-case cursor rows a request still needs: its remaining
        decode steps, rounded up to whole chunks (the shared cursor
        advances chunk rows per dispatch). Speculative mode commits at
        most one row per emitted token (budget - 1 rows) but each verify
        writes up to its effective window past the last committed lens,
        so up to _spec_overshoot rejected rows can sit above it —
        reserving them here is what makes rewind a free lens clamp
        inside the slot's own pages (never a shared prefix page, never
        an allocation)."""
        steps = max(0, budget - 1)                   # first token = prefill
        if self.spec:
            return steps + self._spec_overshoot(rid)
        return -(-steps // self.chunk) * self.chunk

    @staticmethod
    def _group_admissions(adm: list) -> list:
        """Group one step's admissions into prefill dispatch runs — shared
        by both layouts (entries are (req_id, slot, ..., bucket) tuples;
        only positions 1 and 4 are read here). Admissions ride ONE padded
        dispatch per bucket rung (usually one — see _prefill_multi_fn: M
        is always n_slots, short lists repeat the LAST entry, which is
        idempotent; padding with an earlier entry would re-apply writes a
        slot-reusing later entry already superseded). Writes to distinct
        slots commute, so same-bucket entries group regardless of
        interleaving; only when a slot REPEATS within the step (freed by
        a max_new==1 entry and reused) does cross-group ordering matter,
        and then we fall back to contiguity-split runs, which preserve
        admission order per slot."""
        runs: list = []
        if len({e[1] for e in adm}) == len(adm):     # all slots distinct
            by_tb: Dict[int, list] = {}
            for entry in adm:
                by_tb.setdefault(entry[4], []).append(entry)
            runs = list(by_tb.values())
        else:
            for entry in adm:
                if runs and runs[-1][0][4] == entry[4]:
                    runs[-1].append(entry)
                else:
                    runs.append([entry])
        return runs

    def _step_lazy(self) -> list:
        """Admit into free slots and dispatch one decode chunk — WITHOUT
        reading anything back. Returns the req ids that finished this step.

        Greedy fixed-budget decoding makes every scheduling decision —
        admission, slot reuse, epoch roll, completion — a pure function of
        host-side budget bookkeeping; token VALUES only matter to the
        caller. So the step leaves its result arrays on device
        (``self._reads``) and ``_flush`` fetches them all in one
        ``device_get``: a drain costs ONE tunnel round trip total instead
        of one per chunk (the per-step readback was 98% of the serving
        bench — 0.88 s of a 0.90 s run — with dispatches at ~3 ms)."""
        if self._drained:
            raise RuntimeError(
                "engine is drained: restore() the snapshot into a fresh "
                "engine")
        with self._obs_mu:
            self._last_step_t = self._clock.monotonic()
        self._step_faults = []
        if self._faults is not None:
            # Chaos hook: may raise (drop → InjectedFault, preempt →
            # Preempted — the in-process SIGTERM the drain/restore loop
            # catches) BEFORE any state changes this step; passive
            # page-pressure rules are applied to the allocator. The
            # injections this step fires (raising or not) land in the
            # flight recorder, so a post-preemption ring shows WHAT hit
            # the engine, not just that it stopped.
            n0 = len(self._faults.log)
            try:
                rules = self._faults.fire("serve.step")
            except BaseException:
                if self._flight is not None:
                    self._flight.record("fault", injected=[
                        k for _, _, k in self._faults.log[n0:]])
                raise
            self._step_faults = [k for _, _, k in self._faults.log[n0:]]
            self._apply_page_pressure(rules)
        if self.layout == "paged":
            if self.spec:
                return self._step_spec_paged()
            return self._step_lazy_paged()
        if not self._slot_req and self._cursor:
            # Epoch roll: every slot drained — reclaim the cursor space.
            self._cursor = 0
            self._bitmap = jnp.zeros_like(self._bitmap)

        finished: list = []
        free = [s for s in range(self.n_slots) if s not in self._slot_req]
        adm: list = []                               # (req id, slot, cursor, prompt, bucket)
        # len(adm) < n_slots: a max_new==1 admission hands its slot straight
        # back to `free`, so without the cap a burst of short requests could
        # admit more than n_slots entries — growing M past n_slots and
        # recompiling the prefill program per distinct burst size.
        while free and self._queue and len(adm) < self.n_slots:
            req_id, prompt = self._queue[0]
            P = len(prompt)
            tb = self._ladder(P)
            # The prompt writes BACKWARD from the cursor; bump the cursor
            # forward (free — just skips rows) if the window would start
            # below 0. Both bounds mirror _prefill_multi_fn's contract.
            cursor = max(self._cursor, P)
            if (cursor - P + tb > self.S
                    or cursor + self._rows_needed(self._budget[req_id])
                    > self.S):
                # No room this epoch — STOP admitting (strict FCFS). Letting
                # later, smaller requests past the blocked head would keep
                # consuming cursor rows: under sustained short-request load
                # the slots would never all drain, the epoch never rolls,
                # and a long-prompt head starves indefinitely (r4 advisor).
                # With admission frozen the occupied slots finish, the epoch
                # rolls, and the head admits at cursor == P.
                break
            self._queue.pop(0)
            self._cursor = cursor
            slot = free.pop()
            adm.append((req_id, slot, cursor, prompt, tb))
            if self._tracer is not None:
                now = self._clock.monotonic()
                self._obs_span("queue", self._arrival.get(req_id, now),
                               now, rid=req_id, prompt_len=P)
                self._obs_span("admit", now, self._clock.monotonic(),
                               rid=req_id, slot=slot, bucket=tb)
            self._budget[req_id] -= 1                # first token = prefill
            if self._budget[req_id] <= 0:            # max_new == 1
                finished.append(req_id)
                del self._budget[req_id]
                free.append(slot)                    # slot never occupied
            else:
                self._slot_req[slot] = req_id

        # Host inputs go in as NUMPY values: the tunnel device_puts them
        # asynchronously, while converting Python lists/ints through jnp
        # costs a ~0.7 s synchronous round trip EACH — measured 185 s of
        # a 188 s serving run.
        for run in self._group_admissions(adm):
            tb = run[0][4]
            # Pad with the LAST entry, not the first: a max_new==1 request
            # frees its slot mid-step, so an earlier entry's slot can be
            # reused by a later one — duplicating an earlier entry would
            # re-apply its superseded writes after the reuser's. Nothing
            # ever supersedes the last entry within a run.
            rows = run + [run[-1]] * (self.n_slots - len(run))
            tokens = np.asarray(
                [p + [0] * (tb - len(p)) for _, _, _, p, _ in rows],
                np.int32)
            self._dispatch_no += 1
            t_pf = self._clock.monotonic()
            (self._k, self._v, self._ks, self._vs, self._bitmap,
             self._rope_pos, self._last, firsts_arr) = self._prefill(
                self.params, self._k, self._v, self._ks, self._vs,
                self._bitmap, self._rope_pos, self._last,
                np.asarray([s for _, s, _, _, _ in rows], np.int32),
                np.asarray([c for _, _, c, _, _ in rows], np.int32),
                tokens,
                np.asarray([len(p) for _, _, _, p, _ in rows], np.int32),
                np.int32(self._dispatch_no))
            if self._tracer is not None:
                t1 = self._clock.monotonic()
                self._obs_span("prefill", t_pf, t1, bucket=tb,
                               requests=[self._rid(r)
                                         for r, _, _, _, _ in run])
                for rid, slot, _, _, _ in run:
                    self._obs_span("prefill", t_pf, t1, rid=rid,
                                   lane=f"slot{slot}", fold=False)
            # Prefill already produced each request's FIRST token from the
            # prompt's last-position logits (greedy argmax when
            # temperature == 0 — matching the static generate path — else
            # a slot-keyed categorical sample).
            self._reads.append(
                ("firsts", firsts_arr, [rid for rid, _, _, _, _ in run]))

        if not self._slot_req:
            if self._flight is not None:
                self._flight.record("admit_only", active=0,
                                    admitted=len(adm),
                                    retired=len(finished),
                                    faults=self._step_faults)
            return finished
        active = np.asarray(
            [s in self._slot_req for s in range(self.n_slots)])
        self._dispatch_no += 1
        t_dec = self._clock.monotonic()
        (self._k, self._v, self._ks, self._vs, self._bitmap, cursor,
         self._rope_pos, self._last, toks) = self._decode(
            self.params, self._k, self._v, self._ks, self._vs, self._bitmap,
            np.int32(self._cursor), self._rope_pos, self._last, active,
            np.int32(self._dispatch_no))
        self._cursor += self.chunk

        takes: list = []                             # (req id, slot, n tokens)
        for slot, req_id in list(self._slot_req.items()):
            budget = self._budget[req_id]
            take = min(budget, self.chunk)
            takes.append((req_id, slot, take))
            self._budget[req_id] = budget - take
            if self._budget[req_id] <= 0:
                finished.append(req_id)
                del self._budget[req_id]
                del self._slot_req[slot]             # slot free NOW
                if self._tracer is not None:
                    now = self._clock.monotonic()
                    self._obs_span("reap", now, now, rid=req_id, slot=slot)
        self._reads.append(("chunk", toks, takes))
        if self._tracer is not None:
            t1 = self._clock.monotonic()
            self._obs_span("decode_chunk", t_dec, t1,
                           active=int(active.sum()), chunk=self.chunk)
            for req_id, slot, take in takes:
                self._obs_span("decode_chunk", t_dec, t1, rid=req_id,
                               lane=f"slot{slot}", fold=False, tokens=take)
        if self._flight is not None:
            self._flight.record(
                "decode",
                wall_ms=round(
                    (self._clock.monotonic() - t_dec) * 1e3, 3),
                active=int(active.sum()), admitted=len(adm),
                tokens=sum(t for _, _, t in takes),
                retired=len(finished), cursor=self._cursor,
                faults=self._step_faults)
        return finished

    # -- paged step --------------------------------------------------------
    def _pages_needed(self, prompt_len: int, budget: int,
                      rid: Optional[int] = None) -> int:
        """Worst-case pages a request can ever touch: its prompt rows plus
        the decode rows — chunk-rounded in plain mode (the device writes
        whole chunks for active slots), budget + the verify-window
        overshoot in speculative mode (the per-request pinned window
        under adaptive gamma — see _rows_needed/_spec_overshoot for both
        formulas) — page-granular. Reserved in FULL at admission so a
        request in flight never stalls on allocation (no mid-decode
        deadlock); eos early-stop returns the unused tail at finish."""
        return -(-(prompt_len + self._rows_needed(budget, rid))
                 // self.page_size)

    def _hb_bucket(self, n_hit_pages: int) -> int:
        """Prefix-table width bucket for a hit of ``n_hit_pages`` pages:
        0 stays 0 (the plain prefill program), else the next power of two
        clamped to the table width — one compiled tail-prefill program
        per (tb, hb) rung actually used, the ladder idea again."""
        if n_hit_pages == 0:
            return 0
        hb = 1
        while hb < n_hit_pages:
            hb *= 2
        return min(hb, self.n_blocks)

    def _retire_pages(self, own: list, shared: list,
                      prompt: Optional[list],
                      decoded: Optional[list] = None) -> None:
        """A request is done with its pages: donate the full-chunk pages
        of its CONVERSATION — prompt plus (donate_decoded) the decoded
        tokens the caller verified have resident KV rows — into the
        prefix tree where the path is new (the slot's reference
        transfers — models/prefix_cache.py insert), and drop one
        reference on everything else — the shared hit pages it mounted
        (tree/other slots keep theirs) and its own partial/decode pages
        (refcount 0 → back to the free list). Donating the decoded
        suffix is what makes turn N+1 of a conversation mount turn N's
        ENTIRE transcript instead of re-prefilling its own answer
        (SGLang's RadixAttention framing: the cacheable prefix is the
        whole conversation, not just the prompt); the partial last page
        stays owner-freed as always — only full pages donate."""
        adopted: set = set()
        if self._prefix is not None and prompt is not None:
            conv = list(prompt) + list(decoded or ())
            n_full = min(len(conv) // self.page_size, len(shared) + len(own))
            adopted = set(self._prefix.insert(
                conv[:n_full * self.page_size], (shared + own)[:n_full],
                prompt_len=len(prompt)))
        release = [p for p in shared + own if p not in adopted]
        if release:
            self._alloc.free(release)

    def _donatable_decoded(self, rid: int) -> list:
        """The prefix of a request's emitted stream whose KV rows are
        VERIFIABLY resident in its pages — what _retire_pages may donate
        beyond the prompt. The bound is host-derivable with no device
        sync: emitted[i] was sampled AFTER emitted[i-1]'s KV row was
        written, so rows exist for every flushed token but the last
        (``raw[:-1]``); the eos-truncated stream is additionally capped
        there so post-eos garbage rows never enter the tree (they could
        never match a follow-up prompt, which continues from the eos).
        Budget-reaped requests in deferred-readback mode donate only the
        flushed prefix — conservative by design; the multi-turn path
        (eos reaps, spec commits) flushes before reaping and donates the
        full transcript."""
        if not self._donate_decoded or self._prefix is None:
            return []
        raw = self._out.get(rid)
        if not raw or len(raw) < 2:
            return []
        trunc = self._truncate_eos(list(raw))
        return [int(t) for t in trunc[:min(len(trunc), len(raw) - 1)]]

    def _free_slot_pages(self, slot: int,
                         decoded: Optional[list] = None) -> None:
        """Retire a slot's whole reservation. Owns the mid-prefill
        bookkeeping: a slot still in ``_prefill_pending`` has only
        ``prefill_done`` prompt rows resident, so the donation is capped
        there (donating beyond would cache pages whose KV was never
        written — garbage served to every future match); fully-prefilled
        slots donate prompt + the caller's verified decoded suffix."""
        prompt = self._slot_prompt.pop(slot, None)
        done = self._prefill_pending.pop(slot, None)
        if done is not None and prompt is not None:
            prompt, decoded = prompt[:done], None
        self._retire_pages(self._slot_pages.pop(slot),
                           self._slot_shared.pop(slot, []),
                           prompt, decoded)
        self._table_np[slot] = NULL_PAGE
        self._table_dirty = True

    def _drain_demotions(self) -> None:
        """Drain the pending device→host demotion queue at a STEP
        BOUNDARY: ONE batched gather of the enqueued pages' bytes (+
        int8 scale planes), committed into the host tier per page;
        each pool page then returns to the free list (``drop_cached``).
        This never runs inside a dispatch — the pool is donated every
        step, so the copy is scheduled from the host exactly like
        ``drain()``'s sanctioned gathers (a pending page stays
        allocated+cached meanwhile, so no dispatch can overwrite it).
        A commit the tier refuses (DRAM full, nothing evictable)
        forgets the node instead: demote-before-forget degrades to the
        plain eviction outcome, it never blocks admission."""
        if self._tier is None:
            return
        pend = self._tier.take_pending()
        if not pend:
            return
        t0 = self._clock.monotonic()
        idx = np.asarray([p for _, p in pend], np.int32)
        # graftcheck: ignore[host-sync] — sanctioned: the demotion drain IS a readback (one batched O(demoted pages) gather per step boundary, the tier's whole design)
        gathered = jax.device_get(
            # graftcheck: ignore[use-after-donate] — sanctioned: runs at a step boundary (no dispatch in flight), so the pool is the COMMITTED post-dispatch array; pending pages stay allocated+cached until drop_cached below
            [self._k[:, idx], self._v[:, idx]]
            # graftcheck: ignore[use-after-donate] — sanctioned: same step-boundary contract (scale planes)
            + ([self._ks[:, idx], self._vs[:, idx]]
               if self._ks is not None else []))
        k, v = gathered[0], gathered[1]
        ks = vs = None
        if self._ks is not None:
            ks, vs = gathered[2], gathered[3]
        for i, (key, page) in enumerate(pend):
            payload = (np.asarray(k[:, i]), np.asarray(v[:, i]),
                       None if ks is None else np.asarray(ks[:, i]),
                       None if vs is None else np.asarray(vs[:, i]))
            if not self._tier.commit(key, payload):
                self._prefix.drop_demoted(key)
            self._alloc.drop_cached(page)
        if self._tracer is not None:
            self._obs_span("demote", t0, self._clock.monotonic(),
                           pages=len(pend))

    def _admit_paged(self) -> list:
        """Paged admission: take free PAGES wherever they are (no
        contiguous window, no backward-write trick), so the only gates
        are a free slot, free pages, and strict FCFS — and there is NO
        epoch roll: freed pages recycle immediately, so the
        all-slots-drained idle boundary the cursor design pays every ~S
        decode steps simply does not exist. Dispatches the padded
        prefill runs; returns the max_new==1 requests that already
        finished. Shared by the plain decode step (_step_lazy_paged) and
        the speculative verify step (_step_spec_paged)."""
        finished: list = []
        free = [s for s in range(self.n_slots) if s not in self._slot_req]
        adm: list = []           # (req id, slot, pages, prompt, bucket, hits)
        free_after: list = []    # max_new==1 pages: retired post-dispatch
        while free and self._queue and len(adm) < self.n_slots:
            req_id, prompt = self._queue[0]
            P = len(prompt)
            t_adm = self._clock.monotonic()
            evicted = 0
            hits: list = []
            demoted: list = []
            if self._prefix is not None:
                # Longest cached page-aligned prefix (always leaves >= 1
                # token to prefill — the admission needs last-position
                # logits). Retain BEFORE any eviction below: the slot's
                # reference pins the hit path at refcount >= 2, so the
                # LRU sweep can never reclaim pages we are mounting.
                # Retries of a page-blocked head re-match every step but
                # count once, like the allocator's denial metric.
                if self._tier is not None:
                    # Tiered match: the path extends THROUGH demoted
                    # nodes — the resident prefix mounts as usual, the
                    # demoted suffix is re-uploaded into fresh pool
                    # pages below, before the first prefill dispatch.
                    # (A pending demotion the walk crosses is cancelled
                    # in place — the retain pin wins the race for free.)
                    path, demoted = self._prefix.match_tiered(
                        prompt, count=req_id != self._last_denied)
                    hits = path[:len(path) - len(demoted)]
                else:
                    hits = self._prefix.match(
                        prompt, count=req_id != self._last_denied)
                if hits:
                    self._alloc.retain(hits)
            # Fresh pages: the slot's own reservation PLUS one per
            # demoted hit page to promote into.
            need = (self._pages_needed(P, self._budget[req_id], req_id)
                    - len(hits) - len(demoted))
            if self._prefix is not None \
                    and need + len(demoted) > self._alloc.free_count:
                # Tree-only pages are reclaimable capacity, not occupancy:
                # evict the coldest unshared leaves to make room.
                evicted = need + len(demoted) - self._alloc.free_count
                self._prefix.evict(evicted)
                if self._tier is not None:
                    # With a tier, evict() only ENQUEUES demotions — the
                    # pages return to the free list when the readback
                    # queue drains, which must happen before the alloc
                    # below can see them.
                    self._drain_demotions()
                    # The tier-capacity shed inside that drain may have
                    # forgotten cold committed entries — possibly the
                    # tail of THIS request's own demoted path. Keep the
                    # still-promotable prefix.
                    alive = 0
                    for nd in demoted:
                        if nd.demoted is None \
                                or not self._tier.has(nd.demoted):
                            break
                        alive += 1
                    if alive < len(demoted):
                        del demoted[alive:]
                        need = (self._pages_needed(
                            P, self._budget[req_id], req_id)
                            - len(hits) - len(demoted))
            pages = self._alloc.alloc(
                need + len(demoted),
                count_denied=req_id != self._last_denied)
            if pages is None:
                # No pages for the head — STOP admitting (strict FCFS, the
                # same starvation argument as the contiguous path: letting
                # smaller requests jump the blocked head would keep the
                # pool drained and starve it indefinitely). Occupied slots
                # finish, free their pages, and the head admits. The
                # denial counts ONCE per request, not once per retry step.
                if hits:
                    self._alloc.free(hits)           # unwind the match pin
                if self._tracer is not None \
                        and req_id != self._last_denied:
                    # The admission-stall marker (deduped like the
                    # denial metric): the head is blocked on pages, so
                    # its queue span keeps growing until a retire frees
                    # some.
                    self._tracer.event(
                        "page_shortage", lane="engine",
                        rid=self._rid(req_id), need=need,
                        free=self._alloc.free_count)
                self._last_denied = req_id
                break
            if req_id == self._last_denied:
                self._last_denied = None
            self._queue.pop(0)
            slot = free.pop()
            if demoted:
                # Promotion: upload the demoted suffix's parked bytes
                # into the first len(demoted) fresh pages BEFORE the
                # slot's first prefill dispatch — the promoted pages
                # then mount exactly like resident hits (read-only,
                # shared, retained per mounting slot). The tree adopts
                # the allocation's reference (promote() mirrors
                # donation), so the slot's own mount retains on top.
                t_pr = self._clock.monotonic()
                promo, pages = pages[:len(demoted)], pages[len(demoted):]
                pay = [self._tier.pop(nd.demoted) for nd in demoted]
                self._scatter_pages(
                    promo,
                    np.stack([p[0] for p in pay], axis=1),
                    np.stack([p[1] for p in pay], axis=1),
                    (np.stack([p[2] for p in pay], axis=1)
                     if self._ks is not None else None),
                    (np.stack([p[3] for p in pay], axis=1)
                     if self._ks is not None else None))
                self._prefix.promote(demoted, promo)
                self._alloc.retain(promo)
                hits = hits + promo
                with self._obs_mu:
                    self._promoted_hit_buf.append(
                        len(promo) * self.page_size)
                if self._tracer is not None:
                    self._obs_span("promote", t_pr,
                                   self._clock.monotonic(), rid=req_id,
                                   pages=len(promo))
            row = self._table_np[slot]
            row[:] = NULL_PAGE
            row[:len(hits)] = hits                   # shared, read-only
            row[len(hits):len(hits) + len(pages)] = pages
            self._table_dirty = True
            hit_tok = len(hits) * self.page_size
            self._skipped_tokens += hit_tok
            if self._prefix is not None:
                # Per-admission hit-length observation (misses count as
                # 0 — the histogram's head is the miss mass, its tail
                # the warm-conversation mounts).
                with self._obs_mu:
                    self._hit_tok_buf.append(hit_tok)
            # Bucket the UNCACHED TAIL, rounded up to page granularity:
            # the prefill scatter writes whole page blocks, so tb must be
            # a page multiple (ladder rungs below page_size round up to
            # one page) — with a hit, prefill cost scales with the novel
            # suffix, which is the whole point of the cache.
            tb = -(-self._ladder(P - hit_tok) // self.page_size) \
                * self.page_size
            adm.append((req_id, slot, pages, prompt,
                        (tb, self._hb_bucket(len(hits))), hits))
            if self._tracer is not None:
                self._obs_span("queue", self._arrival.get(req_id, t_adm),
                               t_adm, rid=req_id, prompt_len=P)
                self._obs_span("admit", t_adm, self._clock.monotonic(),
                               rid=req_id, slot=slot, bucket=tb,
                               hit_pages=len(hits), new_pages=len(pages),
                               evicted=evicted)
            if self._prefill_chunk is not None:
                # Chunked admission: bind the slot and queue its prefill
                # for the budgeted advance phase (_advance_prefill) —
                # nothing dispatches here. The request's first token
                # comes from its FINAL chunk, so the budget decrement
                # (and the max_new==1 fast finish) happen there, and
                # the slot is occupied from now until then.
                self._slot_req[slot] = req_id
                self._slot_pages[slot] = pages
                self._slot_shared[slot] = hits
                self._slot_prompt[slot] = prompt
                self._prefill_pending[slot] = hit_tok
                continue
            self._budget[req_id] -= 1                # first token = prefill
            if self._budget[req_id] <= 0:            # max_new == 1
                finished.append(req_id)
                del self._budget[req_id]
                free.append(slot)                    # slot never occupied
                # The prefill dispatch below still writes these pages;
                # they are retired (donated + released) only after it is
                # enqueued.
                free_after.append((pages, hits, prompt))
            else:
                self._slot_req[slot] = req_id
                self._slot_pages[slot] = pages
                self._slot_shared[slot] = hits
                self._slot_prompt[slot] = prompt

        if self._prefill_chunk is not None:
            # Chunked mode: every admission above went to the pending
            # queue; _advance_prefill owns the dispatching.
            self._step_admitted = len(adm)
            return finished
        # Same one-padded-dispatch-per-rung grouping as the contiguous
        # path (_group_admissions: slot-repeat contiguity split, pad with
        # the LAST entry — duplicate page ids then carry identical
        # values, keeping the scatter idempotent).
        for run in self._group_admissions(adm):
            tb, hb = run[0][4]
            npg = -(-tb // self.page_size)
            rows = run + [run[-1]] * (self.n_slots - len(run))
            # Normalized dispatch rows: tail tokens only (the cached
            # prefix is already resident, its length rides as hit_len);
            # the page-id row holds the entry's OWN reserved pages in
            # logical order with the overshooting bucket tail on the
            # null page — shared hit pages are deliberately absent from
            # it (the scatter must never touch them) and ride the
            # prefix row instead.
            norm = []
            for _, slot, pg, p, _, h in rows:
                tail = p[len(h) * self.page_size:]
                norm.append((
                    slot,
                    [pg[j] if j < len(pg) else NULL_PAGE
                     for j in range(npg)],
                    [h[j] if j < len(h) else NULL_PAGE
                     for j in range(hb)],
                    len(h) * self.page_size, tail, len(tail)))
            t_pf = self._clock.monotonic()
            firsts_arr = self._dispatch_prefill_paged(norm, tb, hb)
            self._reads.append(
                ("firsts", firsts_arr, [rid for rid, *_ in run]))
            if self._tracer is not None:
                t1 = self._clock.monotonic()
                self._obs_span("prefill", t_pf, t1, bucket=tb,
                               prefix_bucket=hb,
                               requests=[self._rid(r)
                                         for r, *_ in run])
                for rid, slot, _, _, _, h in run:
                    self._obs_span("prefill", t_pf, t1, rid=rid,
                                   lane=f"slot{slot}", fold=False,
                                   hit_pages=len(h))
        for pages, hits, prompt in free_after:
            self._retire_pages(pages, hits, prompt)
        self._step_admitted = len(adm)               # flight-record input
        return finished

    def _device_table(self):
        """Upload the block table only when admissions/frees changed it
        (a copy, so the donated device buffer never aliases the live
        mirror); otherwise the previous dispatch's donated-through table
        is passed straight back — zero-copy steady state."""
        table = self._table_np.copy() if self._table_dirty else self._table
        self._table_dirty = False
        return table

    # -- chunked prefill ---------------------------------------------------
    def _chunk_ladder(self, n: int) -> int:
        """Prefill-chunk token bucket: the page size doubled until the
        chunk fits — page-multiple by construction (the chunk scatter
        writes whole pages) and a bounded rung set no matter the prompt
        length: the admission ladder's idea anchored at the page
        instead of the prefill bucket."""
        tb = self.page_size
        while tb < n:
            tb *= 2
        return min(tb, self.S)

    def _prefill_backlog(self) -> int:
        """Admitted-but-unfinished prefill tokens — the fleet router's
        prefill-pressure signal (queued prompts are NOT counted: they
        hold no pages yet and any replica could still take them)."""
        return sum(len(self._slot_prompt[s]) - d
                   for s, d in self._prefill_pending.items())

    def _dispatch_prefill_paged(self, rows, tb: int, hb: int):
        """ONE padded paged-prefill dispatch — the single marshalling
        point both admission (whole prompts / prefix tails) and the
        chunk scheduler (continuation chunks) feed, so the jitted
        program's calling convention and the padding contract live in
        exactly one place and the two paths cannot drift. ``rows`` are
        (slot, page-id row [tb/ps], prefix row [hb], hit_len, tokens,
        tail_len) tuples already padded to n_slots by REPEATING the
        last real entry (duplicate page ids then carry identical
        values, keeping the scatter idempotent). Returns the
        [n_slots] firsts array — the caller decides which rows are
        real first tokens."""
        tokens = np.asarray(
            [t + [0] * (tb - len(t)) for _, _, _, _, t, _ in rows],
            np.int32)
        self._dispatch_no += 1
        (self._k, self._v, self._ks, self._vs, self._lens, self._last,
         firsts) = self._prefill(
            self.params, self._k, self._v, self._ks, self._vs,
            self._lens, self._last,
            np.asarray([r[0] for r in rows], np.int32),
            np.asarray([r[1] for r in rows], np.int32),
            np.asarray([r[2] for r in rows],
                       np.int32).reshape(self.n_slots, hb),  # [M, 0] 2-D
            np.asarray([r[3] for r in rows], np.int32),
            tokens,
            np.asarray([r[5] for r in rows], np.int32),
            np.int32(self._dispatch_no))
        return firsts

    def _advance_prefill(self) -> list:
        """Spend the per-step prefill token budget advancing partially-
        prefilled slots: PAGE-QUANTUM ROUND-ROBIN, oldest admission
        first. Each allocation pass hands every pending slot one page's
        worth of its prompt (or its final partial remainder) until the
        budget is spent — the oldest slot always draws the first
        quantum (no starvation), and a short prompt slips into the same
        step's budget as a long one mid-walk instead of queueing behind
        its whole remaining prefill (head-of-line blocking would hand
        back the TTFT damage chunking exists to remove; with
        budget == page_size the policy degenerates to strict
        oldest-first, one quantum per step). Allocation is a pure
        function of the pending set — no wall-clock input — so a
        replayed trace chunks identically.

        One bounded-shape dispatch per (tb, hb) rung through the SAME
        jitted prefill program family admission uses: a continuation
        chunk is exactly a prefix-cache tail prefill whose "hit" is the
        rows this slot's own earlier chunks made resident (prefix
        tables = the block-table row below ``prefill_done``, per-slot
        rope offsets via ``hit_lens``), so chunked == unchunked token
        identity rides the same argument as cache-on == cache-off, and
        int8-KV chunking inherits exactly its quantization-noise bound
        (chunk queries attend the DEQUANTIZED resident rows — what
        decode also attends). Every non-final chunk ends page-aligned;
        the bucket tail a chunk overshoots into the NEXT chunk's pages
        is overwritten whole-page by that chunk before anything can
        attend it (rows above ``lens`` are masked throughout).

        The FINAL chunk emits the request's first token from its
        last-position logits — the budget decrement and the max_new==1
        fast finish happen here, not at admission; intermediate chunks
        discard the sampled row (their readback meta rid is None).
        Returns the requests that finished (max_new == 1 final chunks).

        Runs whenever ``_prefill_pending`` is non-empty — with chunking
        OFF (budget None, e.g. a mid-prefill slot restored/absorbed
        from a chunked peer) each pending slot's whole remainder
        dispatches as one chunk."""
        if not self._prefill_pending:
            return []
        budget = self._prefill_chunk
        remaining = {s: len(self._slot_prompt[s]) - d
                     for s, d in self._prefill_pending.items()}
        grants: Dict[int, int] = {}
        if budget is None:
            grants = dict(remaining)
        else:
            left = budget
            progressed = True
            while progressed and left > 0:
                progressed = False
                for slot in self._prefill_pending:
                    rem = remaining[slot] - grants.get(slot, 0)
                    if rem <= 0:
                        continue
                    # A whole page, or the slot's final partial tail —
                    # never a partial NON-final quantum, which would
                    # leave the next chunk starting mid-page. A quantum
                    # the leftover cannot fund is skipped (a smaller
                    # final tail further down may still fit); the
                    # skipped slot draws FIRST from the next step's
                    # budget, so nothing starves.
                    q = min(self.page_size, rem)
                    if q > left:
                        continue
                    grants[slot] = grants.get(slot, 0) + q
                    left -= q
                    progressed = True
                    if left <= 0:
                        break
        # (rid, slot, chunk page ids, chunk tokens, (tb, hb), done, cb,
        # final) — slot at [1] and the rung at [4], the positions
        # _group_admissions reads.
        entries: list = []
        for slot, done in list(self._prefill_pending.items()):
            cb = grants.get(slot, 0)
            if cb <= 0:
                continue
            prompt = self._slot_prompt[slot]
            tb = self._chunk_ladder(cb)
            npg = tb // self.page_size
            start_pg = done // self.page_size
            row = self._table_np[slot]
            # The chunk's OWN pages in logical order; the bucket's
            # beyond-reservation tail targets the null page (rows there
            # are never attended — lens stops below them).
            pids = [int(row[start_pg + j]) if start_pg + j < self.n_blocks
                    else NULL_PAGE for j in range(npg)]
            entries.append((self._slot_req[slot], slot, pids,
                            [int(t) for t in prompt[done:done + cb]],
                            (tb, self._hb_bucket(start_pg)), done, cb,
                            done + cb >= len(prompt)))
        finished: list = []
        retire: list = []
        for run in self._group_admissions(entries):
            tb, hb = run[0][4]
            rows = run + [run[-1]] * (self.n_slots - len(run))
            # Resident prefix per entry: the table row below its
            # prefill_done — shared hit pages first, then the pages its
            # earlier chunks wrote — null-padded to the hb rung.
            norm = [(e[1], e[2],
                     [int(self._table_np[e[1]][j])
                      if j < e[5] // self.page_size else NULL_PAGE
                      for j in range(hb)],
                     e[5], e[3], e[6]) for e in rows]
            t_pf = self._clock.monotonic()
            firsts_arr = self._dispatch_prefill_paged(norm, tb, hb)
            # Only FINAL chunks carry a real first token; intermediate
            # rows ride as rid None and _flush drops them.
            self._reads.append(
                ("firsts", firsts_arr,
                 [e[0] if e[7] else None for e in run]))
            self._prefill_chunks_total += len(run)
            if self._tracer is not None:
                t1 = self._clock.monotonic()
                self._obs_span("prefill_chunk", t_pf, t1, bucket=tb,
                               prefix_bucket=hb,
                               tokens=int(sum(e[6] for e in run)),
                               requests=[self._rid(e[0]) for e in run])
                for e in run:
                    self._obs_span("prefill_chunk", t_pf, t1, rid=e[0],
                                   lane=f"slot{e[1]}", fold=False,
                                   tokens=e[6], done=e[5] + e[6],
                                   final=e[7])
        for rid, slot, _, _, _, done, cb, fin in entries:
            if not fin:
                self._prefill_pending[slot] = done + cb
                continue
            del self._prefill_pending[slot]
            self._budget[rid] -= 1           # first token = final chunk
            if self._budget[rid] <= 0:               # max_new == 1
                finished.append(rid)
                del self._budget[rid]
                del self._slot_req[slot]
                # The dispatch above still writes these pages; retire
                # (donate + release) only after every run is enqueued.
                retire.append(slot)
                if self._tracer is not None:
                    t_rp = self._clock.monotonic()
                    self._obs_span("reap", t_rp, self._clock.monotonic(),
                                   rid=rid, slot=slot)
        for slot in retire:
            self._free_slot_pages(slot)
        if entries and self._flight is not None:
            self._flight.record(
                "prefill_chunk", slots=len(entries),
                tokens=int(sum(e[6] for e in entries)),
                backlog=self._prefill_backlog(),
                retired=len(finished))
        return finished

    def _step_lazy_paged(self) -> list:
        """Admit (see _admit_paged), advance any pending prefill chunks
        (_advance_prefill — the chunked-prefill budget phase), then
        dispatch one decode chunk over the fully-prefilled slots.
        Mid-prefill slots ride the decode dispatch inactive; a step
        with nothing fully prefilled is a pure-prefill step and skips
        the decode dispatch entirely."""
        finished = self._admit_paged()
        finished.extend(self._advance_prefill())
        ready = {s: r for s, r in self._slot_req.items()
                 if s not in self._prefill_pending}
        if self.role == "prefill":
            # Prefill-pool replica: admission + advance ran above; the
            # decode dispatch is the OTHER pool's job. Ready slots
            # (prefill complete, first token emitted) park here until
            # the router hands them off (drain(slots=...) → absorb on a
            # decode replica).
            if self._flight is not None:
                self._flight.record("prefill_only", active=0,
                                    held=len(ready),
                                    admitted=self._step_admitted,
                                    retired=len(finished),
                                    pool_free=self._alloc.free_count,
                                    faults=self._step_faults)
            return finished
        if not ready:
            if self._flight is not None:
                self._flight.record("admit_only", active=0,
                                    admitted=self._step_admitted,
                                    retired=len(finished),
                                    pool_free=self._alloc.free_count,
                                    faults=self._step_faults)
            return finished
        active = np.asarray(
            [s in ready for s in range(self.n_slots)])
        table = self._device_table()
        self._dispatch_no += 1
        t_dec = self._clock.monotonic()
        (self._k, self._v, self._ks, self._vs, self._table, self._lens,
         self._last, toks) = self._decode(
            self.params, self._k, self._v, self._ks, self._vs, table,
            self._lens, self._last, active, np.int32(self._dispatch_no))

        takes: list = []                             # (req id, slot, n tokens)
        for slot, req_id in list(ready.items()):
            budget = self._budget[req_id]
            take = min(budget, self.chunk)
            takes.append((req_id, slot, take))
            self._budget[req_id] = budget - take
            if self._budget[req_id] <= 0:
                finished.append(req_id)
                del self._budget[req_id]
                del self._slot_req[slot]             # slot free NOW
                t_rp = self._clock.monotonic()
                # Pages free NOW too; the flushed emitted prefix rides
                # into the tree as the decoded-suffix donation (this
                # step's still-deferred chunk is not host-visible yet —
                # the conservative bound _donatable_decoded documents).
                self._free_slot_pages(
                    slot, self._donatable_decoded(req_id))
                if self._tracer is not None:
                    self._obs_span("reap", t_rp, self._clock.monotonic(),
                                   rid=req_id, slot=slot)
        self._reads.append(("chunk", toks, takes))
        if self._tracer is not None:
            t1 = self._clock.monotonic()
            self._obs_span("decode_chunk", t_dec, t1,
                           active=int(active.sum()), chunk=self.chunk)
            for req_id, slot, take in takes:
                self._obs_span("decode_chunk", t_dec, t1, rid=req_id,
                               lane=f"slot{slot}", fold=False, tokens=take)
        if self._flight is not None:
            self._flight.record(
                "decode",
                wall_ms=round(
                    (self._clock.monotonic() - t_dec) * 1e3, 3),
                active=int(active.sum()), admitted=self._step_admitted,
                tokens=sum(t for _, _, t in takes),
                retired=len(finished),
                pool_free=self._alloc.free_count,
                pool_in_use=self._alloc.in_use,
                faults=self._step_faults)
        return finished

    def _spec_eff_window(self, rid: int) -> int:
        """Effective verify window for one request THIS dispatch: the
        full gamma unless adaptive, else the accept-rate EMA's estimate
        of how many proposals are worth paying for — capped at the
        request's pinned page reservation (accepted rows must land
        inside reserved pages) and floored at 0 (a 0 window is plain
        1-token decode through the same dispatch). A stuck-at-0 window
        would never observe an accept again, so every 8th dispatch
        probes with a 1-token window to let bursty self-repetition
        reopen it."""
        if not self.spec_adaptive:
            return self.gamma
        ema = self._spec_ema.get(rid, self._spec_fleet_ema)
        w = int(round(ema * self.gamma))
        if w <= 0 and self._dispatch_no % 8 == 0:
            w = 1
        return max(0, min(w, self._spec_reserve.get(rid, self.gamma)))

    def _step_spec_paged(self) -> list:
        """Speculative analog of _step_lazy_paged: admit, then ONE
        batched verify dispatch over all active slots — each commits
        1..gamma+1 tokens. Content-dependent by nature (the next
        proposal needs this step's committed tokens on the host), so the
        step flushes and reads the verify back synchronously instead of
        deferring to the drain — the same trade eos mode makes."""
        finished = self._admit_paged()
        finished.extend(self._advance_prefill())
        ready = {s: r for s, r in self._slot_req.items()
                 if s not in self._prefill_pending}
        if self.role == "prefill":
            # Prefill-pool replica built speculative=True for fleet
            # fingerprint compatibility (spec/gamma pin the page
            # reservation every replica must agree on): it still never
            # proposes or verifies — ready slots park for handoff, same
            # as the lazy path.
            if self._flight is not None:
                self._flight.record("prefill_only", active=0,
                                    held=len(ready),
                                    admitted=self._step_admitted,
                                    retired=len(finished),
                                    pool_free=self._alloc.free_count,
                                    faults=self._step_faults)
            return finished
        if not ready:
            return finished
        # Proposals read the committed stream, so the prefill firsts of
        # requests admitted THIS step must be host-visible first (this
        # also keeps per-request token order intact: firsts land in
        # _out before the verify's direct appends below). Mid-prefill
        # slots have no committed stream yet — they sit out the verify
        # (inactive window rows, no proposal, no commit).
        self._flush()
        self._dispatch_no += 1
        props = np.zeros((self.n_slots, self.gamma), np.int32)
        views = []
        for slot, rid in list(ready.items()):
            # Per-request error isolation: a poison request (host-side
            # failure building ITS proposal — chaos hook serve.propose,
            # or a genuine assert in the proposer's mirror code) fails
            # THAT request with a recorded error; the other slots'
            # proposals, pages and streams are untouched. Preempted
            # passes through: it is the whole-engine drain signal, not a
            # request fault.
            try:
                if self._faults is not None:
                    self._faults.fire("serve.propose")
                view = SlotView(slot, rid, self._slot_prompt[slot],
                                self._out[rid])
                if self._proposer.batched:
                    views.append(view)
                else:
                    props[slot] = self._proposer.propose(view, self.gamma)
            except Preempted:
                raise
            except Exception as e:  # noqa: BLE001 — isolate the poison request
                self._fail_request(slot, rid, e)
        q = None
        if self._proposer.distributional:
            q = np.zeros((self.n_slots, self.gamma, self.cfg.vocab),
                         np.float32)
        if views:
            # Batched proposers (draft model) score every surviving slot
            # in ONE call — a failure here is the draft program itself
            # breaking, an engine-level fault, not a poison request.
            p_arr, q_arr = self._proposer.propose_batch(
                views, self.gamma, self._dispatch_no)
            for i, vw in enumerate(views):
                props[vw.slot] = p_arr[i]
                if q is not None and q_arr is not None:
                    q[vw.slot] = q_arr[i]
        ready = {s: r for s, r in self._slot_req.items()
                 if s not in self._prefill_pending}
        if not ready:                                # every slot poisoned
            return finished
        eff = np.zeros((self.n_slots,), np.int32)
        for slot, rid in ready.items():
            eff[slot] = self._spec_eff_window(rid)
        active = np.asarray(
            [s in ready for s in range(self.n_slots)])
        table = self._device_table()
        t_ver = self._clock.monotonic()
        dispatch = (self.params, self._k, self._v, self._ks, self._vs,
                    table, self._lens, self._last, props, active,
                    np.int32(self._dispatch_no), eff)
        if self._proposer.distributional:
            dispatch = dispatch + (q,)
        (self._k, self._v, self._ks, self._vs, self._table, self._lens,
         self._last, toks, accepts) = self._decode(*dispatch)
        # graftcheck: ignore[host-sync] — sanctioned: speculative scheduling is content-dependent (accept lengths gate budgets and the next proposals), one readback per verify dispatch by design
        toks, accepts = jax.device_get((toks, accepts))
        t_ver1 = self._clock.monotonic()
        step_used = step_emitted = step_eff = 0

        for slot, req_id in list(ready.items()):
            acc = int(accepts[slot])
            take = min(self._budget[req_id], acc + 1)
            self._out[req_id].extend(int(tk) for tk in toks[slot, :take])
            # Gauges count what the stream actually kept: on a finishing
            # dispatch the budget clamp discards accepted-but-over-budget
            # proposals, and those rows are rewound like any rejection —
            # keeps accept_rate and tokens_per_dispatch telling one story.
            used = take - 1
            eff_i = int(eff[slot])
            step_used += used
            step_emitted += take
            step_eff += eff_i
            if self.spec_adaptive:
                # The EMA observes the rate over the EFFECTIVE window
                # (rate over a window the dispatch never opened would
                # drag a good slot down); eff == 0 dispatches carry no
                # signal either way.
                if eff_i > 0:
                    rate = min(used, eff_i) / eff_i
                    ema = self._spec_ema.get(req_id, self._spec_fleet_ema)
                    self._spec_ema[req_id] = (
                        (1.0 - _SPEC_EMA_ALPHA) * ema
                        + _SPEC_EMA_ALPHA * rate)
                    self._spec_fleet_ema = (
                        (1.0 - _SPEC_FLEET_ALPHA) * self._spec_fleet_ema
                        + _SPEC_FLEET_ALPHA * rate)
                self._spec_eff_last[req_id] = eff_i
            with self._obs_mu:
                self._spec_slot_steps += 1
                # proposed = the effective window (== gamma when
                # non-adaptive); rewound = the PHYSICAL overshoot rows
                # the lens clamp discards, always measured against the
                # full padded window the dispatch wrote.
                self._spec_proposed += eff_i
                self._spec_accepted += used
                self._spec_emitted += take
                self._spec_rewound += self.gamma - used
            if self._tracer is not None:
                self._obs_span("verify", t_ver, t_ver1, rid=req_id,
                               lane=f"slot{slot}", fold=False,
                               accepted=used, tokens=take)
                if self.gamma - used:
                    # The rewind is a pure host-side lens clamp — an
                    # instant, but the span makes rewind STORMS (0-accept
                    # waves burning whole verify windows) visible.
                    self._obs_span("rewind", t_ver1, t_ver1, rid=req_id,
                                   lane=f"slot{slot}",
                                   rewound=self.gamma - used)
            self._budget[req_id] -= take
            if self._budget[req_id] <= 0:
                finished.append(req_id)
                del self._budget[req_id]
                del self._slot_req[slot]             # slot free NOW
                self._proposer.drop(slot)
                self._spec_ema.pop(req_id, None)
                self._spec_eff_last.pop(req_id, None)
                self._spec_reserve.pop(req_id, None)
                t_rp = self._clock.monotonic()
                # Spec commits land in _out synchronously above, so the
                # decoded-suffix donation sees the full committed stream.
                self._free_slot_pages(
                    slot, self._donatable_decoded(req_id))
                if self._tracer is not None:
                    self._obs_span("reap", t_rp, self._clock.monotonic(),
                                   rid=req_id, slot=slot)
        n_active = int(active.sum())
        with self._obs_mu:
            self._spec_dispatches += 1
            if step_eff:
                self._spec_accept_buf.append(step_used / step_eff)
        if self._tracer is not None:
            self._obs_span("verify", t_ver, t_ver1, active=n_active,
                           gamma=self.gamma)
        if self._flight is not None:
            self._flight.record(
                "verify",
                wall_ms=round((t_ver1 - t_ver) * 1e3, 3),
                active=n_active, admitted=self._step_admitted,
                tokens=step_emitted,
                accept_rate=(round(step_used / step_eff, 4)
                             if step_eff else 0.0),
                retired=len(finished),
                pool_free=self._alloc.free_count,
                pool_in_use=self._alloc.in_use,
                faults=self._step_faults)
        return finished

    # -- chaos / error isolation -------------------------------------------
    def _apply_page_pressure(self, rules) -> None:
        """Apply the passive ``page_pressure`` rules the step hook
        returned: hold the largest requested hostage count out of the
        allocator (as many as are actually free — pressure takes what is
        there, it never fabricates pages), and release the hostages the
        moment no rule wants them. Chaos tests use this to force the
        admission path through its page-shortage branches (strict-FCFS
        head blocking, prefix-cache eviction) on a seeded schedule."""
        if self.layout != "paged":
            return
        want = max((r.pages for r in rules), default=0)
        held = len(self._chaos_pages)
        if want > held:
            take = min(want - held, self._alloc.free_count)
            if take:
                got = self._alloc.alloc(take, count_denied=False)
                if got:
                    self._chaos_pages.extend(got)
        elif want < held:
            release = self._chaos_pages[want:]
            del self._chaos_pages[want:]
            self._alloc.free(release)

    def _fail_request(self, slot: int, rid: int, exc: BaseException) -> None:
        """Per-request error isolation: a poison request (host-side
        failure while building ITS proposal/admission state) fails with a
        recorded error instead of unwinding the step — every other active
        slot keeps its pages and its stream. The slot and its whole page
        reservation return to the pool; the error text lands in
        ``self.errors`` for the caller."""
        self.errors[rid] = f"{type(exc).__name__}: {exc}"
        self._request_errors += 1
        self._slot_req.pop(slot, None)
        self._budget.pop(rid, None)
        self._eos_scanned.pop(rid, None)
        if self.spec:
            self._proposer.drop(slot)
            self._spec_ema.pop(rid, None)
            self._spec_eff_last.pop(rid, None)
            self._spec_reserve.pop(rid, None)
        if self.layout == "paged" and slot in self._slot_pages:
            # _free_slot_pages owns the mid-prefill donation cap (it
            # pops _prefill_pending itself); errored streams donate no
            # decoded suffix — only rows an ordinary reap would have.
            self._free_slot_pages(slot)
        elif self.layout == "paged":
            self._prefill_pending.pop(slot, None)
        self._out.pop(rid, None)
        self._arrival.pop(rid, None)
        self._first_tok.pop(rid, None)

    def emitted(self, req_id: int) -> list:
        """Tokens emitted so far for an IN-FLIGHT request (eos-truncated,
        a copy) — the fleet router's journal reads this after every step
        to record delivered-token progress, so a hard replica crash loses
        at most the tokens of the step it died in. Unknown/finished ids
        return [] (finished streams are popped by ``step()``)."""
        self._flush()                       # no-op between steps
        out = self._out.get(req_id)
        return self._truncate_eos(list(out)) if out else []

    def cancel(self, req_id: int, reason: str = "cancelled") -> bool:
        """Retire one request NOW — queued or active — with a surfaced
        error record (``self.errors``), its page reservation returned to
        the pool: the router's per-request deadline enforcement
        (``submit(deadline_s=)``) and failover cleanup path. Same
        contract as the poison-request isolation ``_fail_request``
        provides mid-step, callable between steps. Returns False for
        ids this engine does not hold."""
        self._flush()                       # deferred reads may name it
        for i, (rid, _prompt) in enumerate(self._queue):
            if rid == req_id:               # never admitted: no pages yet
                del self._queue[i]
                self.errors[req_id] = f"Cancelled: {reason}"
                self._request_errors += 1
                for d in (self._budget, self._out, self._arrival,
                          self._eos_scanned, self._first_tok):
                    d.pop(req_id, None)
                if self.spec:
                    self._spec_ema.pop(req_id, None)
                    self._spec_reserve.pop(req_id, None)
                return True
        for slot, rid in self._slot_req.items():
            if rid == req_id:
                self._fail_request(slot, req_id, RuntimeError(reason))
                self.errors[req_id] = f"Cancelled: {reason}"
                return True
        return False

    # -- lifecycle: drain / snapshot / restore -----------------------------
    def fingerprint(self) -> Dict[str, object]:
        """The engine-compat contract a snapshot carries: everything that
        must match for restored page bytes to be addressed and decoded
        identically (layout/page geometry/dtypes/model dims) plus the
        scheduling knobs the slot state already encodes worst-case page
        reservations for (chunk, spec, gamma). ``n_pages`` is recorded
        but EXEMPT from the restore check — pages are re-laid-out through
        the fresh allocator, so pool size may differ (snapshot.py
        check_fingerprint). The MESH/tp width is deliberately NOT
        recorded at all: drain gathers the full kv-head dim to host, so
        a snapshot is mesh-agnostic by construction and restores across
        heterogeneous replicas (tp=2 → tp=1 → tp=4) — the fleet
        shed/failover story across mixed replica shapes depends on it.
        ``weight_sharding`` and ``tp_combine`` are likewise excluded:
        weights never ride a snapshot (they are rebuilt from config by
        whoever constructs the target engine), and how a replica slices
        or combines them changes no pool byte and no stream — a
        weight-sharded tp=4 replica absorbs a replicated tp=2 shed
        unchanged. ``prefill_chunk_tokens`` is deliberately NOT
        part of the contract: chunking is a pure scheduling knob — a
        chunked engine's mid-prefill snapshot restores into an unchunked
        one (the tail prefills in one dispatch) and vice versa, with no
        effect on page layout or token identity. ``prefill_attn`` and
        ``donate_decoded`` are likewise excluded: the prefix-attention
        implementation is pinned token-identical to the gather by the
        parity suites (and follows ``decode_attn`` — which IS recorded —
        in auto mode), and decoded-suffix donation only changes what the
        local radix tree caches, never how restored pages decode.
        ``kv_tiering``/``dram_pages``/``kv_tier_disk`` are excluded for
        the same n_pages reason: the tier is pure reclaimable CAPACITY —
        a tiered drain restores onto an untiered engine (the tier
        sidecar drops, demoted tree paths truncate) and vice versa, with
        every live stream and resident page intact. ``role`` is
        deliberately excluded too: a disaggregated fleet's prefill and
        decode pools differ in role BY DESIGN, and the handoff
        (prefill-role drain → decode-pool absorb) must pass the same
        compat check a mixed-fleet shed does — role changes which steps
        an engine dispatches, never how a restored page decodes. Model
        WEIGHTS are the
        caller's obligation: restore into an engine holding different
        params resumes streams that decode differently, and no
        fingerprint can see that."""
        cfg = self.cfg
        fp: Dict[str, object] = {
            "layout": self.layout,
            "kv_dtype": self.kv_dtype,
            "dtype": jnp.dtype(cfg.dtype).name,
            "decode_attn": getattr(cfg, "decode_attn", "dense"),
            "n_layers": cfg.n_layers,
            "n_kv_heads": cfg.n_kv_heads,
            "n_heads": cfg.n_heads,
            "head_dim": cfg.head_dim,
            "vocab": cfg.vocab,
            "n_slots": self.n_slots,
            "chunk": self.chunk,
            "bucket": self.bucket,
            "capacity": self.S,
            "eos_id": self.eos_id,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "speculative": self.spec,
            "gamma": self.gamma if self.spec else None,
            "prefix_cache": (self.layout == "paged"
                             and self._prefix is not None),
        }
        if self.layout == "paged":
            fp["page_size"] = self.page_size
            fp["n_pages"] = self._alloc.n_pages
        return fp

    def drain(self, slots: Optional[list] = None) -> ServingSnapshot:
        """Stop admission and serialize the whole in-flight state machine
        to host: the preemption path's first half (the SIGTERM handler
        calls this, persists the snapshot through utils/checkpoint.py,
        and exits; ``restore`` on a fresh engine is the second half).

        Deferred readbacks are flushed first (one tunnel round trip —
        tokens a client could already have been sent must survive), then
        every REFERENCED pool page (live slots' own + mounted shared +
        prefix-tree pages; free pages are garbage by contract) is
        gathered to host along with the block tables, ``lens``, per-slot
        bindings, budgets, emitted streams, the waiting queue, and the
        radix tree as token-keyed paths. Speculative proposals are
        deliberately NOT captured — they are a pure function of
        prompt + emitted stream and are re-proposed after restore.
        The engine refuses further submit/step afterwards.

        ``slots`` selects a PARTIAL drain — the load-shedding half of
        the fleet tier (fleet/router.py): only the named active slots'
        pages and bookkeeping ship (a filter over ``slot_req`` — same
        format, no queue, no prefix tree), the snapshot is marked
        ``partial`` for ``absorb()`` on the target replica, and THIS
        engine keeps serving — the shed slots retire through the normal
        reap path (their full-prompt pages donate into the local prefix
        tree, the rest free immediately), so shedding both relieves
        page pressure and leaves the hot prefix cached."""
        if self.layout != "paged":
            raise SnapshotError(
                "drain() requires kv_layout='paged' (the snapshot format "
                "is pool pages + block tables)")
        if self._drained:
            raise RuntimeError("engine already drained")
        partial = slots is not None
        if partial:
            slots = sorted(int(s) for s in slots)
            if not slots:
                raise ValueError("partial drain needs at least one slot")
            missing = [s for s in slots if s not in self._slot_req]
            if missing:
                raise ValueError(
                    f"cannot shed inactive slot(s) {missing}: only active "
                    f"slots carry migratable requests")
        t0 = self._clock.monotonic()
        self._flush()
        # Pending demotions resolve first (this IS a step boundary):
        # dump_paths below serializes demoted chunks by tier key, so
        # every key must be COMMITTED before the tree is walked.
        self._drain_demotions()
        if not partial and self._chaos_pages:  # chaos hostages are not state
            self._alloc.free(self._chaos_pages)
            self._chaos_pages = []
        ids: list = []
        seen: set = set()

        def add(pages):
            for p in pages:
                p = int(p)
                # Negative entries are demoted chunks (-(tier key + 1),
                # dump_paths' wire form) — their bytes ride the tier
                # sidecar, not the page payload.
                if p > 0 and p not in seen:
                    seen.add(p)
                    ids.append(p)

        shed = slots if partial else sorted(self._slot_req)
        for slot in shed:
            add(self._slot_shared.get(slot, ()))
            add(self._slot_pages.get(slot, ()))
        tree_paths = (self._prefix.dump_paths()
                      if self._prefix is not None and not partial else [])
        for _, pages in tree_paths:
            add(pages)
        # The DRAM tier rides the snapshot host-numpy-native (it IS
        # host numpy), coldest first — disk spills coldest of all — so
        # a restore into a smaller dram_pages budget keeps the hottest
        # tail. Partial drains never ship it (no tree either).
        tier_keys: list = []
        tier_entries: list = []
        if self._tier is not None and not partial:
            for key, payload in self._tier.items_coldest_first():
                tier_keys.append(int(key))
                tier_entries.append(payload)
        if tier_entries:
            tier_k = np.stack([p[0] for p in tier_entries], axis=1)
            tier_v = np.stack([p[1] for p in tier_entries], axis=1)
            tier_ks = (np.stack([p[2] for p in tier_entries], axis=1)
                       if tier_entries[0][2] is not None else None)
            tier_vs = (np.stack([p[3] for p in tier_entries], axis=1)
                       if tier_entries[0][3] is not None else None)
        else:
            tier_k = tier_v = tier_ks = tier_vs = None

        if ids:
            idx = np.asarray(ids, np.int32)
            # graftcheck: ignore[host-sync] — sanctioned: the drain IS the readback (one gather of live+cached pages per preemption)
            gathered = jax.device_get(
                # graftcheck: ignore[use-after-donate] — sanctioned: drain runs at a step boundary (admission stopped, readbacks flushed), so the pool is the COMMITTED post-dispatch array; no step can race this read
                [self._k[:, idx], self._v[:, idx]]
                # graftcheck: ignore[use-after-donate] — sanctioned: same step-boundary contract (scale planes)
                + ([self._ks[:, idx], self._vs[:, idx]]
                   if self._ks is not None else []))
        else:
            empty = (self.cfg.n_layers, 0, self.page_size,
                     self.cfg.n_kv_heads, self.cfg.head_dim)
            gathered = [np.zeros(empty, self._k.dtype) for _ in range(2)]
            if self._ks is not None:
                gathered += [np.zeros(empty[:-1] + (1,), np.float32)
                             for _ in range(2)]
        # graftcheck: ignore[host-sync] — sanctioned: drain-time readback of two [n_slots] vectors
        lens, last = jax.device_get((self._lens, self._last))
        if self._flight is not None and not partial:
            # Recorded BEFORE the payload dump so the drain marker itself
            # rides the snapshot: the restored ring then reads
            # ...decode, drain, restore... across the process boundary.
            self._flight.record(
                "drain", pages=len(ids),
                in_flight=len(self._slot_req), queued=len(self._queue),
                wall_ms=round(
                    (self._clock.monotonic() - t0) * 1e3, 3))
        shed_set = set(shed)
        shed_rids = {int(self._slot_req[s]) for s in shed_set} \
            if partial else None
        if partial:
            # Table rows of slots that stay MUST NOT ride: their pages
            # are not shipped, and restore/absorb LUT-remaps every row.
            table = np.full_like(self._table_np, NULL_PAGE)
            table[shed] = self._table_np[shed]
        else:
            table = self._table_np.copy()

        def keep_slot(s):
            return not partial or int(s) in shed_set

        def keep_rid(r):
            return not partial or int(r) in shed_rids

        # A mid-prefill slot's device lens is not authoritative (chunked
        # admission dispatches nothing, so its row may still hold the
        # previous occupant's value); the host chunk scheduler is. The
        # snapshot carries lens = prefill_done, which is ALSO how
        # restore/absorb recognize the slot as mid-prefill
        # (lens < len(prompt)) and re-queue its unprefilled tail.
        lens = np.array(lens, np.int32)
        for s, d in self._prefill_pending.items():
            if keep_slot(s):
                lens[s] = d

        snap = ServingSnapshot(
            fingerprint=self.fingerprint(),
            page_ids=ids,
            k_pages=np.asarray(gathered[0]),
            v_pages=np.asarray(gathered[1]),
            k_scales=(np.asarray(gathered[2])
                      if self._ks is not None else None),
            v_scales=(np.asarray(gathered[3])
                      if self._ks is not None else None),
            table=table,
            lens=np.asarray(lens, np.int32),
            last=np.asarray(last, np.int32),
            slot_req={int(s): int(r) for s, r in self._slot_req.items()
                      if keep_slot(s)},
            slot_pages={int(s): [int(p) for p in pg]
                        for s, pg in self._slot_pages.items()
                        if keep_slot(s)},
            slot_shared={int(s): [int(p) for p in pg]
                         for s, pg in self._slot_shared.items()
                         if keep_slot(s)},
            slot_prompt={int(s): [int(t) for t in pr]
                         for s, pr in self._slot_prompt.items()
                         if keep_slot(s)},
            budgets={int(r): int(b) for r, b in self._budget.items()
                     if keep_rid(r)},
            out={int(r): [int(t) for t in ts]
                 for r, ts in self._out.items() if keep_rid(r)},
            queue=[] if partial else [(int(r), [int(t) for t in pr])
                                     for r, pr in self._queue],
            next_id=0 if partial else self._next_id,
            eos_scanned={int(r): int(n)
                         for r, n in self._eos_scanned.items()
                         if keep_rid(r)},
            tree_paths=tree_paths,
            tier_keys=tier_keys,
            tier_k=tier_k,
            tier_v=tier_v,
            tier_ks=tier_ks,
            tier_vs=tier_vs,
            arrival={r: t for r, t in self._arrival.items()
                     if keep_rid(r)},
            first_tok={r: t for r, t in self._first_tok.items()
                       if keep_rid(r)},
            drained_mono=self._clock.monotonic(),
            drained_wall=self._clock.wall(),
            skipped_tokens=0 if partial else self._skipped_tokens,
            flight=([] if partial or self._flight is None
                    else self._flight.to_payload()),
            partial=partial,
            spec_ema=({int(r): float(v)
                       for r, v in self._spec_ema.items()
                       if keep_rid(r)} if self.spec else {}),
            spec_eff=({int(r): int(v)
                       for r, v in self._spec_eff_last.items()
                       if keep_rid(r)} if self.spec else {}),
            spec_reserve=({int(r): int(v)
                           for r, v in self._spec_reserve.items()
                           if keep_rid(r)} if self.spec else {}),
            spec_fleet_ema=(float(self._spec_fleet_ema)
                            if self.spec else 1.0),
        )
        snap.validate()
        if partial:
            # The shed slots leave THROUGH the reap path: full-prompt
            # pages donate into the local tree (the prefix stays warm
            # here too — it is reclaimable capacity, evicted on
            # demand), everything else frees now. The request-level
            # bookkeeping migrates with the snapshot.
            self._shed_total += len(shed)
            for slot in shed:
                rid = self._slot_req.pop(slot)
                # Decoded-suffix donation BEFORE the stream migrates:
                # the shed slot's transcript-so-far stays cached here
                # (reclaimable capacity — the same warm-prefix argument
                # as the prompt pages), while the request itself
                # continues on the absorb target.
                decoded = self._donatable_decoded(rid)
                self._budget.pop(rid, None)
                self._out.pop(rid, None)
                self._eos_scanned.pop(rid, None)
                self._arrival.pop(rid, None)
                self._first_tok.pop(rid, None)
                if self.spec:
                    self._proposer.drop(slot)
                    self._spec_ema.pop(rid, None)
                    self._spec_eff_last.pop(rid, None)
                    self._spec_reserve.pop(rid, None)
                # _free_slot_pages pops _prefill_pending itself and caps
                # a mid-prefill slot's donation at its resident rows.
                self._free_slot_pages(slot, decoded)
            if self._flight is not None:
                self._flight.record(
                    "shed", slots=len(shed), pages=len(ids),
                    requests=len(snap.slot_req),
                    pool_free=self._alloc.free_count,
                    wall_ms=round(
                        (self._clock.monotonic() - t0) * 1e3, 3))
            if self._tracer is not None:
                self._obs_span("shed", t0, self._clock.monotonic(),
                               slots=len(shed), pages=len(ids))
            return snap
        self._drained = True
        self._drain_s = self._clock.monotonic() - t0
        if self._tracer is not None:
            self._obs_span("drain", t0, self._clock.monotonic(),
                           pages=len(ids))
        return snap

    def restore(self, snap: ServingSnapshot) -> int:
        """Fill THIS (fresh) engine from a drained snapshot and resume
        every interrupted stream token-identically to an uninterrupted
        run. Physical page ids need not match — the snapshot's pages are
        re-laid-out through this engine's allocator (same or different
        ``n_pages``; raises when they simply don't fit) and every block
        table, slot page list and tree path is remapped. Refcounts are
        rebuilt exactly: each restored page starts at refcount 1 (its
        owner — a slot's own page, or the tree's reference labeled via
        the insert/adopt path), and each mounting slot's ``retain`` adds
        its share, so ``PageAllocator.assert_consistent`` holds by
        construction (and is asserted). Latency clocks are re-based so
        TTFT/latency records keep charging the real downtime. Token
        identity is a GREEDY guarantee: sampled streams
        (temperature > 0) are seeded per dispatch from a counter the
        fresh engine restarts, so they stay valid samples but not the
        same ones. Returns the number of resumed requests (in-flight +
        queued)."""
        if self.layout != "paged":
            raise SnapshotError("restore() requires kv_layout='paged'")
        if self._drained:
            raise RuntimeError(
                "cannot restore into a drained engine — build a fresh one")
        if (self._slot_req or self._queue or self._next_id
                or self._reads or self._alloc.in_use):
            raise SnapshotError(
                "restore() needs a FRESH engine (no admitted slots, no "
                "queue, no allocated pages)")
        if snap.partial:
            raise SnapshotError(
                "partial snapshot (a shed slot subset): absorb() it into "
                "a running replica; restore() rebuilds a whole engine")
        check_fingerprint(snap.fingerprint, self.fingerprint())
        snap.validate()
        t0 = self._clock.monotonic()
        lut = self._upload_snapshot_pages(snap)
        table = np.asarray(snap.table, np.int64)
        if table.shape != self._table_np.shape:
            raise SnapshotError(
                f"block table shape {table.shape} != "
                f"{self._table_np.shape}")
        if table.max(initial=0) >= len(lut) or (lut[table] < 0).any():
            raise SnapshotError(
                "block table references pages the snapshot did not ship")
        self._table_np = lut[table].astype(np.int32)
        self._table_dirty = True
        self._lens = jnp.asarray(snap.lens, jnp.int32)
        self._last = jnp.asarray(snap.last, jnp.int32)
        self._pin_host_state()
        remap = lambda pages: [int(lut[p]) for p in pages]  # noqa: E731
        if snap.tree_paths and self._prefix is None:
            raise SnapshotError(
                "snapshot carries a prefix tree but prefix_cache=False")
        # Tiered snapshot: re-admit the shipped DRAM payloads under
        # fresh keys. Entries ship coldest first, so only the hottest
        # tail that fits this engine's dram_pages budget is kept; an
        # UNTIERED target drops them all — the tree paths below
        # truncate at the first unmapped demoted chunk, which is also
        # how pre-tiering engines load tiered snapshots unchanged.
        keymap: Dict[int, int] = {}
        if snap.tier_keys and self._tier is not None:
            lo = max(0, len(snap.tier_keys) - self._tier.dram_pages)
            for i in range(lo, len(snap.tier_keys)):
                payload = (
                    np.asarray(snap.tier_k[:, i]),
                    np.asarray(snap.tier_v[:, i]),
                    (np.asarray(snap.tier_ks[:, i])
                     if snap.tier_ks is not None else None),
                    (np.asarray(snap.tier_vs[:, i])
                     if snap.tier_vs is not None else None))
                nk = self._tier.restore_entry(payload)
                if nk is not None:
                    keymap[int(snap.tier_keys[i])] = nk
        for tokens, pages in snap.tree_paths:
            mapped: list = []
            for p in pages:
                p = int(p)
                if p >= 0:
                    mapped.append(int(lut[p]))
                    continue
                nk = keymap.get(-p - 1)
                if nk is None:           # dropped tier entry: truncate
                    break
                mapped.append(-(nk + 1))
            if mapped:
                self._prefix.insert(
                    list(tokens)[:len(mapped) * self.page_size], mapped)
        self._slot_req = dict(snap.slot_req)
        self._slot_pages = {s: remap(pg)
                            for s, pg in snap.slot_pages.items()}
        self._slot_shared = {s: remap(pg)
                             for s, pg in snap.slot_shared.items()}
        for pg in self._slot_shared.values():
            if pg:
                self._alloc.retain(pg)
        self._slot_prompt = {s: list(pr)
                             for s, pr in snap.slot_prompt.items()}
        self._budget = dict(snap.budgets)
        self._out = {r: list(ts) for r, ts in snap.out.items()}
        self._queue = [(r, list(pr)) for r, pr in snap.queue]
        self._next_id = snap.next_id
        self._eos_scanned = dict(snap.eos_scanned)
        self._skipped_tokens = snap.skipped_tokens
        if self.spec:
            # Adaptive-gamma continuity across failover: the restored
            # streams keep their accept-rate history (no cold-start
            # re-learning), and — load-bearing — their PINNED page
            # reservations, which is what lets a restored dispatch's
            # effective window trust the page math the source engine
            # admitted under. Old snapshots default these empty; the
            # effective-window cap then falls back per request to the
            # full gamma its era reserved.
            self._spec_ema = dict(snap.spec_ema)
            self._spec_eff_last = dict(snap.spec_eff)
            self._spec_reserve = dict(snap.spec_reserve)
            self._spec_fleet_ema = float(snap.spec_fleet_ema)
        # Slots drained MID-PREFILL (lens < prompt length — chunked
        # prefill, or an absorbed peer's chunk state) re-queue their
        # unprefilled tail; the advance phase resumes them — budgeted
        # when this engine chunks, in one dispatch when it doesn't.
        # FCFS order rebuilt by request id (lower id = earlier
        # admission).
        lens_np = np.asarray(snap.lens)
        for s in sorted(self._slot_req, key=lambda s: self._slot_req[s]):
            pr = self._slot_prompt.get(s)
            if pr is not None and int(lens_np[s]) < len(pr):
                self._prefill_pending[s] = int(lens_np[s])
        now_m, now_w = self._clock.monotonic(), self._clock.wall()
        self._arrival = snap.rebased_clock(snap.arrival, now_m, now_w)
        self._first_tok = snap.rebased_clock(snap.first_tok, now_m, now_w)
        self._alloc.assert_consistent()
        self._resumed = snap.n_requests_in_flight
        self._restore_s = self._clock.monotonic() - t0
        if self._flight is not None:
            # The pre-preemption ring survives the process boundary: the
            # restored engine can explain behavior it never exhibited.
            self._flight.seed(snap.flight)
            self._flight.record(
                "restore", resumed=self._resumed,
                pages=len(snap.page_ids),
                downtime_s=round(max(0.0, now_w - snap.drained_wall), 3),
                wall_ms=round(self._restore_s * 1e3, 3))
        if self._tracer is not None:
            self._obs_span("restore", t0, self._clock.monotonic(),
                           resumed=self._resumed)
        return self._resumed

    def _upload_snapshot_pages(self, snap: ServingSnapshot) -> np.ndarray:
        """Shared restore/absorb page move: allocate fresh pages for the
        snapshot's shipped ids (evicting tree-only pages on shortage
        when a prefix cache is attached — reclaimable capacity, the
        admission path's argument), scatter the KV bytes (+ int8 scale
        planes) into them, and return the old→new LUT (-1 = unshipped,
        null maps to null)."""
        need = len(snap.page_ids)
        if self._prefix is not None and need > self._alloc.free_count:
            self._prefix.evict(need - self._alloc.free_count)
            # With a tier, evict() enqueues demotions; the pages free
            # only once the readback drains (no-op untiered).
            self._drain_demotions()
        new = self._alloc.alloc(need)
        if new is None:
            raise SnapshotError(
                f"snapshot references {need} pages but the pool has "
                f"only {self._alloc.free_count} free")
        lut = np.full(max(snap.page_ids, default=0) + 1, -1, np.int64)
        lut[NULL_PAGE] = NULL_PAGE
        for old, nw in zip(snap.page_ids, new):
            lut[old] = nw
        if new:
            self._scatter_pages(new, snap.k_pages, snap.v_pages,
                                snap.k_scales, snap.v_scales)
        return lut

    def _scatter_pages(self, pages, k, v, ks=None, vs=None) -> None:
        """Land host page bytes (+ int8 scale planes) into pool
        ``pages`` — ONE eager scatter per plane, shared by the
        snapshot restore/absorb LUT move and the tier promotion upload
        (the old→new relocation over pool bytes IS the migration
        primitive; there is exactly one copy path). Arrays are
        [L, len(pages), ps, Hkv, hd] host values; runs only between
        dispatches (admission / restore time), and re-shards onto the
        island mesh when one is attached — the shipped bytes carry the
        FULL kv-head dim, so tp=2 → tp=1 → tp=4 round trips are pure
        data movement."""
        if self._ks is not None and ks is None:
            raise SnapshotError(
                "int8-KV engine but the shipped pages carry no "
                "scale planes")
        idx = np.asarray(pages, np.int32)
        self._k, self._v, self._ks, self._vs = scatter_pool_pages(
            self._k, self._v, self._ks, self._vs, idx, k, v, ks, vs)
        if self._mesh is not None:
            self._reshard_pool()

    def absorb(self, snap: ServingSnapshot) -> Dict[int, int]:
        """Merge a PARTIAL snapshot — ``drain(slots=...)`` on a hot peer
        replica — into THIS **running** engine: the second half of fleet
        load shedding (fleet/router.py). Unlike ``restore()``, the
        target is busy, so nothing global transfers: each shed slot maps
        onto a free local slot, its pages re-lay out through this
        engine's allocator (LUT remap, exactly restore's move), and its
        request gets a FRESH local id (the source's ids would collide
        with ours) — the returned ``{old rid: new rid}`` mapping is how
        the router re-points its bookkeeping. Pages the source mounted
        READ-ONLY from its prefix tree arrive as slot-OWNED here (their
        bytes shipped; the source tree kept its own copy) — a page two
        shed slots both mounted allocates once and ``retain``s per
        extra holder, so ``assert_consistent`` holds on both engines
        after the handoff, and the normal reap donates the prefix into
        THIS tree when the request finishes. Latency clocks rebase
        across the hop (the migration gap is charged to the request).
        Token identity is the same greedy guarantee restore makes: the
        shipped pages hold exactly the bytes the slot's own prefill/
        decode wrote, and decode resumes at the shipped ``lens``."""
        if self.layout != "paged":
            raise SnapshotError("absorb() requires kv_layout='paged'")
        if self._drained:
            raise RuntimeError("cannot absorb into a drained engine")
        if not snap.partial:
            raise SnapshotError(
                "absorb() takes a PARTIAL snapshot (drain(slots=...)); "
                "restore() a full snapshot into a fresh engine")
        if snap.tree_paths:
            raise SnapshotError(
                "partial snapshot must not carry a prefix tree")
        check_fingerprint(snap.fingerprint, self.fingerprint())
        snap.validate()
        free_slots = sorted(s for s in range(self.n_slots)
                            if s not in self._slot_req)
        if len(snap.slot_req) > len(free_slots):
            raise SnapshotError(
                f"snapshot carries {len(snap.slot_req)} slots but only "
                f"{len(free_slots)} are free here")
        t0 = self._clock.monotonic()
        need = len(snap.page_ids)
        lut = self._upload_snapshot_pages(snap)
        now_m, now_w = self._clock.monotonic(), self._clock.wall()
        arrival = snap.rebased_clock(snap.arrival, now_m, now_w)
        first = snap.rebased_clock(snap.first_tok, now_m, now_w)
        # graftcheck: ignore[host-sync] — sanctioned: absorb-time readback of two [n_slots] vectors (one migration, not a step-loop cost)
        got = jax.device_get((self._lens, self._last))
        lens, last = np.array(got[0]), np.array(got[1])  # writable copies
        mapping: Dict[int, int] = {}
        claimed: set = set()
        # Source-rid order, not slot order: admission hands out HIGH
        # slots first (free.pop()), so slot order would typically invert
        # admission order — and _prefill_pending insertion order is the
        # chunk scheduler's FCFS, which must keep charging the OLDEST
        # migrated request first (restore() sorts by rid for the same
        # reason).
        for src_slot in sorted(snap.slot_req, key=lambda s: snap.slot_req[s]):
            rid = int(snap.slot_req[src_slot])
            tgt = free_slots.pop(0)
            new_rid = self._next_id
            self._next_id += 1
            mapping[rid] = new_rid
            row = np.asarray(snap.table[src_slot], np.int64)
            if row.max(initial=0) >= len(lut) or (lut[row] < 0).any():
                raise SnapshotError(
                    "block table references pages the snapshot did not "
                    "ship")
            self._table_np[tgt] = lut[row].astype(np.int32)
            pages = [int(lut[p])
                     for p in (list(snap.slot_shared.get(src_slot, []))
                               + list(snap.slot_pages.get(src_slot, [])))]
            for p in pages:
                if p in claimed:
                    self._alloc.retain([p])
                claimed.add(p)
            self._slot_req[tgt] = new_rid
            self._slot_pages[tgt] = pages
            self._slot_shared[tgt] = []
            self._slot_prompt[tgt] = [
                int(t) for t in snap.slot_prompt[src_slot]]
            self._budget[new_rid] = int(snap.budgets[rid])
            self._out[new_rid] = [int(t) for t in snap.out.get(rid, [])]
            if rid in snap.eos_scanned:
                self._eos_scanned[new_rid] = int(snap.eos_scanned[rid])
            if rid in arrival:
                self._arrival[new_rid] = arrival[rid]
            if rid in first:
                self._first_tok[new_rid] = first[rid]
            if self.spec:
                # Migrated streams keep their accept-rate history and
                # pinned reservation under the REMAPPED rid; streams
                # from pre-adaptive snapshots get fresh defaults (full
                # gamma — exactly what their era's admission reserved).
                if rid in snap.spec_ema:
                    self._spec_ema[new_rid] = float(snap.spec_ema[rid])
                if rid in snap.spec_eff:
                    self._spec_eff_last[new_rid] = int(snap.spec_eff[rid])
                if rid in snap.spec_reserve:
                    self._spec_reserve[new_rid] = int(
                        snap.spec_reserve[rid])
            lens[tgt] = int(snap.lens[src_slot])
            last[tgt] = int(snap.last[src_slot])
            if lens[tgt] < len(self._slot_prompt[tgt]):
                # Shed mid-prefill: re-queue the unprefilled tail here
                # (the advance phase finishes it — budgeted or whole).
                self._prefill_pending[tgt] = int(lens[tgt])
        self._lens = jnp.asarray(lens, jnp.int32)
        self._last = jnp.asarray(last, jnp.int32)
        self._pin_host_state()
        self._table_dirty = True
        self._alloc.assert_consistent()
        self._resumed += len(mapping)
        if self._flight is not None:
            self._flight.record(
                "absorb", resumed=len(mapping), pages=need,
                pool_free=self._alloc.free_count,
                wall_ms=round((self._clock.monotonic() - t0) * 1e3, 3))
        if self._tracer is not None:
            self._obs_span("absorb", t0, self._clock.monotonic(),
                           resumed=len(mapping), pages=need)
        return mapping

    # -- fleet-tier inputs (fleet/summary.py reads these) ------------------
    def replica_stats(self) -> Dict[str, object]:
        """Instantaneous load numbers a fleet replica publishes for
        cache-aware routing — cheap host-side reads, no device sync."""
        if self.layout != "paged":
            raise ValueError(
                "replica_stats() requires kv_layout='paged' (the fleet "
                "tier routes on page watermarks)")
        if self.spec:
            # Accept counters mutate under _obs_mu in the dispatch
            # commit loop — pair them from one instant so a stats read
            # racing a step never tears proposed against accepted.
            with self._obs_mu:
                spec_proposed = self._spec_proposed
                spec_accepted = self._spec_accepted
        else:
            spec_proposed = spec_accepted = 0
        return {
            "page_size": self.page_size,
            "pages_total": self._alloc.n_pages - 1,
            "pages_free": self._alloc.free_count,
            # Disaggregated pools: which phase this replica serves
            # ("mixed"/"prefill"/"decode") — the summary publishes it so
            # registry consumers can see the pool topology.
            "role": self.role,
            "n_slots": self.n_slots,
            "active_slots": len(self._slot_req),
            "queued": len(self._queue),
            # Prefill pressure (chunked prefill): tokens admitted but
            # not yet prefilled — the blind spot that let long-prompt
            # floods keep landing on one replica (the router folds a
            # discount on it into its score).
            "prefill_backlog_tokens": self._prefill_backlog(),
            # Island width (1 = single-chip): heterogeneous fleets shed
            # snapshots across replicas of different tp — the summary
            # carries it so operators can see which replicas scale UP
            # vs OUT.
            "tp": self._tp,
            # Per-chip weight residency (Megatron-sliced weights): the
            # capacity axis that tells a scale-UP replica — one that
            # actually fits big weights per chip — from a replicated-
            # weight one at the same tp.
            "weight_device_bytes": int(self._weight_dev_bytes),
            # KV tiering: committed host-tier pages (DRAM + disk) — the
            # upload-capacity context behind the digest's demoted-path
            # tier flags (absent/0 on untiered replicas, PR 9's
            # default-tolerant summary convention).
            "dram_cached_pages": (len(self._tier)
                                  if self._tier is not None else 0),
            # Speculation health (0.0 on non-spec replicas): lifetime
            # proposals-accepted ratio — routers can prefer replicas
            # whose current traffic mix speculates well.
            "spec_accept_rate": (
                round(spec_accepted / spec_proposed, 4)
                if spec_proposed else 0.0),
        }

    def cache_digest(self, top_k: int = 8,
                     max_tokens: int = 512) -> list:
        """Routing digest of the radix prefix cache (the top-K hottest
        cached token-prefix paths — models/prefix_cache.py digest());
        [] when the cache is off."""
        if self.layout != "paged" or self._prefix is None:
            return []
        return self._prefix.digest(top_k, max_tokens)

    def active_slot_ids(self) -> list:
        """Sorted slot ids currently bound to a request — the shed
        candidates the router picks a partial drain from."""
        return sorted(self._slot_req)

    def pages_referenced(self, slots) -> int:
        """Distinct non-null pages the given active slots reference
        (own + mounted shared) — the router's shed-size precheck, so a
        partial drain is only taken when the target verifiably has room
        (an absorb failure after the drain would strand the shed
        requests)."""
        seen: set = set()
        for s in slots:
            seen.update(int(p) for p in self._slot_shared.get(s, ()))
            seen.update(int(p) for p in self._slot_pages.get(s, ()))
        seen.discard(NULL_PAGE)
        return len(seen)

    def handoff_ready_slots(self) -> list:
        """Sorted (slot, local rid) pairs whose PREFILL IS COMPLETE —
        bound to a request and not mid-prefill — i.e. the slots a
        disaggregated router may drain to the decode pool. Mid-prefill
        slots are deliberately absent: handoff is defined at the
        phase boundary (prompt fully resident, first token emitted),
        and migrating earlier would just move the prefill problem to
        the pool sized for decode."""
        if self.layout != "paged":
            return []
        return sorted((s, r) for s, r in self._slot_req.items()
                      if s not in self._prefill_pending)

    def label_request(self, req_id: int, label: Optional[str]) -> None:
        """Re-attach a trace label to a live request — the router calls
        this after absorb() hands a request a FRESH local rid (labels
        are engine-local and deliberately not part of the snapshot wire
        format, so cross-replica migration re-labels host-side)."""
        if label:
            self._rid_label[int(req_id)] = str(label)

    def pool_metrics(self) -> Dict[str, object]:
        """Page-pool health (paged layout only; {} otherwise): total/free/
        in-use/cached/watermark page counts, alloc/free/denied churn, the
        instantaneous utilization, and — with the prefix cache on — the
        reuse counters (hit rates, cached pages, evictions, prefill
        tokens skipped). The fragmentation-and-reuse observability the
        serving entrypoint publishes next to the latency records
        (metrics.exporter.export_serving_pool maps it onto Prometheus
        gauges). With a tracer attached the snapshot also carries
        ``phase_durations`` — a drained-exactly-once batch of
        ``(phase, seconds)`` pairs taken in the SAME lock snapshot as the
        watchdog/spec gauges (export_serving_pool folds it into the
        ``tpu_serve_phase_duration_seconds{phase=}`` histogram)."""
        if self.layout != "paged":
            return {}
        out = self._alloc.metrics()
        # Lifecycle/robustness gauges (metrics.exporter maps these onto
        # tpu_serve_drain_duration_seconds etc.): drain/restore cost, the
        # resumed-request handoff count, per-request isolated failures,
        # and the watchdog age of the last step start — the liveness
        # signal an external probe alerts on when the step loop wedges.
        out["drain_duration_seconds"] = self._drain_s or 0.0
        out["restore_duration_seconds"] = self._restore_s or 0.0
        out["requests_resumed_total"] = float(self._resumed)
        out["requests_shed_total"] = float(self._shed_total)
        out["request_errors_total"] = float(self._request_errors)
        if self._prefix is not None:
            out.update(self._prefix.metrics())
            out["prefill_tokens_skipped"] = float(self._skipped_tokens)
        # Chunked-prefill gauges: backlog is the instantaneous prefill
        # pressure (admitted-but-unfinished prompt tokens, the fleet
        # routing input), chunks_total the cumulative chunk dispatches.
        # Present for every paged engine — 0/0 with chunking off unless
        # a restore/absorb re-queued a peer's mid-prefill slot.
        out["prefill_backlog_tokens"] = float(self._prefill_backlog())
        out["prefill_chunks_total"] = float(self._prefill_chunks_total)
        # Multi-chip islands: tp width and the PER-CHIP pool residency.
        # The value is the engine-build-time constant (shapes/shardings
        # never change after birth), NOT a live-array read: the pool
        # buffers are donated every dispatch, so a scrape thread racing
        # a step would trip "Array has been deleted" on
        # addressable_shards and kill the exporter. Unsharded engines
        # report the whole pool; the sharded-serving bench asserts the
        # 1/tp scaling on this gauge.
        out["tp"] = float(self._tp)
        out["kv_pool_device_bytes"] = float(self._kv_pool_dev_bytes)
        # Megatron-sliced weights: per-chip weight residency (total and
        # the WEIGHT_SPECS-sliced subset — the latter is exactly 1/tp by
        # construction, the sharded_weights bench's CI assertion). Both
        # are engine-build-time constants like kv_pool_device_bytes:
        # the weights are live jit operands and a scrape thread must
        # never touch them. ``tp_combine`` is the info-style combine
        # label (exporter: tpu_serve_tp_combine{kind=} = 1).
        out["weight_device_bytes"] = float(self._weight_dev_bytes)
        out["weight_sliced_device_bytes"] = \
            float(self._weight_sliced_dev_bytes)
        out["tp_combine"] = (self._combine if self._wsharded
                             else ("replicated" if self._tp > 1
                                   else "none"))
        # ONE lock snapshot for everything the step loop mutates: the
        # watchdog age, the spec gauges and the drained phase batch all
        # come from the same instant, so a scrape racing a step can
        # never pair (say) this step's accept counters with last step's
        # age — the torn-read class this lock exists to close. The
        # phase batch drains exactly once (into the returned dict);
        # export_serving_pool folds it into the
        # tpu_serve_phase_duration_seconds{phase=} histogram.
        with self._obs_mu:
            # Age is only a wedge signal while there is work to step: an
            # idle engine (nothing queued, no active slots) legitimately
            # stops stepping, and reporting its quiet time would page the
            # probe on every traffic lull.
            out["last_step_age_seconds"] = (
                max(0.0, self._clock.monotonic() - self._last_step_t)
                if self.pending else 0.0)
            if self.spec:
                # Speculation gauges: accept rate (proposals accepted /
                # proposed — how often prompt-lookup pays), committed
                # tokens per active slot per verify dispatch (the
                # per-slot tok/s multiplier vs the 1.0 of plain decode),
                # and the cumulative overshoot rows rewound by the lens
                # clamp.
                out["spec_accept_rate"] = (
                    self._spec_accepted / self._spec_proposed
                    if self._spec_proposed else 0.0)
                out["spec_tokens_per_dispatch"] = (
                    self._spec_emitted / self._spec_slot_steps
                    if self._spec_slot_steps else 0.0)
                out["spec_rewound_tokens_total"] = float(self._spec_rewound)
                # Which proposal source feeds the verify (exporter:
                # label on the accept-rate histogram) and the adaptive
                # effective-window spread across active slots —
                # min/mean/max of the last dispatched windows
                # (tpu_serve_spec_gamma{slot_agg=}). Non-adaptive
                # engines report the flat gamma on all three.
                out["spec_proposer"] = self._proposer.name
                effs = ([self._spec_eff_last[r]
                         for r in self._slot_req.values()
                         if r in self._spec_eff_last]
                        if self.spec_adaptive else [])
                if not effs:
                    effs = [self.gamma if not self.spec_adaptive
                            else self._spec_overshoot()]
                out["spec_gamma_agg"] = {
                    "min": float(min(effs)),
                    "mean": float(sum(effs) / len(effs)),
                    "max": float(max(effs)),
                }
                # Per-dispatch accept rates, drained exactly once in the
                # same lock snapshot (the phase-batch contract):
                # export_serving_pool folds them into the
                # proposer-labeled tpu_serve_spec_accept histogram.
                if self._spec_accept_buf:
                    out["spec_accept_batch"] = tuple(self._spec_accept_buf)
                    self._spec_accept_buf.clear()
            if self._phase_buf:
                out["phase_durations"] = tuple(self._phase_buf)
                self._phase_buf.clear()
            # Per-admission prefix-hit lengths, drained exactly once in
            # the same lock snapshot (the phase-batch contract):
            # export_serving_pool folds them into the
            # tpu_serve_prefix_hit_tokens histogram.
            if self._hit_tok_buf:
                out["prefix_hit_token_batch"] = tuple(self._hit_tok_buf)
                self._hit_tok_buf.clear()
            # The PROMOTED subset of those hit lengths (pages that paid
            # a tier upload) — same drained-exactly-once lock-snapshot
            # contract; export_serving_pool folds them into the
            # tpu_serve_promoted_hit_tokens histogram. Only tiered
            # engines ever populate the buffer, so untiered exposition
            # is byte-identical.
            if self._promoted_hit_buf:
                out["promoted_hit_token_batch"] = \
                    tuple(self._promoted_hit_buf)
                self._promoted_hit_buf.clear()
        return out

    def _flush(self) -> None:
        """Materialize every outstanding result array in ONE batched
        readback and replay them, in dispatch order, into ``self._out``."""
        if not self._reads:
            return
        # graftcheck: ignore[host-sync] — sanctioned: THE one batched readback (one tunnel round trip per drain, the engine's whole design)
        arrays = jax.device_get([arr for _, arr, _ in self._reads])
        now = self._clock.monotonic()
        for (kind, _, meta), vals in zip(self._reads, arrays):
            if kind == "firsts":
                for req_id, val in zip(meta, vals):  # pad rows fall off
                    if req_id is None:
                        # Intermediate prefill chunk: the sampled row is
                        # scratch — only the FINAL chunk's logits are a
                        # request's first token.
                        continue
                    if not self._out[req_id]:
                        self._first_tok.setdefault(req_id, now)
                    self._out[req_id].append(int(val))
            else:
                for req_id, slot, take in meta:
                    if take and not self._out[req_id]:
                        self._first_tok.setdefault(req_id, now)
                    self._out[req_id].extend(int(t) for t in vals[slot, :take])
        self._reads = []

    def _record_done(self, req_ids, now: Optional[float] = None) -> None:
        """Close the latency record for finished requests (tokens counted
        BEFORE eos truncation — what the engine decoded, which is what its
        throughput cost)."""
        if now is None:
            now = self._clock.monotonic()
        for rid in req_ids:
            arrival = self._arrival.pop(rid, now)
            first = self._first_tok.pop(rid, now)
            self._metrics[rid] = {
                "ttft_s": first - arrival,
                "latency_s": now - arrival,
                "tokens": float(len(self._out.get(rid, ()))),
            }

    def pop_request_metrics(self) -> Dict[int, Dict[str, float]]:
        """Drain per-request latency records accumulated since the last
        call: {req id: {ttft_s, latency_s, tokens}}. The serving entrypoint
        folds these into p50/p99 and publishes them as Observations so the
        scheduler can right-size against MEASURED latency, not just
        predicted QPS."""
        out, self._metrics = self._metrics, {}
        return out

    def _reap_eos(self) -> list:
        """Free slots whose flushed output now contains eos — the request
        is done regardless of remaining budget. Only tokens appended since
        the last reap are scanned (a per-request offset), so a long
        generation costs O(tokens) total, not O(tokens²). Row-space note:
        the freed slot's stale cache rows are exactly the normal-finish
        leftovers; the next admission rewrites its bitmap window over
        them."""
        reaped: list = []
        for slot, req_id in list(self._slot_req.items()):
            out = self._out[req_id]
            seen = self._eos_scanned.get(req_id, 0)
            if self.eos_id in out[seen:]:
                del self._slot_req[slot]
                del self._budget[req_id]
                self._eos_scanned.pop(req_id, None)
                if self.spec:
                    self._proposer.drop(slot)
                    self._spec_ema.pop(req_id, None)
                    self._spec_eff_last.pop(req_id, None)
                    self._spec_reserve.pop(req_id, None)
                t_rp = self._clock.monotonic()
                if self.layout == "paged":
                    # Early stop returns the whole worst-case reservation
                    # — including the never-written tail — immediately;
                    # the reap runs post-flush, so the decoded-suffix
                    # donation covers the whole transcript through eos.
                    self._free_slot_pages(
                        slot, self._donatable_decoded(req_id))
                if self._tracer is not None:
                    self._obs_span("reap", t_rp, self._clock.monotonic(),
                                   rid=req_id, slot=slot, eos=True)
                reaped.append(req_id)
            else:
                self._eos_scanned[req_id] = len(out)
        return reaped

    def _truncate_eos(self, toks: list) -> list:
        if self.eos_id is None:
            return toks
        try:
            return toks[: toks.index(self.eos_id) + 1]
        except ValueError:
            return toks

    def step(self) -> Dict[int, list]:
        """Admit into free slots, decode one chunk, return newly finished
        {req id: decoded tokens}."""
        finished = self._step_lazy()
        self._flush()
        if self.eos_id is not None:
            finished.extend(self._reap_eos())
            for rid in finished:                     # budget-finished leak
                self._eos_scanned.pop(rid, None)
        self._record_done(finished)
        return {rid: self._truncate_eos(self._out.pop(rid))
                for rid in finished}

    def run(self) -> Dict[int, list]:
        """Drain everything submitted; returns {req id: tokens}.

        Without an eos_id, scheduling never depends on token values: all
        chunks dispatch back-to-back asynchronously and the results come
        back in one readback. With eos_id set, completion IS
        content-dependent, so each step flushes before the next admission
        decision (step())."""
        if self.role == "prefill":
            raise RuntimeError(
                "run() on a role='prefill' engine would spin forever: "
                "prefill-pool replicas never dispatch decode, so "
                "requests only complete after a fleet handoff — drive "
                "the engine through Router(pools=...) instead")
        if self.eos_id is not None:
            done: Dict[int, list] = {}
            while self.pending:
                done.update(self.step())
            return done
        finished: list = []
        while self.pending:
            finished.extend(self._step_lazy())
        self._flush()
        self._record_done(finished)
        return {rid: self._out.pop(rid) for rid in finished}
