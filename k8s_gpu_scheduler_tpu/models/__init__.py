"""Workload layer — the JAX models this scheduler places and benches.

The reference schedules opaque inference containers (onnx_* workloads in its
recommender matrices) and ships no models. Our BASELINE configs name real
workloads (resnet/bert/llama), so the framework carries a small TPU-native
model zoo: everything jit-compiled, bf16, static-shaped, sharded via
parallel/ — the flagship (llama) is what __graft_entry__/bench.py drive.
"""
from .llama import (
    LlamaConfig, forward, forward_with_aux, init_params, loss_fn,
    make_train_step,
)
from .bert import BertConfig
from .resnet import ResNetConfig
from .serving import (
    ContinuousBatcher, cached_attention, forward_with_cache, generate,
    generate_speculative,
    init_cache, make_server_step, make_speculative_server_step,
)
from .paging import PageAllocator
from .prefix_cache import PrefixCache
from .pipeline import make_pp_train_step, pp_loss_fn

__all__ = [
    "LlamaConfig",
    "BertConfig",
    "ResNetConfig",
    "init_params",
    "forward",
    "forward_with_aux",
    "loss_fn",
    "make_train_step",
    "cached_attention",
    "forward_with_cache",
    "generate",
    "generate_speculative",
    "init_cache",
    "make_server_step",
    "make_speculative_server_step",
    "ContinuousBatcher",
    "PageAllocator",
    "PrefixCache",
    "make_pp_train_step",
    "pp_loss_fn",
]
