"""Radix prefix cache over the paged KV pool — cross-request KV reuse.

The paged ContinuousBatcher (models/serving.py) already stores K/V in
fixed-size pages addressed through per-slot block tables, which is
exactly the representation block-granular sharing needs (vLLM's
PagedAttention insight): a physical page holding the KV of a token chunk
can back ANY slot whose prompt starts with those tokens. This module is
the host-side index that finds such pages (SGLang's RadixAttention idea,
page-granular): a radix tree keyed on ``page_size``-token chunks of
token ids, each node owning ONE physical page whose KV rows are the
prefill of that chunk **in the context of the path above it** — so a
root-to-node path spells a prompt prefix and the pages along it are its
complete KV.

The contract with the pool (models/paging.py) is reference counting:

- every node's page carries the TREE's reference (``PageAllocator.
  adopt``); a ``match`` winner additionally gains one reference per slot
  that mounts it (``retain``), dropped at reap (``free``).
- cached pages are READ-ONLY by construction: a matched prefix is always
  page-aligned and always leaves at least the prompt's last token to
  prefill, so the slot's own writes (the partial last prompt page, every
  decode row) land in freshly-owned pages — copy-on-write at page
  granularity, with nothing ever actually copied.
- eviction (``evict``) removes only LEAVES whose page has no holder but
  the tree (refcount 1), oldest ``last_used`` first — LRU over complete
  suffixes, so an evicted path can never strand a child whose KV depends
  on it.

Insertion is donation, not copying: when a request is reaped, the pages
covering its FULL conversation chunks — prompt AND (since the
decoded-suffix donation landed in the serving engine) the decoded
tokens whose KV rows are resident — transfer into the tree where the
path does not exist yet (the slot's reference is re-labeled as the
tree's), and duplicate chunks — the hit path it was mounted on, or a
path a concurrent request donated first — stay with the caller to
release. Donating decoded pages is what closes the multi-turn loop:
turn N+1's prompt IS turn N's transcript plus the new user text, so
the whole conversation mounts as a cached prefix and only the novel
turn prefills (``prompt_len`` tells ``insert`` where the decoded
suffix starts, for the donation metrics only — the tree itself is
oblivious to the split).

With KV TIERING (serving ``kv_tiering=True``) eviction stops being
forgetting: the LRU sweep DEMOTES refcount-1 effective leaves instead —
the node stays in the tree tier-flagged (``_Node.demoted`` = host-tier
key, ``page = None``) while its bytes ride the engine's step-boundary
readback queue into host DRAM (``paging.HostTierStore``; disk third
tier behind the same interface). ``match_tiered`` walks straight
through demoted nodes so admission can re-upload ("promote") the parked
pages into freshly-reserved pool pages before the slot's first prefill
dispatch — cache capacity becomes a host-memory knob instead of an HBM
constant, at the cost of an upload the fleet router's scoring discounts
(``digest`` tier-flags the non-resident tail of each path).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .paging import HostTierStore, PageAllocator


class _Node:
    """One cached page: ``chunk`` (page_size token ids) under its parent,
    holding physical page ``page``. The root is a chunk-less sentinel.
    A DEMOTED node (kv_tiering) has ``page is None`` and ``demoted`` set
    to its host-tier key — the chunk's KV bytes live off-pool until a
    match promotes them back into freshly-reserved pool pages."""

    __slots__ = ("chunk", "page", "parent", "children", "last_used",
                 "demoted")

    def __init__(self, chunk: Optional[Tuple[int, ...]], page: Optional[int],
                 parent: Optional["_Node"]) -> None:
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self.demoted: Optional[int] = None


class PrefixCache:
    """Page-granular radix tree of cached prompt prefixes over a
    ref-counted ``PageAllocator``. Purely host-side: it stores token
    chunks and page IDS — the KV bytes never leave the device pool."""

    def __init__(self, allocator: PageAllocator, page_size: int,
                 tier: Optional[HostTierStore] = None) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._alloc = allocator
        self.page_size = page_size
        self._root = _Node(None, None, None)
        self._clock = 0                      # logical LRU time
        self._n_nodes = 0
        # kv_tiering: demoted nodes keep their place in the tree with
        # the KV bytes parked in the host tier; ``_demoted`` maps tier
        # keys back to nodes for promotion / tier-eviction pruning.
        self._tier = tier
        self._demoted: Dict[int, _Node] = {}
        self._n_demoted = 0
        self._promotions = 0                 # pages re-uploaded on a match
        if tier is not None:
            tier.can_evict = self._tier_can_evict
            tier.on_drop = self.drop_demoted
            allocator.attach_tier(tier)
        # Aggregate counters for pool_metrics()/the bench leg.
        self._lookups = 0                    # match() calls
        self._lookup_hits = 0                # match() calls with >= 1 page
        self._lookup_tokens = 0              # prompt tokens seen by match()
        self._hit_tokens = 0                 # tokens covered by matches
        self._inserted_pages = 0             # pages adopted into the tree
        self._decoded_inserted = 0           # ... whose chunk spans decode
        self._evictions = 0                  # pages evicted (LRU)

    def __len__(self) -> int:
        """Number of RESIDENT cached pages (tree nodes holding a pool
        page; demoted nodes count in ``demoted_count``)."""
        return self._n_nodes

    @property
    def demoted_count(self) -> int:
        """Nodes whose KV is parked in the host tier."""
        return self._n_demoted

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        """The FULL page_size-token chunks of ``tokens`` (the trailing
        partial chunk is never cacheable — it shares a page with rows the
        owning request keeps writing)."""
        ps = self.page_size
        return [tuple(int(t) for t in tokens[i:i + ps])
                for i in range(0, (len(tokens) // ps) * ps, ps)]

    def match(self, tokens: Sequence[int],
              count: bool = True) -> List[int]:
        """Longest cached page-aligned prefix of ``tokens``: the page ids
        of the matched path, shallowest first. Capped so at least ONE
        prompt token is left to prefill — the admission needs the
        last-position logits to sample the first output token, so a fully
        cached prompt still re-prefills its final page. Touches the
        matched path's LRU clocks; takes NO references (the caller
        retains what it actually mounts). ``count=False`` suppresses the
        hit/lookup counters for RETRIES of a page-blocked queue head —
        the batcher re-matches it every decode step, and counting each
        retry would let one waiting request swamp the hit rate. On a
        tiered cache this is the RESIDENT-only view (truncated at the
        first demoted node); promotion-aware admission uses
        ``match_tiered``."""
        pages, demoted = self.match_tiered(tokens, count=count)
        if demoted:
            pages = pages[:pages.index(None)]
        return pages

    def match_tiered(self, tokens: Sequence[int], count: bool = True,
                     ) -> Tuple[List[Optional[int]], List[_Node]]:
        """The promotion-aware match: walks through RESIDENT and DEMOTED
        nodes alike and returns ``(path, demoted)`` — ``path`` is the
        matched page ids in path order with ``None`` at demoted
        positions, ``demoted`` the corresponding nodes (shallowest
        first) whose tier payloads admission must re-upload into fresh
        pool pages BEFORE the slot's first prefill dispatch. A node
        whose demotion is still PENDING (bytes not yet drained off-pool)
        is un-demoted in place — the mid-match race where the retain pin
        wins and the copy is cancelled for free. Hit counters cover the
        full path: demoted chunks skip prefill exactly like resident
        ones once promoted."""
        self._clock += 1
        chunks = self._chunks(tokens)
        if chunks and len(chunks) * self.page_size == len(tokens):
            chunks = chunks[:-1]             # leave the last token's page
        node, path, demoted = self._root, [], []
        for chunk in chunks:
            child = node.children.get(chunk)
            if child is None:
                break
            if child.demoted is not None and self._tier is not None \
                    and self._tier.is_pending(child.demoted):
                self._cancel_demotion(child)
            if child.demoted is not None:
                if self._tier is None or not self._tier.has(child.demoted):
                    break                    # dead key: path not promotable
                self._tier.touch(child.demoted)
                demoted.append(child)
                path.append(None)
            else:
                path.append(child.page)
            child.last_used = self._clock
            node = child
        if count:
            self._lookups += 1
            self._lookup_tokens += len(tokens)
            self._hit_tokens += len(path) * self.page_size
            if path:
                self._lookup_hits += 1
        return path, demoted

    def _cancel_demotion(self, node: _Node) -> None:
        """Pending-demotion rollback: the page bytes never left the pool
        (the readback queue had not drained), so the node simply takes
        its page back."""
        key = node.demoted
        node.page = self._tier.cancel(key)
        node.demoted = None
        del self._demoted[key]
        self._n_demoted -= 1
        self._n_nodes += 1

    def insert(self, tokens: Sequence[int],
               pages: Sequence[int],
               prompt_len: Optional[int] = None) -> List[int]:
        """Donate ``pages[i]`` as the cached KV of the i-th full chunk of
        ``tokens`` (the reaped request's block-table prefix, shared hit
        pages included; since the decoded-suffix donation, ``tokens``
        may be the whole conversation — prompt + resident decoded
        suffix). Returns the pages the tree ADOPTED (their reference now
        belongs to the tree); every other page — chunks already cached,
        by this request's own hit path or by a concurrent donor — stays
        with the caller, which must ``free`` its reference as usual.
        ``prompt_len`` marks where the decoded suffix starts: adopted
        pages whose chunk extends past it count into the
        ``decoded_pages_donated_total`` metric (the multi-turn reuse
        signal — None attributes everything to the prompt, the pre-
        decoded-donation accounting). Raises if ``pages`` is shorter
        than the chunk walk it must cover.

        Tiering extensions: a NEGATIVE entry ``-(key + 1)`` denotes a
        chunk whose KV lives in the host tier under ``key`` (the
        snapshot-restore wire form of ``dump_paths``) — the node is
        created demoted, nothing is adopted. Donating a REAL page where
        a demoted node already sits un-demotes it in place: prefill KV
        of a chunk is a deterministic function of its prefix, so the
        donated bytes equal the parked ones and the tier copy is
        dropped."""
        self._clock += 1
        chunks = self._chunks(tokens)
        if len(pages) < len(chunks):
            raise ValueError(
                f"{len(chunks)} full chunks but only {len(pages)} pages")
        node, adopted = self._root, []
        for i, (chunk, page) in enumerate(zip(chunks, pages)):
            page = int(page)
            child = node.children.get(chunk)
            if child is None:
                if page < 0:                 # restore of a demoted chunk
                    key = -page - 1
                    child = _Node(chunk, None, node)
                    child.demoted = key
                    self._demoted[key] = child
                    self._n_demoted += 1
                else:
                    self._alloc.adopt([page])
                    child = _Node(chunk, page, node)
                    self._n_nodes += 1
                    self._inserted_pages += 1
                    if prompt_len is not None \
                            and (i + 1) * self.page_size > prompt_len:
                        self._decoded_inserted += 1
                    adopted.append(page)
                node.children[chunk] = child
            elif child.demoted is not None and page >= 0:
                # Donor offers resident bytes for a demoted chunk
                # (absorb of a shed slot whose prefix demoted here):
                # adopt the donated page and drop the tier copy.
                if self._tier is not None \
                        and self._tier.is_pending(child.demoted):
                    # Pending entry: its pool page would strand — the
                    # cancel returns it to the tree, and the DONATED
                    # duplicate stays with the caller (not adopted).
                    self._cancel_demotion(child)
                else:
                    key = child.demoted
                    self._alloc.adopt([page])
                    child.page = page
                    child.demoted = None
                    del self._demoted[key]
                    if self._tier is not None:
                        self._tier.discard(key)
                    self._n_demoted -= 1
                    self._n_nodes += 1
                    self._inserted_pages += 1
                    adopted.append(page)
            child.last_used = self._clock
            node = child
        return adopted

    def _evictable_leaves(self) -> List[_Node]:
        """Resident refcount-1 nodes with NO resident descendants — the
        'effective leaves' for pool-page eviction. Without tiering no
        demoted nodes exist, so this degenerates to the classic
        childless-leaf rule; with tiering a node whose entire subtree
        has demoted stays evictable (its descendants' bytes are already
        off-pool)."""
        out: List[_Node] = []
        resident_below: Dict[int, int] = {}
        post: List[_Node] = []
        stack = [self._root]
        while stack:                         # iterative post-order
            node = stack.pop()
            post.append(node)
            stack.extend(node.children.values())
        for node in reversed(post):
            below = sum(resident_below[id(c)]
                        for c in node.children.values())
            here = 0 if node.page is None else 1
            resident_below[id(node)] = here + below
            if (node is not self._root and here and below == 0
                    and self._alloc.ref(node.page) == 1):
                out.append(node)
        return out

    def evict(self, n_pages: int) -> int:
        """Release up to ``n_pages`` cached pool pages, least-recently-
        used effective leaf first. Only pages no slot shares (tree
        refcount the sole holder) are candidates; evicting a leaf can
        expose its parent, so the sweep re-collects until satisfied or
        dry. Without a tier this FORGETS (the pages return to the free
        list immediately); with one it DEMOTES — the node stays in the
        tree tier-flagged and its page is enqueued on the readback
        queue, returning to the free list only when the engine drains
        the queue at the step boundary (``take_pending``/``commit``).
        Returns the number of pages released-or-enqueued."""
        freed = 0
        while freed < n_pages:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves:
                if freed >= n_pages:
                    break
                if self._tier is not None:
                    self._demote_leaf(leaf)
                else:
                    del leaf.parent.children[leaf.chunk]
                    self._alloc.drop_cached(leaf.page)
                    self._n_nodes -= 1
                self._evictions += 1
                freed += 1
        return freed

    def _demote_leaf(self, node: _Node) -> None:
        """Demote-instead-of-forget: tier-flag the node and enqueue its
        page for the step-boundary device→host readback. The pool page
        stays allocated+cached (the 'pending' window) until the engine
        gathers its bytes — the pool is donated every dispatch, so the
        copy can only be scheduled from the host at a boundary."""
        key = self._tier.reserve(node.page)
        node.demoted = key
        node.page = None
        self._demoted[key] = node
        self._n_nodes -= 1
        self._n_demoted += 1

    def promote(self, nodes: Sequence[_Node],
                pages: Sequence[int]) -> None:
        """Bookkeeping for a completed promotion: ``pages[i]`` (fresh
        from ``alloc``, refcount 1) now holds the uploaded bytes of
        demoted ``nodes[i]``. The allocation's reference is re-labeled
        as the tree's (``adopt``) — mirroring donation — so the caller
        must still ``retain`` what it mounts. Tier payloads must already
        be popped (the engine needed them for the upload)."""
        for node, page in zip(nodes, pages):
            key = node.demoted
            self._alloc.adopt([page])
            node.page = int(page)
            node.demoted = None
            self._demoted.pop(key, None)
            self._n_demoted -= 1
            self._n_nodes += 1
            self._promotions += 1

    def drop_demoted(self, key: int) -> None:
        """Forget a demoted entry (tier capacity shed, or a refused
        commit): prune its node. Normally the node is childless (the
        tier's ``can_evict`` filter guarantees it for capacity sheds);
        a refused commit can in principle hit a node that acquired
        children since enqueue — then the whole subtree is forgotten,
        since a severed path can never be matched again."""
        node = self._demoted.pop(key, None)
        if node is None:
            return                           # restore-time shed: no node yet
        self._n_demoted -= 1
        if node.parent is not None:
            del node.parent.children[node.chunk]
        stack = list(node.children.values())
        while stack:
            sub = stack.pop()
            stack.extend(sub.children.values())
            if sub.demoted is not None:
                self._demoted.pop(sub.demoted, None)
                if self._tier is not None:
                    if self._tier.is_pending(sub.demoted):
                        page = self._tier.cancel(sub.demoted)
                        self._alloc.drop_cached(page)
                    else:
                        self._tier.discard(sub.demoted)
                self._n_demoted -= 1
            elif sub.page is not None:
                self._alloc.drop_cached(sub.page)
                self._n_nodes -= 1

    def _tier_can_evict(self, key: int) -> bool:
        """Capacity-shed filter: only CHILDLESS demoted leaves may leave
        the tier — dropping a mid-path entry would strand descendants
        the match walk could no longer reach."""
        node = self._demoted.get(key)
        return node is not None and not node.children

    def digest(self, top_k: int = 8,
               max_tokens: int = 512) -> List[Tuple[List[int], int]]:
        """Compact routing digest: the ``top_k`` HOTTEST root-to-leaf
        token paths (most-recent ``last_used`` first) as
        ``(tokens, cached_len)`` pairs, each token list truncated to
        ``max_tokens``. This is what a fleet replica publishes to the
        registry so a cache-aware router (fleet/router.py) can score
        ``prefix_match_len(prompt, digest)`` WITHOUT shipping the whole
        tree: hot shared system prompts are short and few, so a handful
        of truncated paths carries almost all the routing signal.
        ``cached_len`` is the path's full cached token length (it can
        exceed ``len(tokens)`` when truncated) — a match against a
        truncated path scores at most ``max_tokens``, which only
        under-claims, never over-claims, reuse.

        Tiered caches emit ``(tokens, cached_len, resident_len)``
        triples instead: ``resident_len`` is the path's longest
        fully-resident prefix in tokens — the part a match mounts for
        free; the ``cached_len - resident_len`` remainder is promotable
        but pays an upload, which the fleet router discounts
        (fleet/router.py) so a 'warm' replica that would actually pay a
        promotion never outranks a truly-resident one. Untiered caches
        keep the 2-tuple wire form byte-identical to pre-tiering
        summaries."""
        paths = self.dump_paths()                # coldest first
        out: List[Tuple] = []
        for tokens, pages in reversed(paths[-top_k:] if top_k else []):
            cached = len(pages) * self.page_size
            if self._tier is None:
                out.append((tokens[:max_tokens], cached))
            else:
                resident = next(
                    (i for i, p in enumerate(pages) if p < 0), len(pages))
                out.append((tokens[:max_tokens], cached,
                            resident * self.page_size))
        return out

    def dump_paths(self) -> List[Tuple[List[int], List[int]]]:
        """The tree as root-to-LEAF ``(tokens, pages)`` paths, ordered by
        the leaf's LRU clock (coldest first) — the serializable form a
        drain snapshot carries (models/snapshot.py). Every node lies on
        at least one leaf path, so replaying the paths through
        ``insert`` in this order rebuilds the whole tree: shared prefix
        nodes are created by the first (coldest) path that walks them
        and de-duplicated by the later ones, and inserting coldest-first
        reproduces the eviction order at leaf granularity — the
        restored tree evicts the same suffixes first. Demoted nodes
        appear as ``-(tier_key + 1)`` in the pages list (the negative
        wire form ``insert`` accepts back)."""
        leaves: List[_Node] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node is not self._root and not node.children:
                leaves.append(node)
            stack.extend(node.children.values())
        leaves.sort(key=lambda n: n.last_used)
        paths: List[Tuple[List[int], List[int]]] = []
        for leaf in leaves:
            tokens: List[int] = []
            pages: List[int] = []
            node = leaf
            while node is not self._root:
                tokens[:0] = node.chunk
                pages.insert(0, node.page if node.demoted is None
                             else -(node.demoted + 1))
                node = node.parent
            paths.append((tokens, pages))
        return paths

    def metrics(self) -> Dict[str, float]:
        """Prefix-reuse counters for pool_metrics()/the exporter: token
        and request hit rates, cached-page count, adoption/eviction
        churn. ``prefix_hit_rate`` is token-weighted (cached tokens /
        prompt tokens looked up) — the number that predicts prefill FLOPs
        saved; ``prefix_request_hit_rate`` is the fraction of lookups
        that matched at all."""
        out = {
            "prefix_cached_pages": float(self._n_nodes),
            "prefix_lookups": float(self._lookups),
            "prefix_lookup_hits": float(self._lookup_hits),
            "prefix_lookup_tokens": float(self._lookup_tokens),
            "prefix_hit_tokens": float(self._hit_tokens),
            "prefix_hit_rate": (self._hit_tokens / self._lookup_tokens
                                if self._lookup_tokens else 0.0),
            "prefix_request_hit_rate": (self._lookup_hits / self._lookups
                                        if self._lookups else 0.0),
            "prefix_inserted_pages": float(self._inserted_pages),
            "prefix_evictions": float(self._evictions),
            # Decoded-suffix donations (multi-turn reuse): adopted pages
            # whose token chunk extends past the donor's prompt — the
            # pages that let turn N+1 mount turn N's answer.
            "decoded_pages_donated_total": float(self._decoded_inserted),
        }
        if self._tier is not None:
            # Tiering gauges ride only on tiered caches — untiered
            # engines keep the pre-tiering exposition byte-identical.
            out["prefix_demoted_pages"] = float(self._n_demoted)
            out["page_promotions_total"] = float(self._promotions)
        return out
