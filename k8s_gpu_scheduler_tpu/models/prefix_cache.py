"""Radix prefix cache over the paged KV pool — cross-request KV reuse.

The paged ContinuousBatcher (models/serving.py) already stores K/V in
fixed-size pages addressed through per-slot block tables, which is
exactly the representation block-granular sharing needs (vLLM's
PagedAttention insight): a physical page holding the KV of a token chunk
can back ANY slot whose prompt starts with those tokens. This module is
the host-side index that finds such pages (SGLang's RadixAttention idea,
page-granular): a radix tree keyed on ``page_size``-token chunks of
token ids, each node owning ONE physical page whose KV rows are the
prefill of that chunk **in the context of the path above it** — so a
root-to-node path spells a prompt prefix and the pages along it are its
complete KV.

The contract with the pool (models/paging.py) is reference counting:

- every node's page carries the TREE's reference (``PageAllocator.
  adopt``); a ``match`` winner additionally gains one reference per slot
  that mounts it (``retain``), dropped at reap (``free``).
- cached pages are READ-ONLY by construction: a matched prefix is always
  page-aligned and always leaves at least the prompt's last token to
  prefill, so the slot's own writes (the partial last prompt page, every
  decode row) land in freshly-owned pages — copy-on-write at page
  granularity, with nothing ever actually copied.
- eviction (``evict``) removes only LEAVES whose page has no holder but
  the tree (refcount 1), oldest ``last_used`` first — LRU over complete
  suffixes, so an evicted path can never strand a child whose KV depends
  on it.

Insertion is donation, not copying: when a request is reaped, the pages
covering its FULL conversation chunks — prompt AND (since the
decoded-suffix donation landed in the serving engine) the decoded
tokens whose KV rows are resident — transfer into the tree where the
path does not exist yet (the slot's reference is re-labeled as the
tree's), and duplicate chunks — the hit path it was mounted on, or a
path a concurrent request donated first — stay with the caller to
release. Donating decoded pages is what closes the multi-turn loop:
turn N+1's prompt IS turn N's transcript plus the new user text, so
the whole conversation mounts as a cached prefix and only the novel
turn prefills (``prompt_len`` tells ``insert`` where the decoded
suffix starts, for the donation metrics only — the tree itself is
oblivious to the split).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .paging import PageAllocator


class _Node:
    """One cached page: ``chunk`` (page_size token ids) under its parent,
    holding physical page ``page``. The root is a chunk-less sentinel."""

    __slots__ = ("chunk", "page", "parent", "children", "last_used")

    def __init__(self, chunk: Optional[Tuple[int, ...]], page: Optional[int],
                 parent: Optional["_Node"]) -> None:
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0


class PrefixCache:
    """Page-granular radix tree of cached prompt prefixes over a
    ref-counted ``PageAllocator``. Purely host-side: it stores token
    chunks and page IDS — the KV bytes never leave the device pool."""

    def __init__(self, allocator: PageAllocator, page_size: int) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._alloc = allocator
        self.page_size = page_size
        self._root = _Node(None, None, None)
        self._clock = 0                      # logical LRU time
        self._n_nodes = 0
        # Aggregate counters for pool_metrics()/the bench leg.
        self._lookups = 0                    # match() calls
        self._lookup_hits = 0                # match() calls with >= 1 page
        self._lookup_tokens = 0              # prompt tokens seen by match()
        self._hit_tokens = 0                 # tokens covered by matches
        self._inserted_pages = 0             # pages adopted into the tree
        self._decoded_inserted = 0           # ... whose chunk spans decode
        self._evictions = 0                  # pages evicted (LRU)

    def __len__(self) -> int:
        """Number of cached pages (== tree nodes, one page per node)."""
        return self._n_nodes

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        """The FULL page_size-token chunks of ``tokens`` (the trailing
        partial chunk is never cacheable — it shares a page with rows the
        owning request keeps writing)."""
        ps = self.page_size
        return [tuple(int(t) for t in tokens[i:i + ps])
                for i in range(0, (len(tokens) // ps) * ps, ps)]

    def match(self, tokens: Sequence[int],
              count: bool = True) -> List[int]:
        """Longest cached page-aligned prefix of ``tokens``: the page ids
        of the matched path, shallowest first. Capped so at least ONE
        prompt token is left to prefill — the admission needs the
        last-position logits to sample the first output token, so a fully
        cached prompt still re-prefills its final page. Touches the
        matched path's LRU clocks; takes NO references (the caller
        retains what it actually mounts). ``count=False`` suppresses the
        hit/lookup counters for RETRIES of a page-blocked queue head —
        the batcher re-matches it every decode step, and counting each
        retry would let one waiting request swamp the hit rate."""
        self._clock += 1
        chunks = self._chunks(tokens)
        if chunks and len(chunks) * self.page_size == len(tokens):
            chunks = chunks[:-1]             # leave the last token's page
        node, pages = self._root, []
        for chunk in chunks:
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = self._clock
            pages.append(child.page)
            node = child
        if count:
            self._lookups += 1
            self._lookup_tokens += len(tokens)
            self._hit_tokens += len(pages) * self.page_size
            if pages:
                self._lookup_hits += 1
        return pages

    def insert(self, tokens: Sequence[int],
               pages: Sequence[int],
               prompt_len: Optional[int] = None) -> List[int]:
        """Donate ``pages[i]`` as the cached KV of the i-th full chunk of
        ``tokens`` (the reaped request's block-table prefix, shared hit
        pages included; since the decoded-suffix donation, ``tokens``
        may be the whole conversation — prompt + resident decoded
        suffix). Returns the pages the tree ADOPTED (their reference now
        belongs to the tree); every other page — chunks already cached,
        by this request's own hit path or by a concurrent donor — stays
        with the caller, which must ``free`` its reference as usual.
        ``prompt_len`` marks where the decoded suffix starts: adopted
        pages whose chunk extends past it count into the
        ``decoded_pages_donated_total`` metric (the multi-turn reuse
        signal — None attributes everything to the prompt, the pre-
        decoded-donation accounting). Raises if ``pages`` is shorter
        than the chunk walk it must cover."""
        self._clock += 1
        chunks = self._chunks(tokens)
        if len(pages) < len(chunks):
            raise ValueError(
                f"{len(chunks)} full chunks but only {len(pages)} pages")
        node, adopted = self._root, []
        for i, (chunk, page) in enumerate(zip(chunks, pages)):
            child = node.children.get(chunk)
            if child is None:
                self._alloc.adopt([page])
                child = _Node(chunk, int(page), node)
                node.children[chunk] = child
                self._n_nodes += 1
                self._inserted_pages += 1
                if prompt_len is not None \
                        and (i + 1) * self.page_size > prompt_len:
                    self._decoded_inserted += 1
                adopted.append(int(page))
            child.last_used = self._clock
            node = child
        return adopted

    def _evictable_leaves(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node is not self._root and not node.children
                    and self._alloc.ref(node.page) == 1):
                out.append(node)
        return out

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` cached pages, least-recently-used leaf
        first. Only leaves whose page no slot shares (tree refcount the
        sole holder) are candidates; evicting a leaf can expose its
        parent, so the sweep re-collects until satisfied or dry. Returns
        the number of pages actually freed."""
        freed = 0
        while freed < n_pages:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for leaf in leaves:
                if freed >= n_pages:
                    break
                del leaf.parent.children[leaf.chunk]
                self._alloc.drop_cached(leaf.page)
                self._n_nodes -= 1
                self._evictions += 1
                freed += 1
        return freed

    def digest(self, top_k: int = 8,
               max_tokens: int = 512) -> List[Tuple[List[int], int]]:
        """Compact routing digest: the ``top_k`` HOTTEST root-to-leaf
        token paths (most-recent ``last_used`` first) as
        ``(tokens, cached_len)`` pairs, each token list truncated to
        ``max_tokens``. This is what a fleet replica publishes to the
        registry so a cache-aware router (fleet/router.py) can score
        ``prefix_match_len(prompt, digest)`` WITHOUT shipping the whole
        tree: hot shared system prompts are short and few, so a handful
        of truncated paths carries almost all the routing signal.
        ``cached_len`` is the path's full cached token length (it can
        exceed ``len(tokens)`` when truncated) — a match against a
        truncated path scores at most ``max_tokens``, which only
        under-claims, never over-claims, reuse."""
        paths = self.dump_paths()                # coldest first
        out: List[Tuple[List[int], int]] = []
        for tokens, pages in reversed(paths[-top_k:] if top_k else []):
            out.append((tokens[:max_tokens], len(pages) * self.page_size))
        return out

    def dump_paths(self) -> List[Tuple[List[int], List[int]]]:
        """The tree as root-to-LEAF ``(tokens, pages)`` paths, ordered by
        the leaf's LRU clock (coldest first) — the serializable form a
        drain snapshot carries (models/snapshot.py). Every node lies on
        at least one leaf path, so replaying the paths through
        ``insert`` in this order rebuilds the whole tree: shared prefix
        nodes are created by the first (coldest) path that walks them
        and de-duplicated by the later ones, and inserting coldest-first
        reproduces the eviction order at leaf granularity — the
        restored tree evicts the same suffixes first."""
        leaves: List[_Node] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node is not self._root and not node.children:
                leaves.append(node)
            stack.extend(node.children.values())
        leaves.sort(key=lambda n: n.last_used)
        paths: List[Tuple[List[int], List[int]]] = []
        for leaf in leaves:
            tokens: List[int] = []
            pages: List[int] = []
            node = leaf
            while node is not self._root:
                tokens[:0] = node.chunk
                pages.insert(0, node.page)
                node = node.parent
            paths.append((tokens, pages))
        return paths

    def metrics(self) -> Dict[str, float]:
        """Prefix-reuse counters for pool_metrics()/the exporter: token
        and request hit rates, cached-page count, adoption/eviction
        churn. ``prefix_hit_rate`` is token-weighted (cached tokens /
        prompt tokens looked up) — the number that predicts prefill FLOPs
        saved; ``prefix_request_hit_rate`` is the fraction of lookups
        that matched at all."""
        return {
            "prefix_cached_pages": float(self._n_nodes),
            "prefix_lookups": float(self._lookups),
            "prefix_lookup_hits": float(self._lookup_hits),
            "prefix_lookup_tokens": float(self._lookup_tokens),
            "prefix_hit_tokens": float(self._hit_tokens),
            "prefix_hit_rate": (self._hit_tokens / self._lookup_tokens
                                if self._lookup_tokens else 0.0),
            "prefix_request_hit_rate": (self._lookup_hits / self._lookups
                                        if self._lookups else 0.0),
            "prefix_inserted_pages": float(self._inserted_pages),
            "prefix_evictions": float(self._evictions),
            # Decoded-suffix donations (multi-turn reuse): adopted pages
            # whose token chunk extends past the donor's prompt — the
            # pages that let turn N+1 mount turn N's answer.
            "decoded_pages_donated_total": float(self._decoded_inserted),
        }
